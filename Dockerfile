# tpudfs service image (reference: the multi-stage rust builder Dockerfile).
# One image serves every role — master, config server, chunkserver, S3
# gateway — selected by the container command (python -m tpudfs.<role>).
FROM python:3.12-slim AS build

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY native/ native/
RUN make -C native

FROM python:3.12-slim

RUN pip install --no-cache-dir \
        grpcio msgpack numpy aiohttp cryptography

WORKDIR /app
COPY tpudfs/ tpudfs/
COPY scripts/ scripts/
COPY deploy/ deploy/
COPY --from=build /app/native/libtpudfs_native.so native/libtpudfs_native.so

ENV PYTHONPATH=/app \
    TPUDFS_NATIVE_LIB=/app/native/libtpudfs_native.so

# Roles (override `command`):
#   python -m tpudfs.configserver --port 50050 --data-dir /data/cfg
#   python -m tpudfs.master       --port 50051 --data-dir /data/raft ...
#   python -m tpudfs.chunkserver  --port 50100 --data-dir /data/blocks ...
#   python -m tpudfs.s3           (env-configured)
CMD ["python", "-m", "tpudfs.master", "--help"]
