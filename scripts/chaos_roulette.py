#!/usr/bin/env python3
"""Randomized live chaos: each round boots a FRESH two-shard cluster and
injects a RANDOM fault plan (chunkserver SIGKILLs, master SIGKILLs,
TCP-proxy partitions at random times and durations) under a concurrent
workload, then verifies exactly like the fixed-schedule tier
(scripts/chaos_live.py): WGL-linearizable history, payload md5 intact
through a fresh client, both shards still writable.

Beyond the kill/partition core, each round randomly composes extra
AXES (round-5 expansion — the round-4 plans centered on kills):

- ``ec``: an RS(3,2) erasure-coded payload written up front and
  md5-verified at the end — EC shard fan-out and degraded decode ride
  the same kills/partitions (2 losses are within RS(3,2) tolerance,
  matching the cs-kill cap).
- ``torn``: a large multi-block write CANCELLED mid-faults at a random
  time, then the same path definitively overwritten post-faults — the
  readback must be exactly the final payload (write-session fencing: a
  stray block or late CompleteFile from the dead session must never
  surface).
- ``tiering``: the cluster boots with 1-2 s cold/EC thresholds
  (COLD_THRESHOLD_SECS / EC_THRESHOLD_SECS / EC_SHAPE env), so the
  tiering scanner converts the replicated payload to RS(3,2) DURING the
  fault window; the md5 must hold whether or not conversion completed
  (the conversion state is printed per round).
- ``overload``: one chunkserver the plan will NOT kill is bandwidth-
  shaped (256 KiB/s + 0.3 s/chunk) for the whole fault window while a
  deadline-budgeted client (op_budget, short rpc_timeout, eager hedges)
  reads the payload through it. Every such read must stay inside
  budget + grace — bounded failure is acceptable under combined faults,
  hanging is not — retry volume must stay within the 2x retry budget,
  and the read must succeed after the shaping lifts.
- ``ckpt``: a 2-shard CheckpointManager (tpudfs/tpu/checkpoint.py,
  hot 3x + RS(2,1)) saves sequential steps THROUGH the fault window —
  interrupted saves are expected and logged, never fatal. Post-faults:
  the last interrupted step is RESUMED to completion (idempotent
  content-ETag skips), every step the namespace lists as published
  restores BIT-EXACT against its regenerated canonical tree
  (tpudfs/testing/ckptchaos.py), and no torn step is ever listed.
  RS(2,1) rather than (3,2) on purpose: killed chunkservers stay dead
  for the round, and the post-fault resume must still be able to place
  k+m EC shards on the 3 guaranteed survivors.
- ``stream``: a dedicated 4 MiB-block client (every block rides the
  sub-block WriteStream frame pipeline, docs/write-pipeline.md) writes
  a sequence of 12 MiB files THROUGH the fault window, and the axis
  SIGKILLs one extra chain chunkserver while a streamed write is
  verifiably in flight (within the 2-CS kill cap). Post-faults: every
  acked file reads back md5-exact, and any UN-acked path that is
  visible at all must also read back exact — a torn or partially
  committed streamed block surfacing is the bug this axis hunts.
- ``tenant``: the cluster boots with per-tenant QoS on (TPUDFS_QOS=1:
  weighted-fair queueing + a per-tenant rate), and a 16-way "abuser"
  flood runs through the whole fault window while a budgeted "fair"
  tenant keeps reading the payload. Every fair read must stay inside
  budget + grace (bounded failure under combined faults is acceptable,
  hanging or starving is not), and post-faults BOTH tenants must read
  the payload back byte-exact — the abuser's throttling must never
  become a permanent penalty.

Safety caps keep every plan survivable by design, so any failure is a
REAL bug, not an over-killed cluster: at most 2 of the 5 chunkservers
die (replication 3 leaves >= 1 live replica of everything; RS(3,2)
loses at most 2 shards), at most one master per 3-member Raft group
dies (quorum holds), partitions always heal.

  python scripts/chaos_roulette.py [rounds] [--tls] [--seed N]
                                   [--topology path.json]

The fixed schedule found two real bugs in round 3 (cross-shard fencing,
torn write) and this roulette caught the stale-dead-leader-hint client
bug in round 4; the new axes widen the interleavings it explores.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import pathlib
import random
import signal
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

PAYLOAD_BLOCKS = 16  # x 256 KiB = 4 MiB multi-block payload
WORKLOAD_CLIENTS = 3
WORKLOAD_OPS = 50


from tpudfs.testing.livecluster import (  # noqa: E402
    find_leader, find_leader_async,
)


def make_plan(rng: random.Random, eps: dict) -> list[tuple]:
    """A random, survivable fault plan: [(delay_s, kind, params), ...]."""
    shards = eps["shards"]
    cs_names = sorted(n for n in eps["procs"] if n.startswith("cs"))
    plan: list[tuple] = []
    cs_kills = 0
    killed_master_shards: set[str] = set()
    # CHAOS_PLAN=masters: control-plane-only faults (no CS kills) — the
    # tiering/EC-conversion window needs all k+m chunkservers live, so a
    # targeted hunt must not starve it (seed-7803 chase).
    masters_only = os.environ.get("CHAOS_PLAN") == "masters"
    t = rng.uniform(1.0, 3.0)
    for _ in range(rng.randint(2, 4)):
        choices = ["partition"]
        if cs_kills < 2 and not masters_only:
            choices.append("kill_cs")
        if len(killed_master_shards) < len(shards):
            choices.append("kill_master")
        kind = rng.choice(choices)
        if kind == "kill_cs":
            victim = rng.choice(cs_names)
            cs_names.remove(victim)
            cs_kills += 1
            plan.append((t, "kill_cs", victim))
        elif kind == "kill_master":
            sid = rng.choice(
                [s for s in shards if s not in killed_master_shards])
            killed_master_shards.add(sid)
            # Leader or follower, decided at injection time.
            plan.append((t, "kill_master", (sid, rng.random() < 0.7)))
        else:
            sid = rng.choice(sorted(shards))
            dur = rng.uniform(1.5, 4.0)
            plan.append((t, "partition", (sid, dur)))
        t += rng.uniform(1.0, 3.0)
    return plan


def make_axes(rng: random.Random) -> dict:
    """Per-round extra fault axes (decided before boot: tiering needs
    master env). CHAOS_FORCE_AXES=a,b pins axes on for targeted hunts
    (e.g. the seed-7803 tiering-window chase)."""
    forced = set(filter(None, os.environ.get(
        "CHAOS_FORCE_AXES", "").split(",")))
    return {
        "ec": "ec" in forced or rng.random() < 0.5,
        "torn": "torn" in forced or rng.random() < 0.5,
        "tiering": "tiering" in forced or rng.random() < 0.4,
        "overload": "overload" in forced or rng.random() < 0.4,
        "ckpt": "ckpt" in forced or rng.random() < 0.35,
        "tenant": "tenant" in forced or rng.random() < 0.35,
        "stream": "stream" in forced or rng.random() < 0.4,
    }


async def run_round(eps: dict, rng: random.Random, rnd: int,
                    axes: dict | None = None,
                    linearize: bool = False) -> None:
    from tpudfs.client.checker import check_linearizability
    from tpudfs.client.client import Client, DfsError
    from tpudfs.client.workload import (
        WorkloadConfig, dump_history, run_workload,
    )
    from tpudfs.testing.certs import tls_from_endpoints
    from tpudfs.testing.netem import FaultProxy

    tls, _ = tls_from_endpoints(eps)
    shards = eps["shards"]
    masters = [a for sid in sorted(shards) for a in shards[sid]]
    procs = eps["procs"]
    addr_to_name = {v["addr"]: k for k, v in procs.items() if v["addr"]}

    client = Client(masters, config_addrs=[eps["config_server"]],
                    block_size=256 * 1024, rpc_timeout=10.0, tls=tls)
    deadline = time.time() + 90
    while True:
        try:
            await client.create_file("/a/probe", b"x")
            await client.delete_file("/a/probe")
            break
        except Exception:
            if time.time() > deadline:
                raise
            await asyncio.sleep(0.5)

    payload = os.urandom(PAYLOAD_BLOCKS * 256 * 1024)
    await client.create_file("/a/roulette-payload", payload)
    payload_md5 = hashlib.md5(payload).hexdigest()

    axes = axes or {}
    ec_md5 = None
    if axes.get("ec"):
        ec_payload = os.urandom(6 * 256 * 1024)
        await client.create_file("/a/roulette-ec", ec_payload, ec=(3, 2))
        ec_md5 = hashlib.md5(ec_payload).hexdigest()

    plan = make_plan(rng, eps)
    print(f"round {rnd}: axes = "
          + (",".join(k for k, v in sorted(axes.items()) if v) or "none")
          + "; plan = "
          + "; ".join(f"+{d:.1f}s {k} {p}" for d, k, p in plan))

    # Partitions interpose proxies per shard leader via host aliases —
    # resolved at round start so the workload client routes through them.
    proxies: dict[str, FaultProxy] = {}
    aliases: dict[str, str] = {}
    part_shards = {p[0] for _, k, p in plan if k == "partition"}
    leaders = {sid: find_leader(shards[sid]) for sid in sorted(shards)}
    for sid in part_shards:
        host, port = leaders[sid].rsplit(":", 1)
        proxy = FaultProxy(host, int(port))
        aliases[leaders[sid]] = await proxy.start()
        proxies[sid] = proxy

    # Overload axis: shape a chunkserver the plan leaves alive, so the
    # budgeted reads exercise hedging-around-a-slow-replica rather than
    # plain failover around a dead one.
    ov_proxy = ov_client = None
    ov_walls: list[float] = []
    ov_budget_grace = 8.0 + 1.0
    if axes.get("overload"):
        killed = {p for _, k, p in plan if k == "kill_cs"}
        live_cs = sorted(n for n in procs
                         if n.startswith("cs") and n not in killed)
        slow = rng.choice(live_cs)
        slow_addr = procs[slow]["addr"]
        sh, sp = slow_addr.rsplit(":", 1)
        ov_proxy = FaultProxy(sh, int(sp))
        ov_alias = await ov_proxy.start()
        ov_proxy.set_latency(0.3)
        ov_proxy.set_bandwidth(256 * 1024)
        ov_client = Client(masters, config_addrs=[eps["config_server"]],
                           block_size=256 * 1024, op_budget=8.0,
                           rpc_timeout=0.5, hedge_delay=0.15,
                           host_aliases={slow_addr: ov_alias}, tls=tls)
        print(f"  overload axis: shaping {slow} ({slow_addr}) to "
              f"256 KiB/s (+0.3 s/chunk)")

    # Ckpt axis: sequential sharded saves THROUGH the fault window on a
    # dedicated client; which steps publish (and which get torn) depends
    # on where the kills land.
    ck_client = ck_mgr = None
    ck_published: set[int] = set()
    ck_attempted = 0
    if axes.get("ckpt"):
        from tpudfs.tpu.checkpoint import CheckpointManager
        ck_client = Client(masters, config_addrs=[eps["config_server"]],
                           block_size=256 * 1024, rpc_timeout=3.0,
                           max_retries=8, tls=tls)
        ck_mgr = CheckpointManager(ck_client, "/a/roulette-ckpt",
                                   num_shards=2, ec=(2, 1))

    # Tenant axis: QoS is live on the cluster (one_cluster_round exported
    # TPUDFS_QOS=1), so a named-tenant flood and a budgeted fair tenant
    # contend for admission through the whole fault window.
    tn_fair = tn_abuser = None
    tn_fair_walls: list[float] = []
    tn_fair_errors: list = []
    tn_abuser_shed = 0
    tn_budget_grace = 6.0 + 1.0
    tn_stop = asyncio.Event()
    if axes.get("tenant"):
        # The tenant axis exercises the native engine's admission ladder;
        # a chunkserver that silently fell back to the asyncio blockport
        # fails the round before any fault fires.
        from tpudfs.testing.livecluster import assert_native_data_planes
        await assert_native_data_planes(procs, tls, "tenant axis")
        # local_reads=False: everything is on 127.0.0.1 and the local-read
        # short circuit would bypass server admission entirely.
        tn_fair = Client(masters, config_addrs=[eps["config_server"]],
                         block_size=256 * 1024, op_budget=6.0,
                         rpc_timeout=1.0, initial_backoff=0.05, tls=tls,
                         tenant="fair", local_reads=False)
        tn_abuser = Client(masters, config_addrs=[eps["config_server"]],
                           block_size=256 * 1024, op_budget=6.0,
                           rpc_timeout=1.0, initial_backoff=0.05, tls=tls,
                           tenant="abuser", local_reads=False)
        print("  tenant axis: budgeted fair reader vs 16-way abuser flood")

    # Stream axis: a 4 MiB-block client (every block >= MIN_STREAM_BYTES
    # rides the WriteStream frame pipeline) writes files through the
    # fault window, and ONE extra chunkserver — inside the 2-kill safety
    # cap — is SIGKILLed only once a streamed write is verifiably in
    # flight, so the kill lands mid-chain, mid-stream.
    st_client = None
    st_md5 = None
    st_results: list[tuple[str, bool]] = []
    st_inflight = asyncio.Event()
    st_victim = None
    if axes.get("stream"):
        st_client = Client(masters, config_addrs=[eps["config_server"]],
                           block_size=4 * 1024 * 1024, rpc_timeout=3.0,
                           max_retries=8, tls=tls)
        st_payload = os.urandom(12 * 1024 * 1024)
        st_md5 = hashlib.md5(st_payload).hexdigest()
        plan_killed = {p for _, k, p in plan if k == "kill_cs"}
        spare = sorted(n for n in procs
                       if n.startswith("cs") and n not in plan_killed)
        if len(plan_killed) < 2 and spare:
            st_victim = rng.choice(spare)
            print(f"  stream axis: will SIGKILL {st_victim} "
                  f"({procs[st_victim]['addr']}) mid-streamed-write")
        else:
            print("  stream axis: kill cap reached by the plan; riding "
                  "the plan's own CS kills")

    wl_client = Client(masters, config_addrs=[eps["config_server"]],
                       rpc_timeout=3.0, max_retries=8,
                       host_aliases=aliases, tls=tls)
    cfg = WorkloadConfig(clients=WORKLOAD_CLIENTS,
                         ops_per_client=WORKLOAD_OPS, keys=9,
                         seed=rng.randrange(1 << 30), rename_pod_size=3)
    workload = asyncio.create_task(run_workload(wl_client, cfg))

    torn_task: asyncio.Task | None = None
    torn_cancel_at = None
    if axes.get("torn"):
        # 16 MiB / 64 blocks and an early cancel point: an 8 MiB session
        # often FINISHED before a 0.5-5 s cancel (seeds 5002/5100 logged
        # DEGENERATE), so the axis rarely exercised mid-session death.
        big = os.urandom(64 * 256 * 1024)
        torn_task = asyncio.create_task(
            wl_client.create_file("/a/roulette-torn", big, overwrite=True))
        torn_task.add_done_callback(
            lambda t: None if t.cancelled() else t.exception())
        torn_cancel_at = rng.uniform(0.15, 2.0)

    async def injector() -> None:
        # Plan offsets are absolute from round start.
        t0 = time.monotonic()
        for offset, kind, params in plan:
            wait = offset - (time.monotonic() - t0)
            if wait > 0:
                await asyncio.sleep(wait)
            if kind == "kill_cs":
                os.kill(procs[params]["pid"], signal.SIGKILL)
                print(f"  +{offset:.1f}s SIGKILL {params} "
                      f"({procs[params]['addr']})")
            elif kind == "kill_master":
                sid, want_leader = params
                if want_leader:
                    # Loop-friendly discovery; a still-running election is
                    # not a bug — skip the action instead of aborting.
                    addr = await find_leader_async(shards[sid])
                    if addr is None:
                        print(f"  +{offset:.1f}s kill_master {sid} skipped "
                              f"(no leader during election)")
                        continue
                else:
                    addr = next(a for a in shards[sid]
                                if a != leaders[sid])
                name = addr_to_name.get(addr)
                if name:
                    os.kill(procs[name]["pid"], signal.SIGKILL)
                    print(f"  +{offset:.1f}s SIGKILL master {name} "
                          f"({addr}, leader={want_leader})")
            else:
                sid, dur = params
                proxy = proxies.get(sid)
                if proxy:
                    proxy.partition()
                    print(f"  +{offset:.1f}s partition {sid} "
                          f"for {dur:.1f}s")
                    await asyncio.sleep(dur)
                    proxy.heal()
                    print(f"  +{offset + dur:.1f}s healed {sid}")

    torn_cancelled = False

    async def torn_killer() -> None:
        nonlocal torn_cancelled
        if torn_task is None:
            return
        await asyncio.sleep(torn_cancel_at)
        if not torn_task.done():
            torn_task.cancel()
            torn_cancelled = True
            print(f"  +{torn_cancel_at:.1f}s cancelled torn write "
                  f"mid-session")

    async def checkpointer() -> None:
        nonlocal ck_attempted
        if ck_mgr is None:
            return
        from tpudfs.common.resilience import BudgetExhausted
        from tpudfs.testing.ckptchaos import ckpt_tree
        for step in range(1, 5):
            ck_attempted = step
            trees = {s: ckpt_tree(step, s) for s in range(2)}
            try:
                await ck_mgr.save(step, trees)
                ck_published.add(step)
                print(f"  ckpt axis: step {step} published under faults")
            except (DfsError, BudgetExhausted, asyncio.TimeoutError,
                    OSError) as e:
                # An interrupted save is the point of the axis; whether
                # the commit actually landed is decided post-faults from
                # what the namespace LISTS, not from this exception.
                print(f"  ckpt axis: step {step} save interrupted "
                      f"({type(e).__name__})")
            await asyncio.sleep(rng.uniform(0.2, 0.8))

    async def stream_writer() -> None:
        if st_client is None:
            return
        for i in range(10):
            path = f"/a/roulette-stream-{i}"
            st_inflight.set()
            try:
                await st_client.create_file(path, st_payload)
                st_results.append((path, True))
            except DfsError:
                # Clean bounded failure under faults is acceptable; the
                # post-fault sweep decides whether anything torn became
                # visible.
                st_results.append((path, False))
            await asyncio.sleep(rng.uniform(0.05, 0.2))

    async def stream_killer() -> None:
        if st_victim is None:
            return
        await st_inflight.wait()
        await asyncio.sleep(rng.uniform(0.1, 0.6))
        os.kill(procs[st_victim]["pid"], signal.SIGKILL)
        print(f"  stream axis: SIGKILL {st_victim} "
              f"({procs[st_victim]['addr']}) during streamed writes")

    async def overloaded_reader() -> None:
        if ov_client is None:
            return
        for _ in range(3):
            t0 = time.monotonic()
            try:
                back = await ov_client.get_file("/a/roulette-payload")
                assert hashlib.md5(back).hexdigest() == payload_md5, \
                    f"overloaded read corrupt (round {rnd}); plan: {plan}"
            except DfsError:
                # Bounded failure under overload + concurrent kills and
                # partitions is the contract working; a hang would blow
                # the wall-clock assert below.
                pass
            ov_walls.append(time.monotonic() - t0)
            await asyncio.sleep(0.5)

    async def tenant_flood() -> None:
        if tn_abuser is None:
            return

        async def one() -> None:
            nonlocal tn_abuser_shed
            try:
                await tn_abuser.get_file("/a/roulette-payload")
            except DfsError as e:
                if "Overloaded" in str(e):
                    tn_abuser_shed += 1

        while not tn_stop.is_set():
            await asyncio.gather(*(one() for _ in range(16)))

    async def tenant_fair_reader() -> None:
        if tn_fair is None:
            return
        try:
            for _ in range(6):
                t0 = time.monotonic()
                try:
                    back = await tn_fair.get_file("/a/roulette-payload")
                    assert hashlib.md5(back).hexdigest() == payload_md5, (
                        f"tenant axis: fair read corrupt (round {rnd}); "
                        f"plan: {plan}")
                except DfsError as e:
                    # Bounded failure under flood + kills + partitions is
                    # acceptable; the wall-clock assert below catches hangs.
                    tn_fair_errors.append(e)
                tn_fair_walls.append(time.monotonic() - t0)
                await asyncio.sleep(0.4)
        finally:
            tn_stop.set()  # always release the flood loop

    await asyncio.gather(workload, injector(), torn_killer(),
                         overloaded_reader(), checkpointer(),
                         tenant_flood(), tenant_fair_reader(),
                         stream_writer(), stream_killer())
    entries = workload.result()
    ok_ops = sum(1 for e in entries if e.get("return_ts") is not None)
    print(f"  workload: {len(entries)} ops ({ok_ops} returned)")

    hist_path = tempfile.mkstemp(suffix=".jsonl")[1]
    dump_history(entries, hist_path)
    result = check_linearizability(entries, max_states=2_000_000)
    if not result.linearizable and not result.exhausted:
        raise SystemExit(
            f"LINEARIZABILITY VIOLATION (round {rnd}): {result.message}\n"
            f"history: {hist_path}\nplan: {plan}")
    print(f"  history {'linearizable' if result.linearizable else 'UNKNOWN'}"
          f" ({hist_path})")

    v_client = Client(masters, config_addrs=[eps["config_server"]],
                      rpc_timeout=10.0, tls=tls)
    # Availability-settling discipline, shared by every verification:
    # random plans can kill a leader seconds before verification, and an
    # election is not a bug — AVAILABILITY errors (IndeterminateError:
    # retry-budget exhaustion) retry under a 45 s deadline. CONSISTENCY
    # stays strict: anything else — NOT_FOUND on acked data, checksum
    # errors — fails immediately, and whatever succeeds must be
    # byte-identical.
    from tpudfs.client.client import IndeterminateError

    async def settle(what: str, op):
        deadline = time.time() + 45
        while True:
            try:
                return await op()
            except IndeterminateError as e:
                if time.time() > deadline:
                    raise SystemExit(
                        f"{what} failed 45s after faults (round {rnd}): "
                        f"{e}; plan: {plan}")
                await asyncio.sleep(1.0)
            except DfsError as e:
                # A DETERMINATE failure is a consistency-bug candidate —
                # but classify it first: retry ONCE after a pause and
                # dump the metadata, so a recurrence (seed 7803's
                # tiering-window EC decode failure) tells us whether the
                # state was transient or persistent before failing.
                meta = None
                try:
                    meta = await v_client.get_file_info(
                        "/a/roulette-payload")
                except Exception:
                    pass
                print(f"  {what}: DETERMINATE failure: {e}\n"
                      f"  meta at failure: {meta}")
                await asyncio.sleep(2.0)
                try:
                    out = await op()
                    print(f"  {what}: SUCCEEDED on the post-failure "
                          f"retry — transient window, still a bug")
                    raise SystemExit(
                        f"{what} transiently failed then healed "
                        f"(round {rnd}): {e}; plan: {plan}")
                except DfsError as e2:
                    raise SystemExit(
                        f"{what} PERSISTENTLY failed (round {rnd}): "
                        f"first {e}; retry {e2}; plan: {plan}")

    back = await settle("payload read",
                        lambda: v_client.get_file("/a/roulette-payload"))
    assert hashlib.md5(back).hexdigest() == payload_md5, \
        f"payload md5 mismatch (round {rnd}); plan: {plan}"
    if axes.get("tiering"):
        meta = await v_client.get_file_info("/a/roulette-payload")
        converted = all(b.get("ec_data_shards") for b in meta["blocks"])
        print(f"  tiering axis: payload md5 held; EC conversion "
              f"{'completed' if converted else 'still replicated'} "
              f"under faults")
    if ec_md5 is not None:
        ec_back = await settle("EC payload read",
                               lambda: v_client.get_file("/a/roulette-ec"))
        assert hashlib.md5(ec_back).hexdigest() == ec_md5, \
            f"EC payload md5 mismatch (round {rnd}); plan: {plan}"
        print("  ec axis: RS(3,2) payload md5 held (degraded decode "
              "within the kill cap)")
    if axes.get("torn"):
        # The dead session must never surface: the definitive overwrite
        # wins, byte-exactly.
        final = os.urandom(3 * 256 * 1024)

        async def overwrite_and_read():
            await v_client.create_file("/a/roulette-torn", final,
                                       overwrite=True)
            return await v_client.get_file("/a/roulette-torn")

        torn_back = await settle("torn-path overwrite",
                                 overwrite_and_read)
        assert torn_back == final, \
            (f"torn axis: final overwrite did not win byte-exactly "
             f"(round {rnd}); plan: {plan}")
        if torn_cancelled:
            print("  torn axis: cancelled session never surfaced; final "
                  "overwrite read back byte-exact")
        else:
            # The 8 MiB write completed/failed before the cancel point —
            # no mid-session cancellation happened; say so instead of
            # claiming coverage the seed never exercised.
            print("  torn axis DEGENERATE (write finished before the "
                  "cancel); overwrite still byte-exact")
    if ov_client is not None:
        assert ov_walls and max(ov_walls) <= ov_budget_grace, (
            f"overload axis: read blew its deadline budget "
            f"(walls {['%.2f' % w for w in ov_walls]}, round {rnd}); "
            f"plan: {plan}")
        orc = ov_client.retry_budget.counters()
        assert orc["retry_budget_retries_total"] \
            <= 2 * orc["retry_budget_first_tries_total"], \
            f"overload axis: retry amplification > 2x: {orc}"
        ov_proxy.set_latency(0.0)
        ov_proxy.set_bandwidth(0)
        healed = await settle(
            "overload healed read",
            lambda: ov_client.get_file("/a/roulette-payload"))
        assert hashlib.md5(healed).hexdigest() == payload_md5, \
            f"overload axis: healed read corrupt (round {rnd})"
        print(f"  overload axis: walls "
              f"{['%.2f' % w for w in ov_walls]} <= {ov_budget_grace}s, "
              f"retries {orc}, healed read ok")
    if ck_mgr is not None:
        from tpudfs.testing.ckptchaos import (
            assert_restores_bit_exact, ckpt_tree,
        )
        listed = await settle("ckpt list", ck_mgr.list_steps)
        # The save-loop's view is a lower bound: a commit whose ack was
        # lost to a kill still published. The namespace is authoritative.
        assert ck_published <= set(listed), (
            f"ckpt axis: acked steps {sorted(ck_published)} missing from "
            f"listed {listed} (round {rnd}); plan: {plan}")
        resume = ck_attempted if ck_attempted > max(listed, default=0) else 0
        if resume:
            # Finish the interrupted save: idempotent re-puts skip every
            # shard that already landed (content ETag), then publish.
            trees = {s: ckpt_tree(resume, s) for s in range(2)}
            await settle(f"ckpt resume step {resume}",
                         lambda: ck_mgr.save(resume, trees))
            listed = await settle("ckpt relist", ck_mgr.list_steps)
            assert resume in listed, (
                f"ckpt axis: resumed step {resume} not listed "
                f"(round {rnd}); plan: {plan}")
        assert listed, (
            f"ckpt axis: no step published or resumable (round {rnd}); "
            f"plan: {plan}")
        # EVERY step the namespace lists must restore bit-exact — a torn
        # checkpoint that is visible at all is the bug this axis hunts.
        for s in listed:
            trees = await settle(f"ckpt restore step {s}",
                                 lambda s=s: ck_mgr.restore(s))
            assert_restores_bit_exact(trees, s)
        print(f"  ckpt axis: steps {listed} all restore bit-exact "
              f"(resumed {resume or 'none'}; "
              f"degraded reads {ck_mgr.stats['degraded_shard_reads']}, "
              f"shards skipped on resume {ck_mgr.stats['shards_skipped']})")
    if st_client is not None:
        acked = [p for p, ok in st_results if ok]
        failed = [p for p, ok in st_results if not ok]
        assert acked, (
            f"stream axis: 0/{len(st_results)} streamed writes completed "
            f"(round {rnd}); plan: {plan}")
        # Every acked streamed file is chain-durable by contract (final
        # ack = group-committed watermark covering the block) and must
        # read back byte-exact even with the victim still dead.
        for path in acked:
            back = await settle(f"stream read {path}",
                                lambda p=path: v_client.get_file(p))
            assert hashlib.md5(back).hexdigest() == st_md5, (
                f"stream axis: acked streamed file {path} corrupt "
                f"(round {rnd}); plan: {plan}")
        # Un-acked paths: invisible is fine (the abort discarded staged
        # frames), but anything VISIBLE must be byte-exact — a torn
        # partially-committed streamed block must never surface.
        torn_visible = 0
        for path in failed:
            try:
                back = await v_client.get_file(path)
            except DfsError:
                continue
            torn_visible += 1
            assert hashlib.md5(back).hexdigest() == st_md5, (
                f"stream axis: un-acked streamed file {path} surfaced "
                f"TORN (round {rnd}); plan: {plan}")
        print(f"  stream axis: {len(acked)}/{len(st_results)} streamed "
              f"writes acked + byte-exact; {len(failed)} clean failures "
              f"({torn_visible} visible-and-exact); victim "
              f"{st_victim or 'plan-drawn'}")
    if tn_fair is not None:
        assert tn_fair_walls and max(tn_fair_walls) <= tn_budget_grace, (
            f"tenant axis: fair read blew its deadline budget under the "
            f"flood (walls {['%.2f' % w for w in tn_fair_walls]}, "
            f"round {rnd}); plan: {plan}")
        fair_ok = len(tn_fair_walls) - len(tn_fair_errors)
        assert fair_ok >= 1, (
            f"tenant axis: fair tenant STARVED — 0/{len(tn_fair_walls)} "
            f"reads succeeded under the flood (round {rnd}); plan: {plan}")
        fair_back = await settle(
            "tenant-axis fair read",
            lambda: tn_fair.get_file("/a/roulette-payload"))
        assert hashlib.md5(fair_back).hexdigest() == payload_md5, \
            f"tenant axis: post-fault fair read corrupt (round {rnd})"
        # Re-admission: throttling the abuser must never be permanent.
        ab_back = await settle(
            "tenant-axis abuser re-admission read",
            lambda: tn_abuser.get_file("/a/roulette-payload"))
        assert hashlib.md5(ab_back).hexdigest() == payload_md5, \
            f"tenant axis: post-fault abuser read corrupt (round {rnd})"
        print(f"  tenant axis: fair walls "
              f"{['%.2f' % w for w in tn_fair_walls]} <= "
              f"{tn_budget_grace}s ({fair_ok} ok, "
              f"{len(tn_fair_errors)} bounded failures; abuser shed "
              f"{tn_abuser_shed}x); both tenants read clean post-faults")
    for prefix in ("/a/", "/z/"):
        deadline = time.time() + 45
        while True:
            try:
                await v_client.create_file(f"{prefix}post", b"alive",
                                           overwrite=True)
                break
            except Exception as e:
                if time.time() > deadline:
                    raise SystemExit(
                        f"post-chaos write to {prefix} failed: {e}; "
                        f"plan: {plan}")
                await asyncio.sleep(1.0)
    print(f"  round {rnd}: md5 + post-chaos writes ok")

    if linearize:
        # Post-fault WGL pass: the mid-fault history above proves nothing
        # about the HEALED cluster (elections settled, partitions lifted,
        # proxies still aliased). Run a fresh per-op-history workload
        # against the recovered endpoints and require it strictly
        # linearizable — recovery bugs (stale leader serving reads, a
        # replayed rename) surface here, not as md5 mismatches.
        pf_cfg = WorkloadConfig(clients=3, ops_per_client=10, keys=6,
                                seed=rng.randrange(1 << 30),
                                rename_pod_size=3)
        pf_entries = await run_workload(v_client, pf_cfg)
        pf_ok = sum(1 for e in pf_entries
                    if e.get("return_ts") is not None)
        pf_path = tempfile.mkstemp(suffix=".post.jsonl")[1]
        dump_history(pf_entries, pf_path)
        pf_result = check_linearizability(pf_entries,
                                          max_states=2_000_000)
        if not pf_result.linearizable and not pf_result.exhausted:
            raise SystemExit(
                f"POST-FAULT LINEARIZABILITY VIOLATION (round {rnd}): "
                f"{pf_result.message}\nhistory: {pf_path}\nplan: {plan}")
        print(f"  post-fault history "
              f"{'linearizable' if pf_result.linearizable else 'UNKNOWN'}"
              f" ({pf_ok}/{len(pf_entries)} ops returned, {pf_path})")

    for proxy in proxies.values():
        await proxy.stop()
    if ov_proxy is not None:
        await ov_proxy.stop()
    if ov_client is not None:
        await ov_client.close()
    if ck_client is not None:
        await ck_client.close()
    if st_client is not None:
        await st_client.close()
    if tn_fair is not None:
        await tn_fair.close()
        await tn_abuser.close()
    await client.close()
    await wl_client.close()
    await v_client.close()


def one_cluster_round(rnd: int, rng: random.Random, use_tls: bool,
                      topology: str, axes: dict,
                      linearize: bool = False) -> None:
    from tpudfs.testing.livecluster import boot_cluster

    extra_env: dict[str, str] = {}
    if axes.get("tiering"):
        extra_env.update({
            "COLD_THRESHOLD_SECS": "1", "EC_THRESHOLD_SECS": "2",
            "EC_SHAPE": "3,2",
            # Scans every 3 s: the default 60 s scan fired at most
            # once per round, at the edge — conversions must land
            # INSIDE the fault window for the axis to mean anything.
            "TIERING_INTERVAL_SECS": "3"})
    if axes.get("tenant"):
        # Per-tenant admission on every server; the rate only bites
        # NAMED tenants (untenanted traffic maps to system, which is
        # never rate-limited), so the other axes see stock admission.
        extra_env.update({
            "TPUDFS_QOS": "1", "TPUDFS_QOS_RATE": "120",
            "TPUDFS_QOS_QUEUE_DEPTH": "16", "TPUDFS_QOS_QUEUE_WAIT": "0.3",
            "TPUDFS_QOS_WEIGHTS": "fair=2"})
    with boot_cluster(topology, tls=use_tls,
                      extra_env=extra_env or None) as eps:
        asyncio.run(run_round(eps, rng, rnd, axes, linearize=linearize))


def main() -> None:
    import argparse

    from tpudfs.testing.livecluster import retry_start

    ap = argparse.ArgumentParser("chaos-roulette")
    ap.add_argument("rounds", type=int, nargs="?", default=3)
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--tls", action="store_true")
    ap.add_argument("--topology",
                    default=str(REPO / "deploy/topologies/two-shard-ha.json"))
    ap.add_argument("--force-axes", default="",
                    help="comma-separated axes pinned on every round "
                         "(same as CHAOS_FORCE_AXES env)")
    ap.add_argument("--linearize", action="store_true",
                    help="after faults heal, run a fresh per-op-history "
                         "workload and WGL-check it (post-fault "
                         "linearizability, on in CI's seeded rounds)")
    args = ap.parse_args()
    if args.force_axes:
        merged = set(filter(None, os.environ.get(
            "CHAOS_FORCE_AXES", "").split(",")))
        merged |= set(filter(None, args.force_axes.split(",")))
        os.environ["CHAOS_FORCE_AXES"] = ",".join(sorted(merged))
    rng = random.Random(args.seed)
    for rnd in range(1, args.rounds + 1):
        axes = make_axes(rng)
        retry_start(lambda: one_cluster_round(rnd, rng, args.tls,
                                              args.topology, axes,
                                              linearize=args.linearize))
    print(f"CHAOS ROULETTE PASSED ({args.rounds} rounds, seed {args.seed}, "
          f"tls={args.tls})")


if __name__ == "__main__":
    main()
