#!/usr/bin/env python3
"""Live-tier dynamic Raft membership change against REAL OS processes.

Reference parity: test_scripts/dynamic_membership_test.sh (374 lines: add a
master to a running cluster, wait for catch-up + joint->final config,
remove the old leader, verify no write loss) and cluster_membership_test.sh.
The model tier proves joint consensus + learner catch-up in isolation
(tests/test_raft_core.py); THIS tier proves the whole operational flow:

  t0   single-shard-ha cluster up (3 masters + 5 chunkservers)
  t1   multi-block payload written, md5 recorded; background workload on
  t2   spawn a FOURTH master process (empty data dir, --peers = the three
       incumbents) — it boots as a non-member; prevote keeps it harmless
  t3   `cluster add-server` via the client CLI surface -> learner catch-up
       (InstallSnapshot/appends) -> joint -> final; poll /raft/state until
       the new node is a VOTER and the config is non-joint
  t4   the config server's shard map now lists 4 peers (the leader's
       ShardHeartbeat reports its voter group; reconciliation is what a
       fresh client discovers through)
  t5   `cluster remove-server` on the CURRENT LEADER -> joint -> final
       without it; a new leader emerges among the survivors; the removed
       process is then SIGTERMed (kill AFTER removal — the group must stay
       available throughout)
  t6   workload drains; WGL linearizability check over its history
  t7   a FRESH client that knows ONLY the config server reads the payload
       back md5-intact and writes new data — end-to-end proof that
       discovery, quorum, and data survived the membership change

Run directly or via scripts/run_all_tests.py (the CI live tier).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

WORKLOAD_CLIENTS = 2
WORKLOAD_OPS = 40
PAYLOAD_BLOCKS = 12  # x 256 KiB = 3 MiB multi-block file


from tpudfs.testing.livecluster import find_leader, raft_state  # noqa: E402


def wait_config(addrs: list[str], predicate, what: str,
                timeout: float = 90.0) -> dict:
    """Poll /raft/state across ``addrs`` until the LEADER's config
    satisfies ``predicate`` (voters list, joint flag)."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        for addr in addrs:
            st = raft_state(addr)
            if not st or st.get("role") != "leader":
                continue
            last = st.get("config") or {}
            if predicate(last):
                return last
        time.sleep(0.5)
    raise SystemExit(f"timed out waiting for {what}; last config: {last}")


async def drive(eps: dict, root: pathlib.Path) -> None:
    from tpudfs.client.checker import check_linearizability
    from tpudfs.client.client import Client
    from tpudfs.client.workload import WorkloadConfig, dump_history, run_workload
    from tpudfs.common.rpc import RpcClient
    from tpudfs.testing import procs as procutil

    sid = sorted(eps["shards"])[0]
    masters = list(eps["shards"][sid])
    cfg = eps["config_server"]

    from tpudfs.testing.certs import tls_from_endpoints

    tls, tls_args = tls_from_endpoints(eps)
    client = Client(masters, config_addrs=[cfg], block_size=256 * 1024,
                    rpc_timeout=10.0, tls=tls)
    deadline = time.time() + 90
    while True:
        try:
            await client.create_file("/m/probe", b"x")
            await client.delete_file("/m/probe")
            break
        except Exception:
            if time.time() > deadline:
                raise
            await asyncio.sleep(0.5)

    # t1: payload + background workload.
    payload = os.urandom(PAYLOAD_BLOCKS * 256 * 1024)
    await client.create_file("/m/member-payload", payload)
    payload_md5 = hashlib.md5(payload).hexdigest()
    print(f"t1: payload written ({len(payload)} bytes, md5 {payload_md5})")
    wl_client = Client(masters, config_addrs=[cfg], rpc_timeout=3.0,
                      max_retries=8, tls=tls)
    cfg_wl = WorkloadConfig(clients=WORKLOAD_CLIENTS,
                            ops_per_client=WORKLOAD_OPS, keys=6, seed=7,
                            rename_pod_size=3)
    workload = asyncio.create_task(run_workload(wl_client, cfg_wl))

    # t2: spawn the joiner with an EMPTY data dir; it must receive the
    # whole state through the leader's snapshot/appends.
    new_port = procutil.free_port()
    new_addr = f"127.0.0.1:{new_port}"
    logdir = root / "logs"
    joiner_procs: list[subprocess.Popen] = []
    procutil.spawn(joiner_procs, "m-join", logdir, "tpudfs.master",
                   "--port", str(new_port),
                   "--data-dir", str(root / "m-join"),
                   "--peers", ",".join(masters), "--shard-id", sid,
                   "--config-servers", cfg, *tls_args,
                   env={"JAX_PLATFORMS": "cpu"})
    procutil.wait_ready(logdir, "m-join")
    print(f"t2: joiner master up at {new_addr} (empty data dir)")

    try:
        # t3: add-server through the SAME surface the CLI uses.
        leader0 = find_leader(masters)
        await client.cluster_add_server(new_addr)
        final = wait_config(
            masters + [new_addr],
            lambda c: new_addr in (c.get("voters") or []) and not c.get("joint"),
            f"{new_addr} to become a voter (learner catch-up -> joint -> final)",
        )
        print(f"t3: joiner is a VOTER; config voters={sorted(final['voters'])}")
        # The joiner really replicated the namespace: its own /raft/state
        # shows applied progress.
        st = raft_state(new_addr)
        assert st and st["last_applied"] > 0, f"joiner never applied: {st}"

        # t4: client-visible discovery through the config server.
        rpc = RpcClient(tls=tls)
        deadline = time.time() + 60
        while True:
            m = await rpc.call(cfg, "ConfigService", "FetchShardMap", {},
                               timeout=5.0)
            peers = m["shard_map"]["peers"].get(sid) or []
            if new_addr in peers:
                break
            if time.time() > deadline:
                raise SystemExit(
                    f"shard map never learned {new_addr}; peers={peers}")
            await asyncio.sleep(1.0)
        print(f"t4: shard map reconciled; peers={sorted(peers)}")

        # t5: remove the CURRENT leader (the hardest member to remove —
        # it must commit itself out via joint consensus, then step down).
        await client.cluster_remove_server(leader0)
        survivors = [a for a in masters + [new_addr] if a != leader0]
        final = wait_config(
            survivors,
            lambda c: leader0 not in (c.get("voters") or [])
            and not c.get("joint"),
            f"{leader0} removed from the voter set",
        )
        new_leader = find_leader(survivors)
        print(f"t5: old leader {leader0} removed; new leader {new_leader}; "
              f"voters={sorted(final['voters'])}")
        # Only NOW is it safe to kill the removed process.
        old = eps["procs"][
            next(n for n, v in eps["procs"].items() if v["addr"] == leader0)
        ]
        os.kill(old["pid"], signal.SIGTERM)
        print(f"t5: SIGTERMed removed master pid {old['pid']}")

        # t6: drain + WGL-check the concurrent workload.
        entries = await workload
        ok_ops = sum(1 for e in entries if e.get("return_ts") is not None)
        print(f"t6: workload done: {len(entries)} ops ({ok_ops} returned)")
        hist_path = tempfile.mkstemp(suffix=".jsonl")[1]
        dump_history(entries, hist_path)
        result = check_linearizability(entries, max_states=2_000_000)
        if not result.linearizable and not result.exhausted:
            raise SystemExit(
                f"LINEARIZABILITY VIOLATION across membership change: "
                f"{result.message}\nhistory: {hist_path}")
        print(f"t6: history {'linearizable' if result.linearizable else 'UNKNOWN (budget)'}"
              f" ({hist_path})")

        # t7: a fresh client knowing ONLY the config server must discover
        # the post-change group and find every byte intact.
        fresh = Client(config_addrs=[cfg], block_size=256 * 1024,
                       rpc_timeout=10.0, tls=tls)
        back = await fresh.get_file("/m/member-payload")
        got = hashlib.md5(back).hexdigest()
        assert got == payload_md5, f"payload md5 {got} != {payload_md5}"
        await fresh.create_file("/m/post-change", b"alive", overwrite=True)
        assert await fresh.get_file("/m/post-change") == b"alive"
        print("t7: fresh config-discovered client verified payload md5 + "
              "wrote post-change data")
        await fresh.close()
        await rpc.close()
    finally:
        procutil.terminate_all(joiner_procs)
    await client.close()
    await wl_client.close()


def main() -> None:
    for attempt in (1, 2):
        try:
            _run_once()
            return
        except SystemExit as e:
            if attempt == 2 or "failed to start" not in str(e):
                raise
            print(f"cluster start failed ({e}); retrying once")


def _run_once() -> None:
    env = {**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"}
    with tempfile.TemporaryDirectory(prefix="tpudfs-member-") as tmp:
        ready = pathlib.Path(tmp) / "endpoints.json"
        launcher = subprocess.Popen(
            [sys.executable, "scripts/start_cluster.py",
             "--topology", str(REPO / "deploy/topologies/single-shard-ha.json"),
             "--data-dir", f"{tmp}/cluster",
             "--s3-port", "0", "--ready-file", str(ready),
             *(["--tls"] if "--tls" in sys.argv else [])],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            deadline = time.time() + 120
            while not ready.exists():
                if launcher.poll() is not None:
                    out = launcher.stdout.read() if launcher.stdout else ""
                    raise SystemExit(f"cluster failed to start:\n{out}")
                if time.time() > deadline:
                    raise SystemExit("cluster start timed out")
                time.sleep(0.5)
            eps = json.loads(ready.read_text())
            print(f"membership tier against {eps['topology']}")
            asyncio.run(drive(eps, pathlib.Path(tmp) / "cluster"))
            print("MEMBERSHIP TIER PASSED")
        finally:
            launcher.send_signal(signal.SIGINT)
            try:
                launcher.wait(timeout=15)
            except subprocess.TimeoutExpired:
                launcher.kill()


if __name__ == "__main__":
    main()
