#!/usr/bin/env python3
"""tpusched exploration gate: real components under explored schedules.

Runs the production writestream chain, Raft commit, checkpoint
stage→publish, and QoS admission code on the deterministic virtual-clock
loop (tpudfs/testing/vclock.py), systematically exploring bounded task
interleavings around their await points. Every schedule asserts the
declared invariants — ack⇒durable, no-torn-visible, monotonic step
fence, admission never overshoots — plus Wing-Gong-Leung
linearizability of the recorded client histories
(tpudfs/analysis/linearize.py).

A failing schedule writes a replayable trace artifact under
``.tpusched/`` and prints the exact replay command; ``--replay`` re-runs
the recorded choice sequence and must reproduce the identical failure.
``--mutate`` re-introduces a known-fixed ordering bug (publish before
durable, the group-commit lost wakeup) so the gate can prove it still
catches them at its pinned seed.

Usage:
    explore_gate.py                         # all scenarios, pinned seed
    explore_gate.py --scenario ckpt --seed 1234
    explore_gate.py --replay .tpusched/ckpt-....trace.json --scenario ckpt
    explore_gate.py --mutate publish_before_durable --scenario ckpt
    explore_gate.py --changed               # only scenarios mapped to
                                            # modules changed vs HEAD
"""

from __future__ import annotations

import argparse
import asyncio
import os
import pathlib
import shutil
import subprocess
import sys
import tempfile

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))
sys.path.insert(0, str(ROOT / "tests"))

from tpudfs.analysis.linearize import HistoryRecorder, check_history
from tpudfs.testing.vclock import (
    InvariantViolation,
    explore,
    replay,
    trace_from_json,
    trace_to_json,
)

ART_DIR = pathlib.Path(os.environ.get("TPUSCHED_ART_DIR",
                                      ROOT / ".tpusched"))

#: Per-scenario exploration budget: (preemption_bound, max_runs, seeds).
#: Seeds are pinned — the gate's verdict is reproducible by construction.
BUDGETS = {
    "writestream": (2, 18, (101, 102)),
    "raft": (2, 14, (201,)),
    "ckpt": (2, 20, (301, 302)),
    "qos": (2, 20, (401, 402)),
}

#: ``--changed`` routing: path prefix -> scenarios that exercise it.
CHANGED_MAP = [
    ("tpudfs/chunkserver/", ("writestream", "qos")),
    ("tpudfs/common/writestream.py", ("writestream",)),
    ("tpudfs/common/blocknet.py", ("writestream",)),
    ("tpudfs/common/resilience.py", ("qos", "writestream")),
    ("tpudfs/raft/", ("raft",)),
    ("tpudfs/tpu/checkpoint.py", ("ckpt",)),
    ("tpudfs/common/ckptpaths.py", ("ckpt",)),
    ("tpudfs/client/", ("ckpt",)),
    ("tpudfs/testing/vclock.py",
     ("writestream", "raft", "ckpt", "qos")),
    ("tpudfs/analysis/linearize.py",
     ("writestream", "raft", "ckpt", "qos")),
    ("scripts/explore_gate.py",
     ("writestream", "raft", "ckpt", "qos")),
]


# ---------------------------------------------------------------------------
# In-memory duplex plumbing for the writestream scenario
# ---------------------------------------------------------------------------


class _MemTransport:
    def get_write_buffer_size(self) -> int:
        return 0  # never above the backpressure watermark


class _MemWriter:
    """StreamWriter lookalike feeding a peer StreamReader directly."""

    def __init__(self, peer: asyncio.StreamReader):
        self._peer = peer
        self.transport = _MemTransport()
        self._closed = False

    def write(self, data) -> None:
        if not self._closed:
            self._peer.feed_data(bytes(data))

    def writelines(self, bufs) -> None:
        for b in bufs:
            self.write(b)

    async def drain(self) -> None:
        await asyncio.sleep(0)  # a real drain is a suspension point

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._peer.feed_eof()

    def is_closing(self) -> bool:
        return self._closed

    async def wait_closed(self) -> None:
        return

    def get_extra_info(self, name, default=None):
        return default


def _duplex() -> tuple[asyncio.StreamReader, _MemWriter,
                       asyncio.StreamReader, _MemWriter]:
    """(client_reader, client_writer, server_reader, server_writer)."""
    to_server = asyncio.StreamReader(limit=1 << 22)
    to_client = asyncio.StreamReader(limit=1 << 22)
    return to_client, _MemWriter(to_server), to_server, _MemWriter(to_client)


# ---------------------------------------------------------------------------
# Scenarios — each call builds FRESH components and returns one coroutine
# ---------------------------------------------------------------------------


def scenario_writestream():
    """Two concurrent streamed writes then a late third, all through the
    REAL ChunkServer frame pipeline (stage → CRC → group commit → final
    ack) over in-memory duplex connections. The late write lands after
    the first group-commit drain task has finished — the lost-wakeup
    window in the committer's respawn check. Invariants: a success ack
    implies the block is durably readable with the exact bytes
    (ack⇒durable), and the write/read history is linearizable per
    block."""
    from tpudfs.chunkserver.blockstore import BlockStore
    from tpudfs.chunkserver.service import ChunkServer
    from tpudfs.common import blocknet, writestream
    from tpudfs.common.checksum import crc32c

    async def body():
        tmp = tempfile.mkdtemp(prefix="tpusched-ws-")
        try:
            store = BlockStore(pathlib.Path(tmp) / "hot")
            cs = ChunkServer(store)
            loop = asyncio.get_running_loop()
            rec = HistoryRecorder(loop.time)
            payloads = {
                "blk-a": b"alpha-frame-" * 600,
                "blk-b": b"bravo-frame-" * 800,
            }
            acks: dict[str, dict] = {}

            async def one_write(bid: str, data: bytes):
                cr, cw, sr, sw = _duplex()

                async def serve():
                    header, _ = await blocknet._read_frame(sr)
                    await cs.rpc_write_stream(header, sr, sw)

                server_task = asyncio.ensure_future(serve())
                e = rec.invoke(f"writer-{bid}", "write", bid,
                               value=f"{bid}-v1")
                begin = {
                    "m": "WriteStream", "block_id": bid,
                    "size": len(data), "frame_size": 2048,
                    "expected_crc32c": crc32c(data),
                }
                try:
                    resp = await writestream.send_block_stream(
                        cr, cw, begin, data)
                except Exception as exc:  # determinate refusal
                    rec.ret(e, {"ok": False})
                    acks[bid] = {"success": False, "error": repr(exc)}
                else:
                    rec.ret(e, {"ok": bool(resp.get("success"))})
                    acks[bid] = resp
                await server_task

            await asyncio.gather(*(
                one_write(bid, data) for bid, data in payloads.items()))

            # Late arrival: by now the committer's drain task exists and
            # is done — a "respawn only when _task is None" regression
            # parks this writer forever (DeadlockError under vclock).
            payloads["blk-c"] = b"charlie-frame-" * 500
            await one_write("blk-c", payloads["blk-c"])

            for bid, data in payloads.items():
                e = rec.invoke("verifier", "read", bid)
                try:
                    got = store.read_verified(bid)
                except Exception:
                    got = None
                rec.ret(e, f"{bid}-v1" if got == data else None)
                if acks[bid].get("success") and got != data:
                    raise InvariantViolation(
                        f"ack⇒durable violated: {bid} acked success but "
                        f"readback {'differs' if got is not None else 'is missing'}")
            res = check_history(rec.entries)
            if not res.linearizable:
                raise InvariantViolation(
                    f"writestream history not linearizable: {res.message}")
            await cs.committer.stop()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    return body()


def scenario_raft():
    """Three-node Raft commit with explorer-ordered message delivery:
    every Send becomes its own task, so the schedule explorer reorders
    deliveries. Invariants: applied logs are pairwise prefix-consistent
    (no divergence), and every entry the leader reports committed is
    durable in a quorum of logs (ack⇒durable)."""
    import raft_sim

    async def body():
        cluster = raft_sim.SimCluster(3, seed=11)
        lead = cluster.wait_for_leader()
        loop = asyncio.get_running_loop()
        inflight_tasks: set[asyncio.Task] = set()

        async def deliver(src: str, dst: str, msg: dict):
            await asyncio.sleep(0)  # the explorer's reorder point
            node = cluster.nodes[dst]
            if not node.alive or frozenset((src, dst)) in cluster.cut:
                return
            cluster._process_effects(
                node, node.core.handle_message(msg, cluster.now))
            pump()

        def pump() -> None:
            while cluster.inflight:
                src, dst, msg = cluster.inflight.pop(0)
                t = loop.create_task(
                    deliver(src, dst, msg),
                    name=f"deliver:{msg.get('type')}:{src}->{dst}")
                inflight_tasks.add(t)
                t.add_done_callback(inflight_tasks.discard)

        def tick(dt: float) -> None:
            cluster.now += dt
            for n in cluster.nodes.values():
                if n.alive:
                    cluster._process_effects(n, n.core.tick(cluster.now))
            pump()

        from tpudfs.raft.core import NotLeaderError

        proposed: list[tuple[int, tuple]] = []
        for k in range(3):
            cmd = ("set", f"k{k}")
            for _attempt in range(25):
                leader = cluster.leader()
                if leader is None:
                    tick(0.02)
                    await asyncio.sleep(0.02)
                    continue
                try:
                    idx, effects = leader.core.propose(cmd, cluster.now)
                except NotLeaderError:
                    tick(0.02)
                    await asyncio.sleep(0.02)
                    continue
                cluster._process_effects(leader, effects)
                pump()
                proposed.append((idx, cmd))
                break
            for _ in range(6):
                await asyncio.sleep(0.01)
                tick(0.01)

        for _ in range(60):
            if all(len(n.applied) >= len(proposed)
                   for n in cluster.nodes.values()) and not inflight_tasks:
                break
            await asyncio.sleep(0.02)
            tick(0.02)
        while inflight_tasks:
            await asyncio.sleep(0.01)

        seqs = {nid: list(n.applied) for nid, n in cluster.nodes.items()}
        ordered = sorted(seqs.items(), key=lambda kv: len(kv[1]))
        for (a_id, a), (b_id, b) in zip(ordered, ordered[1:]):
            if b[:len(a)] != a:
                raise InvariantViolation(
                    f"applied logs diverged: {a_id}={a} vs {b_id}={b}")
        lead = cluster.leader() or lead
        for idx, cmd in proposed:
            if lead.core.commit_index < idx:
                continue  # never acked committed: no durability claim
            holders = sum(
                1 for n in cluster.nodes.values()
                if any(e.index == idx and e.command == cmd
                       for e in n.durable["log"]))
            if holders < 2:
                raise InvariantViolation(
                    f"committed entry {idx} {cmd} durable on only "
                    f"{holders}/3 logs (ack⇒durable)")

    return body()


class _MemDfsClient:
    """In-memory async stand-in for the DFS client surface
    CheckpointManager uses. Each op suspends at least once so the
    explorer can interleave concurrent savers/readers mid-metadata."""

    block_size = 1 << 20
    tenant = None

    def __init__(self):
        self.files: dict[str, bytes] = {}
        self.meta: dict[str, dict] = {}

    async def _yield(self):
        await asyncio.sleep(0)

    def _stamp(self, path: str, data: bytes, etag: str | None):
        self.files[path] = bytes(data)
        self.meta[path] = {
            "size": len(data),
            "etag_md5": etag or f"mem-{len(data)}",
        }

    async def create_file(self, path, data, ec=None, etag=None,
                          overwrite=False, attrs=None):
        from tpudfs.client.client import DfsError
        await self._yield()
        if not overwrite and path in self.files:
            raise DfsError(f"{path} exists")
        await self._yield()  # widen the metadata/payload window
        self._stamp(path, data, etag)

    async def get_file(self, path):
        from tpudfs.client.client import DfsError
        await self._yield()
        if path not in self.files:
            raise DfsError(f"{path} not found")
        return self.files[path]

    async def get_file_info(self, path):
        await self._yield()
        return dict(self.meta[path]) if path in self.meta else None

    async def publish_checkpoint(self, base, step, src, dst) -> bool:
        from tpudfs.client.client import DfsError
        await self._yield()
        if dst in self.files:
            return False  # idempotent re-publish
        body = self.files.get(src)
        if body is None:
            raise DfsError(f"staged manifest {src} missing")
        await self._yield()
        self._stamp(dst, body, None)
        return True

    async def list_files_with_meta(self, prefix, meta=True, basename=None):
        await self._yield()
        return sorted(
            (p, dict(self.meta[p]) if meta else None)
            for p in self.files if p.startswith(prefix))

    async def delete_file(self, path):
        await self._yield()
        self.files.pop(path, None)
        self.meta.pop(path, None)


def scenario_ckpt():
    """Checkpoint stage→publish with a straggling shard save racing an
    external coordinator's commit, while a reader polls. Invariants: a
    listed step is fully durable (no-torn-visible), latest_step never
    moves backwards (monotonic step fence), and the publish/list/latest
    history is linearizable against the checkpoint model."""
    import numpy as np

    from tpudfs.tpu.checkpoint import (
        CheckpointManager,
        IncompleteCheckpointError,
    )

    base = "/ckpt/run"

    async def body():
        client = _MemDfsClient()
        mgr = CheckpointManager(client, base, num_shards=2, ec=None,
                                hot_copies=True)
        loop = asyncio.get_running_loop()
        rec = HistoryRecorder(loop.time)

        def tree(step: int, shard: int) -> dict:
            return {"w": np.arange(8, dtype=np.float32) * (step + shard + 1)}

        async def commit_step(who: str, step: int) -> bool:
            e = rec.invoke(who, "ckpt_publish", base, value=step)
            try:
                await mgr.commit(step)
            except IncompleteCheckpointError:
                rec.ret(e, {"ok": False})  # may-drop for the checker
                return False
            rec.ret(e, {"ok": True})
            return True

        writer_done = asyncio.Event()

        async def writer():
            try:
                await asyncio.gather(mgr.save_shard(1, 0, tree(1, 0)),
                                     mgr.save_shard(1, 1, tree(1, 1)))
                await commit_step("writer", 1)
                # Step 2: the straggler — an external coordinator fires
                # commit while the shards are still saving. Correct
                # ordering (verify THEN publish) just fails the early
                # commit; publish-before-durable exposes a torn step
                # until the saves land.
                commit_t = asyncio.ensure_future(
                    commit_step("coordinator", 2))
                save = asyncio.ensure_future(asyncio.gather(
                    mgr.save_shard(2, 0, tree(2, 0)),
                    mgr.save_shard(2, 1, tree(2, 1))))
                await commit_t
                await save
                await commit_step("writer", 2)
            finally:
                writer_done.set()

        def incomplete_reason(step: int) -> str | None:
            # Ground-truth durability oracle over the fake client's
            # state, deliberately SYNCHRONOUS: it runs in the same
            # scheduler step as the list that returned ``step``, so a
            # torn window a few yields wide cannot slip between the
            # observation and the check.
            import json as _json

            from tpudfs.common import ckptpaths
            for shard in range(mgr.num_shards):
                spec_path = ckptpaths.shard_spec_path(base, step, shard)
                body = client.files.get(spec_path)
                if body is None:
                    return f"shard {shard} spec missing"
                spec = _json.loads(body)
                for path in (spec.get("path"), spec.get("ec_path")):
                    if path is None:
                        continue
                    info = client.meta.get(path)
                    if info is None or info.get("etag_md5") != spec["etag"] \
                            or int(info.get("size", -1)) != spec["size"]:
                        return f"shard {shard} payload {path} not durable"
            return None

        async def reader():
            prev_latest = None
            polls = 0
            last_seen = object()  # record reads only when the view moves,
            # else the spin-poll floods the WGL search with identical ops
            while not (writer_done.is_set() and polls >= 2):
                polls += 1
                if polls > 400:  # safety valve, never hit in practice
                    break
                record = False
                e = rec.invoke("reader", "ckpt_list", base)
                steps = await mgr.list_steps()
                if tuple(steps) != last_seen:
                    record = True
                    last_seen = tuple(steps)
                    rec.ret(e, tuple(steps))
                else:
                    rec.entries.remove(e)
                for step in steps:
                    reason = incomplete_reason(step)
                    if reason is not None:
                        raise InvariantViolation(
                            f"torn checkpoint visible: step {step} is "
                            f"listed but incomplete ({reason})")
                latest = steps[-1] if steps else None
                if record:
                    e = rec.invoke("reader", "ckpt_latest", base)
                    rec.ret(e, latest)
                if prev_latest is not None and (
                        latest is None or latest < prev_latest):
                    raise InvariantViolation(
                        f"step fence moved backwards: latest went "
                        f"{prev_latest} -> {latest}")
                if latest is not None:
                    prev_latest = latest
                await asyncio.sleep(0)

        await asyncio.gather(writer(), reader())
        res = check_history(rec.entries)
        if not res.linearizable and not res.exhausted:
            raise InvariantViolation(
                f"checkpoint history not linearizable: {res.message}")

    return body()


def scenario_qos():
    """Six tenants contending for two admission slots on the real
    QosShedder. Invariants: inflight never exceeds the limit (the
    TPL050 stale-guard overshoot), and every admit is paired with a
    release (no leaked slots at quiescence)."""
    from tpudfs.common.resilience import QosRejected, QosShedder

    async def body():
        loop = asyncio.get_running_loop()
        shed = QosShedder(max_inflight=2, base_retry_after=0.01,
                          max_queue_wait=0.5, queue_depth=4,
                          clock=loop.time)
        admitted = [0]

        async def worker(i: int):
            tenant = f"t{i % 3}"
            try:
                await shed.acquire(tenant)
            except QosRejected:
                return
            admitted[0] += 1
            try:
                if shed.inflight > shed.max_inflight:
                    raise InvariantViolation(
                        f"admission overshoot: {shed.inflight} inflight "
                        f"> limit {shed.max_inflight}")
                await asyncio.sleep(0.005 * (i + 1))
            finally:
                shed.release(tenant, 0.005)

        await asyncio.gather(*(worker(i) for i in range(6)))
        if shed.peak_inflight > shed.max_inflight:
            raise InvariantViolation(
                f"peak inflight {shed.peak_inflight} exceeded limit "
                f"{shed.max_inflight}")
        if shed.inflight != 0:
            raise InvariantViolation(
                f"leaked admission slots: {shed.inflight} inflight at "
                "quiescence")
        if admitted[0] == 0:
            raise InvariantViolation("no worker was ever admitted")

    return body()


SCENARIOS = {
    "writestream": scenario_writestream,
    "raft": scenario_raft,
    "ckpt": scenario_ckpt,
    "qos": scenario_qos,
}


# ---------------------------------------------------------------------------
# Mutations: re-introduce known-fixed ordering bugs (gate self-proof)
# ---------------------------------------------------------------------------


def mutate_publish_before_durable() -> None:
    """The TPL025-proven checkpoint ordering, reversed: publish the
    manifest FIRST, verify shard durability after. A reader between the
    two observes a torn step."""
    import json as _json
    import time as _time

    from tpudfs.common import ckptpaths
    from tpudfs.tpu import checkpoint as _ckpt

    async def buggy_commit(self, step: int) -> dict:
        with self._op_scope(self.save_budget_s):
            manifest = {
                "format": _ckpt.FORMAT, "base": self.base, "step": step,
                "num_shards": self.num_shards,
                "ec": list(self.ec) if self.ec else None,
                "created_at_ms": int(_time.time() * 1000),
                "shards": [],
            }
            body = _json.dumps(manifest, sort_keys=True).encode()
            staged = ckptpaths.staged_manifest_path(self.base, step)
            await self.client.create_file(staged, body, overwrite=True)
            await self.client.publish_checkpoint(
                self.base, step, src=staged,
                dst=ckptpaths.manifest_path(self.base, step))
            manifest["shards"] = await self._verify_staged(step)
            self.stats["commits"] += 1
        return manifest

    _ckpt.CheckpointManager.commit = buggy_commit


def mutate_lost_wakeup() -> None:
    """The group-commit lost wakeup: a writer that enqueues after the
    drain task already finished never respawns it, so its durability
    future is never resolved — the loop reports a deadlock."""
    from tpudfs.chunkserver import service as _svc

    async def buggy_commit_staged(self, block_id: str, token: str) -> None:
        if self._closed:
            raise OSError("chunkserver stopping")
        fut = asyncio.get_running_loop().create_future()
        fut.add_done_callback(
            lambda f: None if f.cancelled() else f.exception())
        self._pending.append((block_id, token, fut))
        if self._task is None:  # BUG: a finished drain is never respawned
            self._task = asyncio.create_task(self._drain())
        await asyncio.shield(fut)

    _svc.GroupCommitter.commit_staged = buggy_commit_staged


MUTATIONS = {
    "publish_before_durable": mutate_publish_before_durable,
    "lost_wakeup": mutate_lost_wakeup,
}


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def changed_scenarios() -> list[str]:
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=ROOT, capture_output=True, text=True, check=True,
        ).stdout
    except (OSError, subprocess.CalledProcessError):
        return list(SCENARIOS)  # can't tell: run everything
    picked: list[str] = []
    for path in out.split():
        for prefix, names in CHANGED_MAP:
            if path.startswith(prefix):
                for n in names:
                    if n not in picked:
                        picked.append(n)
    return picked


def run_scenario(name: str, *, seed: int, runs: int | None,
                 bound: int | None) -> int:
    factory = SCENARIOS[name]
    pbound, max_runs, base_seeds = BUDGETS[name]
    if bound is not None:
        pbound = bound
    if runs is not None:
        max_runs = runs
    seeds = tuple(seed + s for s in base_seeds)
    report = explore(factory, preemption_bound=pbound, max_runs=max_runs,
                     seeds=seeds)
    if report.ok:
        print(f"  {name}: ok — {report.runs} schedules, "
              f"{report.decision_points} decision points")
        return 0
    failure = report.failure
    ART_DIR.mkdir(parents=True, exist_ok=True)
    art = ART_DIR / f"{name}-seed{seed}.trace.json"
    art.write_text(trace_to_json(failure.trace) + "\n")
    print(f"  {name}: FAIL after {report.runs} schedules")
    print(f"    {failure.describe()}")
    print(f"    trace: {art}")
    print(f"    replay: python scripts/explore_gate.py "
          f"--scenario {name} --replay {art}")
    return 1


def run_replay(name: str, trace_path: str) -> int:
    trace = trace_from_json(pathlib.Path(trace_path).read_text())
    result = replay(SCENARIOS[name], trace)
    print(f"  {name} replay: {result.describe()}")
    return 0 if result.ok else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--scenario", choices=sorted(SCENARIOS),
                    action="append",
                    help="scenario(s) to run (default: all)")
    ap.add_argument("--seed", type=int, default=1234,
                    help="base seed for the random-walk schedules")
    ap.add_argument("--runs", type=int, default=None,
                    help="override per-scenario schedule budget")
    ap.add_argument("--bound", type=int, default=None,
                    help="override preemption bound")
    ap.add_argument("--replay", metavar="TRACE",
                    help="replay a recorded trace (requires --scenario)")
    ap.add_argument("--mutate", choices=sorted(MUTATIONS),
                    help="re-introduce a known-fixed ordering bug first")
    ap.add_argument("--changed", action="store_true",
                    help="run only scenarios mapped to modules changed "
                         "vs HEAD")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    args = ap.parse_args(argv)

    if args.list:
        for n, fn in sorted(SCENARIOS.items()):
            print(f"{n}: {' '.join((fn.__doc__ or '').split()[:18])}…")
        return 0

    if args.mutate:
        MUTATIONS[args.mutate]()
        print(f"mutation applied: {args.mutate}")

    if args.replay:
        if not args.scenario or len(args.scenario) != 1:
            ap.error("--replay needs exactly one --scenario")
        return run_replay(args.scenario[0], args.replay)

    names = args.scenario or (
        changed_scenarios() if args.changed else list(SCENARIOS))
    if not names:
        print("explore gate: no scenarios mapped to the change — skipped")
        return 0

    print(f"explore gate: {', '.join(names)} (seed={args.seed})")
    rc = 0
    for name in names:
        rc |= run_scenario(name, seed=args.seed, runs=args.runs,
                           bound=args.bound)
    return rc


if __name__ == "__main__":
    sys.exit(main())
