#!/usr/bin/env python3
"""Full test driver (reference run_all_tests.sh): lint gate, unit suite,
then a LIVE sharded-HA cluster exercised end-to-end — cross-shard writes and
renames, a benchmark burst, and a concurrent workload whose history is
linearizability-checked.

  python scripts/run_all_tests.py             # everything
  python scripts/run_all_tests.py --skip-unit # live-cluster tiers only
  python scripts/run_all_tests.py --topology deploy/topologies/two-shard.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
ENV = {**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"}


def run(title: str, cmd: list[str], **kw) -> None:
    print(f"\n=== {title}: {' '.join(cmd[:6])} ...")
    t0 = time.time()
    r = subprocess.run(cmd, env=ENV, cwd=REPO, **kw)
    if r.returncode != 0:
        raise SystemExit(f"FAILED: {title} (rc={r.returncode})")
    print(f"=== ok ({time.time() - t0:.1f}s)")


def cli(masters: list[str], cfg: str, *args: str, check: bool = True,
        tls_flags: tuple = ()) -> subprocess.CompletedProcess:
    cmd = [sys.executable, "-m", "tpudfs.client.cli",
           "--masters", ",".join(masters), "--config-servers", cfg,
           *tls_flags, *args]
    r = subprocess.run(cmd, env=ENV, cwd=REPO, capture_output=True, text=True)
    if check and r.returncode != 0:
        print(r.stdout)
        print(r.stderr)
        raise SystemExit(f"CLI failed: {' '.join(args)}")
    return r


def live_cluster_tier(topology: str, workload_ops: int,
                      tls: bool = False) -> None:
    # One retry: start_cluster's free_port reservation has a TOCTOU
    # window (same discipline as chaos_live) — an unlucky port collision
    # should not fail the whole tier.
    for attempt in (1, 2):
        try:
            return _live_cluster_tier_once(topology, workload_ops, tls)
        except SystemExit as e:
            if attempt == 2 or "failed to start" not in str(e):
                raise
            print(f"cluster start failed ({e}); retrying once")


def _live_cluster_tier_once(topology: str, workload_ops: int,
                            tls: bool = False) -> None:
    with tempfile.TemporaryDirectory(prefix="tpudfs-alltests-") as tmp:
        ready = pathlib.Path(tmp) / "endpoints.json"
        launcher = subprocess.Popen(
            [sys.executable, "scripts/start_cluster.py",
             "--topology", topology, "--data-dir", f"{tmp}/cluster",
             "--s3-port", str(_free_port()), "--ready-file", str(ready),
             *(["--tls"] if tls else [])],
            env=ENV, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            deadline = time.time() + 120
            while not ready.exists():
                if launcher.poll() is not None:
                    out = launcher.stdout.read() if launcher.stdout else ""
                    raise SystemExit(f"cluster failed to start:\n{out}")
                if time.time() > deadline:
                    raise SystemExit("cluster start timed out")
                time.sleep(0.5)
            eps = json.loads(ready.read_text())
            masters = [a for addrs in eps["shards"].values() for a in addrs]
            cfg = eps["config_server"]
            tls_flags = (("--tls-ca", eps["tls"]["ca"])
                         if eps.get("tls") else ())

            def ccli(*a, **kw):
                return cli(masters, cfg, *a, tls_flags=tls_flags, **kw)

            print(f"live cluster up: {eps['topology']} "
                  f"({len(eps['shards'])} shards, "
                  f"{len(eps['chunkservers'])} chunkservers)")

            # --- cross-shard smoke: keys on both sides of the /m split.
            src = pathlib.Path(tmp) / "payload.bin"
            src.write_bytes(os.urandom(256 * 1024))
            ccli("put", str(src), "/a/left-shard-file")
            ccli("put", str(src), "/z/right-shard-file")
            for path in ("/a/left-shard-file", "/z/right-shard-file"):
                dst = pathlib.Path(tmp) / "out.bin"
                ccli("get", path, str(dst))
                assert dst.read_bytes() == src.read_bytes(), path
            # Cross-shard rename = 2PC over two Raft groups.
            ccli("rename", "/a/left-shard-file", "/z/moved")
            dst = pathlib.Path(tmp) / "moved.bin"
            ccli("get", "/z/moved", str(dst))
            assert dst.read_bytes() == src.read_bytes()
            r = ccli("inspect", "/a/left-shard-file",
                    check=False)
            assert r.returncode != 0 or "not found" in (
                r.stdout + r.stderr).lower()
            print("cross-shard put/get/rename ok")

            # --- shard-map visibility (reference inspect-ShardMap flow).
            r = ccli("shardmap")
            smap = json.loads(r.stdout)
            assert len(smap["ranges"]) >= len(eps["shards"]), smap
            assert smap["peers"], smap
            print("shardmap CLI ok")

            # --- benchmark burst (reference dfs_cli benchmark semantics).
            ccli("benchmark", "write", "--files", "20",
                "--size", str(64 * 1024), "--concurrency", "5",
                "--prefix", "/a/bench/")
            ccli("benchmark", "read", "--files", "20",
                "--concurrency", "5", "--prefix", "/a/bench/")
            print("benchmark write/read ok")

            if tls:
                # The round-3 verdict's configuration cliff: secured
                # clusters used to silently drop to the asyncio blockport.
                # The native C++ engine's counters must show it carried
                # the writes above (asyncio fallback leaves them 0).
                import urllib.request

                dp_writes = 0.0
                for cs in eps["chunkservers"]:
                    port = int(cs.rsplit(":", 1)[1]) + 1000
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics",
                            timeout=10) as resp:
                        text = resp.read().decode()
                    for line in text.splitlines():
                        if line.startswith(
                                "tpudfs_chunkserver_dataplane_writes_total"):
                            dp_writes += float(line.split()[-1])
                from tpudfs.common import blocknet, native

                if native.has_dataplane() and blocknet.enabled():
                    assert dp_writes > 0, \
                        "native engine inactive under TLS (regression: " \
                        "secured cluster fell back to asyncio blockport)"
                    print(f"native data plane active under TLS "
                          f"(dataplane_writes_total={dp_writes:.0f})")
                else:
                    print("native engine / blockport disabled on this "
                          "host; TLS tier ran without the C++ data plane")

            # --- concurrent workload spanning both shards + WGL check.
            hist = pathlib.Path(tmp) / "history.jsonl"
            ccli("workload", "--clients", "4",
                "--ops", str(workload_ops), "--keys", "6",
                "--out", str(hist))
            r = ccli("check-history", str(hist))
            print(r.stdout.strip().splitlines()[-1])
            print("linearizability check ok")
        finally:
            launcher.send_signal(signal.SIGINT)
            try:
                launcher.wait(timeout=15)
            except subprocess.TimeoutExpired:
                launcher.kill()


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> None:
    ap = argparse.ArgumentParser("tpudfs-run-all-tests")
    ap.add_argument("--skip-unit", action="store_true")
    ap.add_argument("--skip-live", action="store_true")
    ap.add_argument("--skip-chaos", action="store_true")
    ap.add_argument("--topology",
                    default="deploy/topologies/two-shard-ha.json")
    ap.add_argument("--workload-ops", type=int, default=25)
    args = ap.parse_args()

    run("lint (compile gate)", [
        sys.executable, "-m", "compileall", "-q",
        "tpudfs", "tests", "scripts", "bench.py", "__graft_entry__.py",
    ])
    # tpulint: the distributed-systems-aware static analysis gate. Runs
    # BEFORE pytest so an event-loop stall or unverified read path fails
    # fast, with file:line output, instead of as a flaky live-cluster tier.
    # The SARIF artifact makes lint results diffable across CI runs (and
    # loadable in code-scanning viewers) the same way BENCH_*.json is.
    run("lint (tpulint static analysis)",
        [sys.executable, "-m", "tpudfs.analysis"])
    run("lint (tpulint.sarif artifact)",
        [sys.executable, "-m", "tpudfs.analysis",
         "--format", "sarif", "--output", "tpulint.sarif", "-q"])
    # Byte-cost ledger drift gate: the committed copy_ledger.json must
    # match the tree exactly (staleness) and no data-plane route may
    # spend more full-buffer copies than its committed budget (breach).
    # One injected bytes(view) on the write path fails here with the
    # exact file:line hop (docs/static-analysis.md, TPL06x).
    run("byte-cost ledger gate (copy_ledger.json)",
        [sys.executable, "-m", "tpudfs.analysis", "--check-ledger"])
    # Dynamic half of the TPL042/TPL043 native-concurrency contract: build
    # dataplane.cc with -fsanitize=thread and stress the streaming write
    # engine (concurrent streams, mid-stream aborts, stats polling from a
    # second thread). Any race report anchored in native/ fails the run;
    # hosts without a usable TSan toolchain print "SKIP native-sanitize:
    # <reason>" and the stage passes (the script exits 0 on skip).
    run("native sanitizer gate (TSan stress)",
        [sys.executable, "-u", "scripts/native_sanitize.py"])
    # tpusched: real components (writestream chain, Raft commit,
    # checkpoint stage→publish, QoS admission) on the deterministic
    # virtual-clock loop under seeded bounded-preemption schedule
    # exploration, asserting ack⇒durable / no-torn-visible / monotonic
    # step fence plus WGL linearizability of the recorded histories. A
    # failing schedule leaves a replayable trace in .tpusched/ and
    # prints the replay command (docs/static-analysis.md).
    run("tpusched exploration gate (seeded)",
        [sys.executable, "-u", "scripts/explore_gate.py"])
    if not args.skip_unit:
        run("unit + integration suite",
            [sys.executable, "-m", "pytest", "tests/", "-x", "-q"])
    if not args.skip_live:
        live_cluster_tier(args.topology, args.workload_ops)
        # Same tier with EVERY transport encrypted (cluster PKI via
        # --tls): gRPC, raft peers, the native-engine blockport, and the
        # gateway's backend client. Secured clusters must keep the full
        # feature set AND the C++ data plane (reference security.rs).
        live_cluster_tier(args.topology, args.workload_ops, tls=True)
    if not args.skip_chaos:
        # Kill a chunkserver + the shard-0 leader mid-workload, partition
        # shard-1's leader behind a real TCP proxy, then md5-verify and
        # WGL-check (reference chaos_test.sh / network_partition_test.sh /
        # linearizability_test.sh).
        run("live chaos tier",
            [sys.executable, "-u", "scripts/chaos_live.py", args.topology])
        # The same fault schedule fully encrypted: failover, partition
        # heal (TLS re-handshakes through the L4 proxy), and recovery all
        # ride TLS channels, native engine included.
        run("live chaos tier (TLS)",
            [sys.executable, "-u", "scripts/chaos_live.py", args.topology,
             "--tls"])
        # Randomized fault plan, seeded for CI determinism — explores
        # interleavings around the fixed schedule (the plan is printed, so
        # a failure is reproducible from the log).
        # --linearize adds a post-fault WGL pass: once the faults heal, a
        # fresh per-op-history workload must be strictly linearizable.
        run("live chaos roulette (seeded)",
            [sys.executable, "-u", "scripts/chaos_roulette.py", "1",
             "--seed=1234", "--linearize", "--topology", args.topology])
        # Overload-pinned round: one chunkserver bandwidth-shaped while a
        # deadline-budgeted client reads through it — asserts bounded op
        # latency, <= 2x retry amplification, and post-heal recovery on
        # top of whatever kills/partitions the seeded plan draws.
        run("live chaos roulette (overload axis)",
            [sys.executable, "-u", "scripts/chaos_roulette.py", "1",
             "--seed=2468", "--force-axes=overload",
             "--topology", args.topology])
        # Ckpt-pinned round: a 2-shard sharded checkpoint saves steps
        # through the seeded fault window — interrupted saves resume to
        # completion, every listed step restores bit-exact, and no torn
        # checkpoint is ever visible (the atomic-manifest-commit tier).
        run("live chaos roulette (ckpt axis)",
            [sys.executable, "-u", "scripts/chaos_roulette.py", "1",
             "--seed=3579", "--force-axes=ckpt",
             "--topology", args.topology])
        # Stream-pinned round: 4 MiB-block streamed writes (the sub-block
        # frame pipeline) run through the seeded fault window and one
        # extra chain chunkserver is SIGKILLed mid-stream — acked files
        # must read back byte-exact and no torn partially-committed block
        # may ever surface (docs/write-pipeline.md abort semantics).
        run("live chaos roulette (stream axis)",
            [sys.executable, "-u", "scripts/chaos_roulette.py", "1",
             "--seed=5791", "--force-axes=stream",
             "--topology", args.topology])
        # Tenant-pinned round: the cluster boots with per-tenant QoS on
        # and an abuser tenant floods the data path through the seeded
        # fault window — the fair tenant stays inside its deadline budget
        # and never starves, and both tenants read clean post-faults
        # (the noisy-neighbor tier, docs/qos.md). Since ABI 6 the QoS
        # ladder lives in the C++ engine, so this round runs against the
        # NATIVE data plane: the roulette asserts the DataPort handshake
        # reports "native": true on every chunkserver before flooding —
        # a silent fall-back to the asyncio blockport fails the round.
        run("live chaos roulette (tenant axis, native QoS)",
            [sys.executable, "-u", "scripts/chaos_roulette.py", "1",
             "--seed=4680", "--force-axes=tenant",
             "--topology", args.topology])
        # Add a 4th master to a RUNNING group under workload, remove the
        # old leader, verify discovery + no write loss (reference
        # dynamic_membership_test.sh / cluster_membership_test.sh).
        run("live membership tier",
            [sys.executable, "-u", "scripts/membership_live.py"])
        # Learner catch-up (InstallSnapshot + appends), joint consensus,
        # and leader removal all over encrypted raft channels; the joiner
        # process serves the cluster PKI.
        run("live membership tier (TLS)",
            [sys.executable, "-u", "scripts/membership_live.py", "--tls"])
        # Drive hot-prefix traffic until the split detector carves the
        # range to a spare group; verify REDIRECTs + pre-split data
        # (reference auto_scaling_test.sh / shard_split_migration_test.sh).
        run("live autosplit tier",
            [sys.executable, "-u", "scripts/autosplit_live.py"])
        # Hot-range carve + metadata handover to a freshly allocated
        # group, fully encrypted.
        run("live autosplit tier (TLS)",
            [sys.executable, "-u", "scripts/autosplit_live.py", "--tls"])
        # Drive the authenticated gateway with the curl binary: presigned
        # PUT/GET/HEAD, range reads, aws-chunked streaming (reference
        # run_s3_test.sh exercises the same flows with the AWS CLI).
        run("curl S3 conformance",
            [sys.executable, "-u", "scripts/s3_curl_conformance.py"])
    print("\nALL TIERS PASSED")


if __name__ == "__main__":
    main()
