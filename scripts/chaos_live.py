#!/usr/bin/env python3
"""Live-cluster chaos tier: kills and partitions against REAL OS processes.

Reference parity: chaos_test.sh:31-70 (kill a chunkserver and a master
mid-workload, md5-verify a multi-block file afterward),
network_partition_test.sh:30-52 (real TCP faults in front of a master —
here via testing/netem.FaultProxy instead of Toxiproxy containers), and
linearizability_test.sh (the under-fault workload history goes through the
WGL checker).

Timeline against a two-shard-HA cluster (6 masters, 5 chunkservers):

  t0   write a multi-block payload, record its md5
  t1   start a 4-client workload (>= 200 ops, keys span both shards)
  t2   SIGKILL one chunkserver                         (replica loss)
  t3   SIGKILL the leader master of shard-0            (Raft failover)
  t4   partition shard-1's leader behind a FaultProxy  (network fault)
  t5   heal the partition
  t6   workload drains; WGL-check its history (crash ops = maybe-applied)
  t7   md5-verify the payload (reads must fail over around the dead CS)
  t8   post-chaos write/read sanity on a fresh key
  t9   bandwidth-shape one chunkserver (overload); budgeted hedged reads
       must stay inside their deadline budget and recover after the heal
  t10  kill-mid-checkpoint: publish a 2-shard hot-3x checkpoint, then
       SIGKILL two MORE chunkservers while the next step's sharded save
       is in flight (3 of 5 CS now dead). The latest published step must
       restore BIT-EXACT, the interrupted save must RESUME to completion
       (idempotent content-ETag re-puts; replication degrades to the 2
       survivors with healer repair), and the namespace must never list
       a torn checkpoint. Hot-only on purpose: EC allocation hard-fails
       below k+m live chunkservers, so the RS cold-copy path is chaos'd
       where the survivor count supports it (the roulette ckpt axis) and
       the EC-reconstruction restore is proven by the unit tier and the
       degraded bench.
  t11  noisy neighbor: with per-tenant QoS live on the surviving
       chunkservers (the launcher exports TPUDFS_QOS=1), an "abuser"
       tenant floods the data path at ~10x a "fair" tenant's concurrency.
       The fair tenant's p99 must stay within 3x its uncontended baseline
       and its error rate under 1%, the abuser must show up throttled in
       the per-tenant shed counters on the chunkserver ops endpoints, and
       once the flood stops the abuser must be admitted again.

Run directly or via scripts/run_all_tests.py (the CI live tier).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

WORKLOAD_CLIENTS = 4
WORKLOAD_OPS = 60  # per client -> >= 240 ops total under faults
PAYLOAD_BLOCKS = 24  # x 256 KiB = 6 MiB multi-block file


from tpudfs.testing.livecluster import find_leader  # noqa: E402


async def chaos(eps: dict) -> None:
    from tpudfs.client.checker import check_linearizability
    from tpudfs.client.client import Client
    from tpudfs.client.workload import WorkloadConfig, dump_history, run_workload
    from tpudfs.testing.netem import FaultProxy

    shards = eps["shards"]
    sids = sorted(shards)
    masters = [a for sid in sids for a in shards[sid]]
    procs = eps["procs"]
    addr_to_name = {v["addr"]: k for k, v in procs.items() if v["addr"]}

    from tpudfs.testing.certs import tls_from_endpoints

    tls, _ = tls_from_endpoints(eps)
    client = Client(masters, config_addrs=[eps["config_server"]],
                    block_size=256 * 1024, rpc_timeout=10.0, tls=tls)
    deadline = time.time() + 90
    while True:
        try:
            await client.create_file("/a/probe", b"x")
            await client.delete_file("/a/probe")
            break
        except Exception:
            if time.time() > deadline:
                raise
            await asyncio.sleep(0.5)

    # t0: multi-block payload whose md5 must survive everything below.
    payload = os.urandom(PAYLOAD_BLOCKS * 256 * 1024)
    await client.create_file("/a/chaos-payload", payload)
    payload_md5 = hashlib.md5(payload).hexdigest()
    print(f"payload written: {len(payload)} bytes, md5 {payload_md5}")

    leader0 = find_leader(shards[sids[0]])
    leader1 = find_leader(shards[sids[1]])
    print(f"leaders: {sids[0]}={leader0}  {sids[1]}={leader1}")

    # t4 prep: a REAL TCP proxy in front of shard-1's leader; the workload
    # client routes that master through it (host-alias indirection — how
    # the reference interposes Toxiproxy via container DNS).
    host, port = leader1.rsplit(":", 1)
    proxy = FaultProxy(host, int(port))
    proxy_addr = await proxy.start()
    # Generous retries + short RPC timeout: ops caught in a fault window
    # should mostly SUCCEED after failover/heal rather than exhaust into
    # maybe-applied (each crash op gives the WGL search an infinite
    # window; dozens of them blow the budget into UNKNOWN).
    wl_client = Client(masters, config_addrs=[eps["config_server"]],
                      rpc_timeout=3.0, max_retries=8,
                      host_aliases={leader1: proxy_addr}, tls=tls)

    # Small rename pods keep the checker's rename-connected components
    # tractable under many maybe-applied ops (each crash op widens the
    # search exponentially).
    cfg = WorkloadConfig(clients=WORKLOAD_CLIENTS,
                         ops_per_client=WORKLOAD_OPS, keys=9, seed=11,
                         rename_pod_size=3)
    workload = asyncio.create_task(run_workload(wl_client, cfg))

    async def inject() -> None:
        await asyncio.sleep(2.0)
        # t2: kill a chunkserver that holds payload replicas.
        cs_names = [n for n in procs if n.startswith("cs")]
        victim = cs_names[0]
        os.kill(procs[victim]["pid"], signal.SIGKILL)
        print(f"t2: SIGKILLed chunkserver {victim} "
              f"({procs[victim]['addr']})")
        await asyncio.sleep(2.0)
        # t3: kill shard-0's leader master (Raft failover under load).
        lname = addr_to_name.get(leader0, "")
        os.kill(procs[lname]["pid"], signal.SIGKILL)
        print(f"t3: SIGKILLed leader master {lname} ({leader0})")
        await asyncio.sleep(2.0)
        # t4-t5: partition shard-1's leader for 3 s, then heal.
        proxy.partition()
        print("t4: partitioned shard-1 leader route")
        await asyncio.sleep(3.0)
        proxy.heal()
        print("t5: healed partition")

    await asyncio.gather(workload, inject())
    entries = workload.result()
    ok_ops = sum(1 for e in entries if e.get("return_ts") is not None)
    print(f"t6: workload done: {len(entries)} ops ({ok_ops} returned, "
          f"{len(entries) - ok_ops} crash/maybe-applied)")
    assert len(entries) >= 200, "need >= 200 ops under fault"

    hist_path = tempfile.mkstemp(suffix=".jsonl")[1]
    dump_history(entries, hist_path)
    # ~2M states keeps the pure-Python WGL search to ~1-2 min on this
    # host; beyond that the tier's wall clock blows up for little extra
    # proving power (exhaustion is reported as UNKNOWN, not failure).
    result = check_linearizability(entries, max_states=2_000_000)
    if not result.linearizable:
        if result.exhausted:
            # Search budget ran out: UNKNOWN, not a proven violation (the
            # WGL search is exponential in concurrent maybe-applied ops).
            print(f"t6: WARNING linearizability UNKNOWN (budget exhausted; "
                  f"{hist_path})")
        else:
            raise SystemExit(
                f"LINEARIZABILITY VIOLATION under chaos: {result.message}\n"
                f"history: {hist_path}"
            )
    else:
        print(f"t6: history linearizable ({result.message}; {hist_path})")

    # t7: md5-verify the payload with a FRESH client (no warm leader hints);
    # reads must fail over around the dead chunkserver's replicas.
    v_client = Client(masters, config_addrs=[eps["config_server"]],
                      rpc_timeout=10.0, tls=tls)
    back = await v_client.get_file("/a/chaos-payload")
    got_md5 = hashlib.md5(back).hexdigest()
    assert got_md5 == payload_md5, (
        f"payload md5 mismatch after chaos: {got_md5} != {payload_md5}"
    )
    print("t7: payload md5 verified after CS kill + leader kill + partition")

    # t8: the cluster still takes writes on both shards. Until the
    # master's liveness cutoff (15 s, reference master.rs:729-760) prunes
    # the killed chunkserver, allocations may still place replicas on it —
    # retry through that window like any real client would.
    for prefix in ("/a/", "/z/"):
        deadline = time.time() + 45
        while True:
            try:
                await v_client.create_file(f"{prefix}post-chaos", b"alive",
                                           overwrite=True)
                break
            except Exception as e:
                if time.time() > deadline:
                    raise SystemExit(
                        f"post-chaos write to {prefix} never succeeded: {e}"
                    )
                await asyncio.sleep(1.0)
        assert await v_client.get_file(f"{prefix}post-chaos") == b"alive"
    print("t8: post-chaos writes/reads ok on both shards")

    # t9: overload — bandwidth-shape one LIVE chunkserver's data path
    # (256 KiB/s + 0.3 s per chunk, the netem bandwidth/latency toxics) and
    # drive deadline-budgeted hedged reads through it. The resilience
    # contract: ops stay inside budget + grace (hedges dodge the slow
    # replica, the budget bounds whatever is left), retry volume stays
    # within 2x first tries, and throughput recovers once the shaping lifts.
    # The overload runs against the C++ admission plane — a chunkserver
    # that silently fell back to the asyncio blockport fails the run.
    from tpudfs.testing.livecluster import assert_native_data_planes
    await assert_native_data_planes(procs, tls, "t9")
    dead_cs = [n for n in procs if n.startswith("cs")][0]
    slow_addr = next(v["addr"] for k, v in procs.items()
                     if k.startswith("cs") and k != dead_cs and v["addr"])
    sh, sp = slow_addr.rsplit(":", 1)
    ov_proxy = FaultProxy(sh, int(sp))
    ov_addr = await ov_proxy.start()
    ov_proxy.set_latency(0.3)
    ov_proxy.set_bandwidth(256 * 1024)
    # 8 s budget: generous against CI contention for a 6 MiB payload, yet
    # far below the ~24 s the shaped path alone would take — only hedging
    # away from the slow replica can make these reads.
    ov_client = Client(masters, config_addrs=[eps["config_server"]],
                       block_size=256 * 1024, op_budget=8.0,
                       rpc_timeout=0.5, hedge_delay=0.15,
                       host_aliases={slow_addr: ov_addr}, tls=tls)
    print(f"t9: shaping {slow_addr} to 256 KiB/s (+0.3 s/chunk)")
    budget_grace = 8.0 + 1.0
    for i in range(3):
        t0 = time.monotonic()
        back = await ov_client.get_file("/a/chaos-payload")
        wall = time.monotonic() - t0
        assert hashlib.md5(back).hexdigest() == payload_md5
        assert wall <= budget_grace, (
            f"overloaded read {i} blew the deadline budget: {wall:.2f}s"
        )
    rc = ov_client.retry_budget.counters()
    assert rc["retry_budget_retries_total"] \
        <= 2 * rc["retry_budget_first_tries_total"], rc
    ov_proxy.set_latency(0.0)
    ov_proxy.set_bandwidth(0)
    t0 = time.monotonic()
    back = await ov_client.get_file("/a/chaos-payload")
    assert hashlib.md5(back).hexdigest() == payload_md5
    print(f"t9: overload reads bounded (retries {rc}), healed read in "
          f"{time.monotonic() - t0:.2f}s")
    await ov_proxy.stop()
    await ov_client.close()

    # t10: kill-mid-checkpoint. Hot-only (no EC cold copy): with t2's kill
    # plus two more here only 2 of 5 chunkservers survive, and EC
    # allocation hard-fails below k+m live servers while 3x replication
    # degrades (healer repairs when capacity returns) — the resume must be
    # able to finish on the survivors.
    from tpudfs.testing.ckptchaos import assert_restores_bit_exact, ckpt_tree
    from tpudfs.tpu.checkpoint import CheckpointManager

    ck_client = Client(masters, config_addrs=[eps["config_server"]],
                       block_size=256 * 1024, rpc_timeout=3.0,
                       max_retries=8, tls=tls)
    ck = CheckpointManager(ck_client, "/a/chaos-ckpt",
                           num_shards=2, ec=None)
    trees_by_step = {s: {sh: ckpt_tree(s, sh, kib=768) for sh in range(2)}
                     for s in (1, 2)}
    await ck.save(1, trees_by_step[1])
    print("t10: checkpoint step 1 published (pre-kill baseline)")

    live_cs = [n for n in procs
               if n.startswith("cs") and n != dead_cs][:2]
    save_task = asyncio.create_task(ck.save(2, trees_by_step[2]))
    await asyncio.sleep(0.05)
    mid_save = not save_task.done()
    for victim in live_cs:
        os.kill(procs[victim]["pid"], signal.SIGKILL)
    when = "mid-save of step 2" if mid_save else \
        "after step 2 completed (DEGENERATE: kills missed the save window)"
    print(f"t10: SIGKILLed {live_cs} {when}")
    try:
        await save_task
        print("t10: in-flight save of step 2 rode out the kills")
    except Exception as e:
        print(f"t10: in-flight save interrupted ({type(e).__name__}: {e})")

    # Resume the (possibly torn) step-2 save to completion. Allocations
    # may still target the freshly-killed chunkservers until the 15 s
    # liveness cutoff prunes them — retry through that window; every
    # shard that already landed durably is skipped by its content ETag.
    deadline = time.time() + 60
    while True:
        try:
            await ck.save(2, trees_by_step[2])
            break
        except Exception as e:
            if time.time() > deadline:
                raise SystemExit(
                    f"t10: step-2 save never resumed to completion: {e}")
            await asyncio.sleep(1.0)
    steps = await ck.list_steps()
    assert steps == [1, 2], (
        f"t10: namespace lists {steps}, want [1, 2] — a torn or missing "
        "checkpoint is visible")
    for s in steps:
        assert_restores_bit_exact(await ck.restore(s), s, kib=768)
    print(f"t10: steps {steps} restore bit-exact with 3/5 chunkservers "
          f"dead (resume skipped {ck.stats['shards_skipped']} durable "
          f"shard copies, {ck.stats['degraded_shard_reads']} degraded "
          f"shard reads)")
    await ck_client.close()

    # t11: noisy neighbor. The launcher started every server with
    # TPUDFS_QOS=1, so the surviving chunkservers run the tenant-aware
    # admission plane (weighted-fair queueing + 40 ops/s per named
    # tenant). An "abuser" tenant floods them at ~10x the "fair" tenant's
    # concurrency; QoS must keep the fair tenant's latency and error rate
    # bounded, visibly throttle the abuser, and re-admit the abuser once
    # the flood stops.
    # Handshake first: the noisy-neighbor assertions below are only
    # meaningful against the native engine's DRR/rate-bucket ladder.
    await assert_native_data_planes(procs, tls, "t11")
    t11_payload = os.urandom(4 * 256 * 1024)
    t11_md5 = hashlib.md5(t11_payload).hexdigest()
    # local_reads=False: the whole cluster is on 127.0.0.1, and the
    # local-read short circuit would bypass server admission — QoS must
    # be in the measured path.
    fair = Client(masters, config_addrs=[eps["config_server"]],
                  block_size=256 * 1024, op_budget=6.0, rpc_timeout=1.0,
                  initial_backoff=0.05, tls=tls, tenant="fair",
                  local_reads=False)
    abuser = Client(masters, config_addrs=[eps["config_server"]],
                    block_size=256 * 1024, op_budget=6.0, rpc_timeout=1.0,
                    initial_backoff=0.05, tls=tls, tenant="abuser",
                    local_reads=False)
    deadline = time.time() + 45  # ride out the liveness cutoff on t10 kills
    while True:
        try:
            await fair.create_file("/a/t11-payload", t11_payload,
                                   overwrite=True)
            break
        except Exception as e:
            if time.time() > deadline:
                raise SystemExit(f"t11: payload write never succeeded: {e}")
            await asyncio.sleep(1.0)

    async def timed_fair_read(errors: list) -> float:
        t0 = time.monotonic()
        try:
            got = await fair.get_file("/a/t11-payload")
            assert hashlib.md5(got).hexdigest() == t11_md5
        except Exception as e:
            errors.append(e)
        return time.monotonic() - t0

    baseline = sorted([await timed_fair_read([]) for _ in range(8)])
    base_p99 = baseline[-1]
    print(f"t11: fair baseline p99 {base_p99:.3f}s; starting flood")

    stop = asyncio.Event()
    abuser_errors: list = []

    async def flood() -> int:
        done = 0

        async def one() -> None:
            nonlocal done
            try:
                await abuser.get_file("/a/t11-payload")
                done += 1
            except Exception as e:
                abuser_errors.append(e)

        while not stop.is_set():
            await asyncio.gather(*(one() for _ in range(20)))
        return done

    flood_task = asyncio.create_task(flood())
    await asyncio.sleep(1.0)  # let the flood build a backlog
    fair_errors: list = []
    walls = sorted([await timed_fair_read(fair_errors) for _ in range(12)])
    stop.set()
    abuser_ok = await flood_task
    err_rate = len(fair_errors) / len(walls)
    assert err_rate < 0.01, (
        f"t11: fair tenant error rate {err_rate:.0%} under flood: "
        f"{fair_errors}")
    bound = max(3 * base_p99, 2.0)  # absolute floor: baseline can be ~ms
    assert walls[-1] <= bound, (
        f"t11: fair p99 {walls[-1]:.2f}s blew the {bound:.2f}s bound "
        f"under a noisy neighbor")

    # The abuser was actually throttled: per-tenant shed/rate-limit
    # counters on the surviving chunkservers' ops endpoints (data port
    # + 1000, start_cluster's convention).
    import urllib.request
    throttled = 0.0
    for name, v in procs.items():
        if not name.startswith("cs") or not v["addr"]:
            continue
        ops_port = int(v["addr"].rsplit(":", 1)[1]) + 1000
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{ops_port}/metrics", timeout=3
            ).read().decode()
        except Exception:
            continue  # one of the t2/t10 corpses
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            if ("qos_tenant_abuser_shed_total" in line
                    or "qos_tenant_abuser_rate_limited_total" in line):
                throttled += float(line.split()[-1])
    assert throttled > 0, (
        "t11: abuser flooded but no chunkserver reported per-tenant "
        "qos shed/rate-limit counters for it")

    # Recovery: tokens refill, the former abuser reads clean again.
    await asyncio.sleep(1.0)
    got = await abuser.get_file("/a/t11-payload")
    assert hashlib.md5(got).hexdigest() == t11_md5
    print(f"t11: fair p99 {walls[-1]:.2f}s <= {bound:.2f}s under flood "
          f"({len(fair_errors)} fair errors, abuser {abuser_ok} ok / "
          f"{len(abuser_errors)} shed, {throttled:.0f} throttle counts); "
          f"abuser re-admitted after flood")
    await fair.close()
    await abuser.close()

    await proxy.stop()
    await client.close()
    await wl_client.close()
    await v_client.close()


def main() -> None:
    # One retry: start_cluster's free_port reservation has a TOCTOU window
    # and an unlucky collision should not fail the whole tier.
    for attempt in (1, 2):
        try:
            _run_once()
            return
        except SystemExit as e:
            if attempt == 2 or "failed to start" not in str(e):
                raise
            print(f"cluster start failed ({e}); retrying once")


def _run_once() -> None:
    args = [a for a in sys.argv[1:] if a != "--tls"]
    use_tls = "--tls" in sys.argv
    topology = args[0] if args else \
        str(REPO / "deploy/topologies/two-shard-ha.json")
    env = {**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu",
           # t11 drives tenant-aware admission on the live cluster. The
           # rate only bites named tenants (system traffic — everything
           # t0-t10 sends — is never rate-limited), so earlier stages see
           # the same admission behavior as the flat shedder.
           # Rate 40/s + burst 12: the 20-way abuser flood (hundreds of
           # ops/s) reliably trips per-tenant throttling, while the fair
           # tenant's paced single stream stays far under the rate.
           "TPUDFS_QOS": "1", "TPUDFS_QOS_RATE": "40",
           "TPUDFS_QOS_BURST": "12",
           "TPUDFS_QOS_QUEUE_DEPTH": "16", "TPUDFS_QOS_QUEUE_WAIT": "0.3",
           "TPUDFS_QOS_WEIGHTS": "fair=2"}
    with tempfile.TemporaryDirectory(prefix="tpudfs-chaos-") as tmp:
        ready = pathlib.Path(tmp) / "endpoints.json"
        launcher = subprocess.Popen(
            [sys.executable, "scripts/start_cluster.py",
             "--topology", topology, "--data-dir", f"{tmp}/cluster",
             "--s3-port", "0", "--ready-file", str(ready),
             *(["--tls"] if use_tls else [])],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            deadline = time.time() + 120
            while not ready.exists():
                if launcher.poll() is not None:
                    out = launcher.stdout.read() if launcher.stdout else ""
                    raise SystemExit(f"cluster failed to start:\n{out}")
                if time.time() > deadline:
                    raise SystemExit("cluster start timed out")
                time.sleep(0.5)
            eps = json.loads(ready.read_text())
            print(f"chaos tier against {eps['topology']}: "
                  f"{len(eps['shards'])} shards, "
                  f"{len(eps['chunkservers'])} chunkservers")
            asyncio.run(chaos(eps))
            print("CHAOS TIER PASSED")
        finally:
            launcher.send_signal(signal.SIGINT)
            try:
                launcher.wait(timeout=15)
            except subprocess.TimeoutExpired:
                launcher.kill()


if __name__ == "__main__":
    main()
