#!/usr/bin/env python3
"""Live-tier hot-prefix auto-split against REAL OS processes.

Reference parity: test_scripts/auto_scaling_test.sh and
shard_split_migration_test.sh — drive hot-prefix traffic on a running
cluster until the split detector fires, then verify REDIRECT handling,
metadata ingest, and post-split reads of pre-split files. The model tier
covers the detector + migration machinery in isolation
(tests/test_autoshard.py); THIS tier proves it against live processes:

  t0   cluster up: one 3-master shard + 3 SPARE masters (the allocation
       pool for the split-off group) + 5 chunkservers, with a LOW split
       threshold (5 rps; production default 100, reference
       bin/master.rs:51-52)
  t1   pre-split data written under /hot/ and /cold/, md5s recorded
  t2   sustained hot traffic on /hot/* (> threshold) — the leader's
       ThroughputMonitor EMA must cross the threshold AFTER its 30 s
       cooldown warm-up, then the detector carves the /hot range to a
       freshly allocated spare group and hands the metadata over
  t3   FetchShardMap shows >= 2 shards and /hot owned by the NEW shard
  t4   a FRESH config-discovered client reads every pre-split file back
       md5-intact (REDIRECTs resolved transparently), writes + reads new
       data in the hot range (served by the new group), and still reads
       /cold from the original shard

Run directly or via scripts/run_all_tests.py (the CI live tier).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SPLIT_THRESHOLD_RPS = 5.0
PRE_FILES = 12
TRAFFIC_DEADLINE_S = 180.0


async def drive(eps: dict) -> None:
    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient

    sid0 = sorted(eps["shards"])[0]
    masters = list(eps["shards"][sid0])
    cfg = eps["config_server"]

    from tpudfs.testing.certs import tls_from_endpoints

    tls, _ = tls_from_endpoints(eps)
    client = Client(masters, config_addrs=[cfg], block_size=256 * 1024,
                    rpc_timeout=10.0, max_retries=8, tls=tls)
    deadline = time.time() + 90
    while True:
        try:
            await client.create_file("/hot/probe", b"x")
            await client.delete_file("/hot/probe")
            break
        except Exception:
            if time.time() > deadline:
                raise
            await asyncio.sleep(0.5)

    # t1: pre-split payloads (multi-block under /hot, one under /cold).
    md5s: dict[str, str] = {}
    for i in range(PRE_FILES):
        payload = os.urandom(3 * 256 * 1024)
        path = f"/hot/pre-{i:02d}"
        await client.create_file(path, payload)
        md5s[path] = hashlib.md5(payload).hexdigest()
    cold = os.urandom(256 * 1024)
    await client.create_file("/cold/keep", cold)
    md5s["/cold/keep"] = hashlib.md5(cold).hexdigest()
    print(f"t1: {PRE_FILES} pre-split files under /hot + 1 under /cold")

    # t2: sustained hot traffic until the map splits. The EMA needs the
    # rate ABOVE threshold across several 5 s decay windows plus the 30 s
    # cooldown warm-up, so expect ~40-60 s before the carve.
    rpc = RpcClient(tls=tls)
    t0 = time.time()
    split_map = None
    ops = 0
    while time.time() - t0 < TRAFFIC_DEADLINE_S:
        burst = [
            client.get_file_info(f"/hot/pre-{i % PRE_FILES:02d}")
            for i in range(10)
        ]
        await asyncio.gather(*burst)
        ops += len(burst)
        m = await rpc.call(cfg, "ConfigService", "FetchShardMap", {},
                           timeout=5.0)
        shards = m["shard_map"]["peers"]
        if len(shards) >= 2:
            split_map = m["shard_map"]
            break
        await asyncio.sleep(0.3)
    if split_map is None:
        raise SystemExit(
            f"no split after {TRAFFIC_DEADLINE_S}s of hot traffic ({ops} ops)")
    new_sid = next(s for s in split_map["peers"] if s != sid0)
    elapsed = time.time() - t0
    print(f"t3: split fired after {elapsed:.0f}s / {ops} hot ops: "
          f"new shard {new_sid} peers={sorted(split_map['peers'][new_sid])}")
    # The allocation unit is one whole SPARE GROUP: start_cluster boots
    # spares as independent singleton Raft groups, so the carved shard is
    # served by a 1-master group here (production would pool 3-node spare
    # groups; the group-allocation invariant is what matters).
    assert len(split_map["peers"][new_sid]) >= 1

    # t4: FRESH config-discovered client — REDIRECTs and the new routing
    # must be completely transparent.
    fresh = Client(config_addrs=[cfg], block_size=256 * 1024,
                   rpc_timeout=10.0, max_retries=8, tls=tls)
    # Ingest/shuffle may still be settling; reads retry through it.
    for path, want in md5s.items():
        deadline = time.time() + 60
        while True:
            try:
                got = hashlib.md5(await fresh.get_file(path)).hexdigest()
                break
            except Exception as e:
                if time.time() > deadline:
                    raise SystemExit(f"post-split read of {path} failed: {e}")
                await asyncio.sleep(1.0)
        assert got == want, f"{path}: md5 {got} != {want} after split"
    print(f"t4: all {len(md5s)} pre-split files md5-verified post-split")

    # The hot range is genuinely served by the new group now: a write to
    # it must land and read back (retrying through the migration tail).
    deadline = time.time() + 60
    while True:
        try:
            await fresh.create_file("/hot/post-split", b"routed",
                                    overwrite=True)
            break
        except Exception as e:
            if time.time() > deadline:
                raise SystemExit(f"post-split hot write failed: {e}")
            await asyncio.sleep(1.0)
    assert await fresh.get_file("/hot/post-split") == b"routed"
    owner = None
    if fresh.shard_map is not None:
        owner = fresh.shard_map.get_shard("/hot/post-split")
    print(f"t4: post-split hot write ok (range owner: {owner})")
    assert owner == new_sid, f"/hot should route to {new_sid}, got {owner}"

    await fresh.close()
    await client.close()
    await rpc.close()


def main() -> None:
    for attempt in (1, 2):
        try:
            _run_once()
            return
        except SystemExit as e:
            if attempt == 2 or "failed to start" not in str(e):
                raise
            print(f"cluster start failed ({e}); retrying once")


def _run_once() -> None:
    env = {**os.environ, "PYTHONPATH": str(REPO), "JAX_PLATFORMS": "cpu"}
    with tempfile.TemporaryDirectory(prefix="tpudfs-autosplit-") as tmp:
        ready = pathlib.Path(tmp) / "endpoints.json"
        launcher = subprocess.Popen(
            [sys.executable, "scripts/start_cluster.py",
             "--masters", "3", "--spares", "3", "--chunkservers", "5",
             "--split-threshold-rps", str(SPLIT_THRESHOLD_RPS),
             "--data-dir", f"{tmp}/cluster",
             "--s3-port", "0", "--ready-file", str(ready),
             *(["--tls"] if "--tls" in sys.argv else [])],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            deadline = time.time() + 150
            while not ready.exists():
                if launcher.poll() is not None:
                    out = launcher.stdout.read() if launcher.stdout else ""
                    raise SystemExit(f"cluster failed to start:\n{out}")
                if time.time() > deadline:
                    raise SystemExit("cluster start timed out")
                time.sleep(0.5)
            eps = json.loads(ready.read_text())
            print(f"autosplit tier against {eps['topology']}: "
                  f"threshold {SPLIT_THRESHOLD_RPS} rps")
            asyncio.run(drive(eps))
            print("AUTOSPLIT TIER PASSED")
        finally:
            launcher.send_signal(signal.SIGINT)
            try:
                launcher.wait(timeout=15)
            except subprocess.TimeoutExpired:
                launcher.kill()


if __name__ == "__main__":
    main()
