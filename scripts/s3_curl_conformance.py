#!/usr/bin/env python3
"""Curl-only S3 conformance pass against the live gateway.

Drives the authenticated S3 gateway with the **curl binary** — a third
independent HTTP stack beside pyarrow/AWS-C++-SDK and the urllib
independent-signer tests. All auth material comes from the from-spec
signer in ``tpudfs/testing/indep_sigv4.py`` (zero shared code with
``tpudfs.auth``); curl contributes the wire behavior: its own header
casing, connection handling, 100-continue, and range plumbing.

Checks (reference parity: ``test_scripts/run_s3_test.sh`` drives the
same flows with the AWS CLI; curl stands in because the AWS CLI is not
installable in this image):

1. header-auth bucket create
2. presigned PUT of a 1 MiB object (``curl -T``), presigned GET back,
   byte-for-byte md5 compare
3. presigned HEAD (ETag + Content-Length)
4. single-range GET (``curl -r``) → 206 with the exact slice
5. aws-chunked STREAMING-AWS4-HMAC-SHA256-PAYLOAD upload via
   ``curl --data-binary`` with hand-assembled per-chunk signatures,
   read back intact
6. tampered presigned signature → 403 (no bytes served)

Usage: ``python scripts/s3_curl_conformance.py`` (spawns its own
single-shard cluster + gateway; ~30 s).
"""

from __future__ import annotations

import hashlib
import pathlib
import shutil
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tpudfs.testing.indep_sigv4 import Signer  # noqa: E402
from tpudfs.testing.procs import terminate_all  # noqa: E402
from tpudfs.testing.s3stack import (  # noqa: E402
    create_bucket_when_ready, spawn_s3_stack,
)

AK, SK = "AKIACURL", "curl-conformance-secret"
S = Signer(AK, SK)


def curl(*args: str, body_out: pathlib.Path | None = None) -> tuple[int, str]:
    """Run curl, return (http_code, stdout-written-metadata)."""
    cmd = ["curl", "-s", "-o", str(body_out) if body_out else "/dev/null",
           "-w", "%{http_code}", *args]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    if r.returncode != 0:
        raise SystemExit(f"curl failed rc={r.returncode}: {' '.join(cmd)}\n"
                         f"{r.stderr}")
    return int(r.stdout.strip() or 0), r.stderr


def md5(p: pathlib.Path) -> str:
    return hashlib.md5(p.read_bytes()).hexdigest()


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"  {'PASS' if ok else 'FAIL'}  {name}  {detail}")
    if not ok:
        raise SystemExit(f"curl conformance failed at: {name}")


def main() -> None:
    if shutil.which("curl") is None:
        raise SystemExit("curl binary not found")
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="tpudfs-curl-"))
    logdir = tmp / "logs"
    logdir.mkdir()
    procs: list = []
    try:
        host, _ = spawn_s3_stack(procs, tmp, logdir, {AK: SK})

        # 1. bucket create via header auth (retried until the cluster can
        # place data — shared readiness helper).
        create_bucket_when_ready(S, host, "curlbkt")
        check("header-auth bucket create", True)

        payload = (b"curl conformance payload \xf0\x9f\x8c\x8a" * 37449)[
            : 1 << 20]  # exactly 1 MiB, non-ASCII bytes included
        src = tmp / "payload.bin"
        src.write_bytes(payload)
        want_md5 = hashlib.md5(payload).hexdigest()

        # 2. presigned PUT via curl -T, presigned GET back.
        url = S.presign_url("PUT", host, "/curlbkt/obj.bin")
        code, _ = curl("-T", str(src), url)
        check("presigned PUT (curl -T)", code == 200, f"code={code}")
        url = S.presign_url("GET", host, "/curlbkt/obj.bin")
        got = tmp / "got.bin"
        code, _ = curl(url, body_out=got)
        got_md5 = md5(got)
        check("presigned GET", code == 200 and got_md5 == want_md5,
              f"code={code} md5={'ok' if got_md5 == want_md5 else 'BAD'}")

        # 3. presigned HEAD: ETag is the content md5, length matches.
        hdrs = tmp / "head.txt"
        url = S.presign_url("HEAD", host, "/curlbkt/obj.bin")
        code, _ = curl("-I", "-X", "HEAD", url, body_out=hdrs)
        head = hdrs.read_text().lower()
        check("presigned HEAD", code == 200
              and f"content-length: {len(payload)}" in head
              and want_md5 in head,
              f"code={code}")

        # 4. single-range GET via curl -r → 206 with the exact slice.
        url = S.presign_url("GET", host, "/curlbkt/obj.bin")
        part = tmp / "part.bin"
        code, _ = curl("-r", "100000-299999", url, body_out=part)
        check("range GET (curl -r)", code == 206
              and part.read_bytes() == payload[100000:300000],
              f"code={code} len={part.stat().st_size}")

        # 5. aws-chunked streaming upload via curl --data-binary.
        headers, amz_ts, date, seed = S.sign_headers(
            "PUT", host, "/curlbkt/chunked.bin",
            "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
            extra_headers={
                "x-amz-decoded-content-length": str(len(payload)),
                "content-encoding": "aws-chunked",
            },
        )
        body = S.aws_chunked_body(payload, 64 * 1024, amz_ts, date, seed)
        chunked_src = tmp / "chunked.body"
        chunked_src.write_bytes(body)
        hdr_args: list[str] = []
        for k, v in headers.items():
            if k != "host":  # curl derives Host from the URL
                hdr_args += ["-H", f"{k}: {v}"]
        code, _ = curl("-X", "PUT", "--data-binary", f"@{chunked_src}",
                       "-H", "Content-Type:",  # drop curl's form default
                       *hdr_args, f"http://{host}/curlbkt/chunked.bin")
        check("aws-chunked PUT (curl --data-binary)", code == 200,
              f"code={code}")
        url = S.presign_url("GET", host, "/curlbkt/chunked.bin")
        got2 = tmp / "got2.bin"
        code, _ = curl(url, body_out=got2)
        check("aws-chunked readback", code == 200 and md5(got2) == want_md5,
              f"code={code}")

        # 6. tampered presigned signature must be rejected with no bytes.
        url = S.presign_url("GET", host, "/curlbkt/obj.bin")
        bad = url[:-4] + ("0000" if not url.endswith("0000") else "1111")
        denied = tmp / "denied.bin"
        code, _ = curl(bad, body_out=denied)
        check("tampered presign rejected", code == 403
              and want_md5 != (md5(denied) if denied.exists() else ""),
              f"code={code}")

        print("curl conformance: ALL PASS")
    finally:
        terminate_all(procs)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
