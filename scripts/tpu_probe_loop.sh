#!/bin/bash
# Background TPU liveness probe: appends one line per probe to
# /root/repo/tpu_probe.log every 10 min. Mutually exclusive with bench.py
# via flock on /tmp/tpudfs-tpu.lock (bench holds it exclusively for its
# whole run; we skip the probe rather than contend for the one TPU + the
# one CPU core). A second loop instance exits instead of doubling probes.
exec 9>/tmp/tpudfs-probe-loop.lock
flock -n 9 || { echo "probe loop already running" >&2; exit 1; }
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(flock -n /tmp/tpudfs-tpu.lock timeout 60 python -c \
        "import jax; d=jax.devices(); print(d[0].platform, len(d))" 2>&1)
  rc=$?
  if [ $rc -eq 0 ] && echo "$out" | grep -qi tpu; then
    echo "$ts LIVE $out" >> /root/repo/tpu_probe.log
  elif [ $rc -eq 1 ] && [ -z "$out" ]; then
    echo "$ts SKIP bench holds the TPU lock" >> /root/repo/tpu_probe.log
  else
    echo "$ts WEDGED rc=$rc $(echo "$out" | tail -1 | cut -c1-120)" >> /root/repo/tpu_probe.log
  fi
  sleep 600
done
