#!/bin/bash
# Background TPU liveness probe + WINDOW SPRINT trigger.
#
# Every PROBE_INTERVAL seconds, probe the tunneled TPU in a disposable
# subprocess (a wedged tunnel hangs even jax.devices()) and append one
# line to /root/repo/tpu_probe.log. While wedged, keep a CPU-only
# "standby" bench cluster resident with the read fileset pre-written
# (bench.py --standby) so that the moment a probe sees LIVE, the sprint
# (bench.py --sprint) can touch the device within seconds and capture the
# device-dependent windows before the tunnel wedges again — round 4 lost
# its only window to ~10 min of host-side warm-up.
#
# Mutual exclusion: bench.py (any mode) holds /tmp/tpudfs-tpu.lock
# exclusively; probes skip rather than contend. A second loop instance
# exits instead of doubling probes.
exec 9>/tmp/tpudfs-probe-loop.lock
flock -n 9 || { echo "probe loop already running" >&2; exit 1; }

REPO=/root/repo
SPRINT_DIR=/tmp/tpudfs-sprint
PROBE_INTERVAL=240   # short windows: round 4's 10-min cadence missed them
mkdir -p "$SPRINT_DIR"

ensure_standby() {
  local pid
  pid=$(python -c "import json;print(json.load(open('$SPRINT_DIR/standby.json'))['pid'])" 2>/dev/null)
  if [ -n "$pid" ] && kill -0 "$pid" 2>/dev/null; then
    return 0
  fi
  rm -f "$SPRINT_DIR/standby.json"
  ( cd "$REPO" && JAX_PLATFORMS=cpu nohup python bench.py --standby \
      > "$SPRINT_DIR/standby.log" 2>&1 & )
}

while true; do
  # Manage the standby only while the TPU lock is free: a FULL bench run
  # (which holds it) kills the standby to keep the core quiet, and
  # relaunching it mid-run would undo that.
  if flock -n /tmp/tpudfs-tpu.lock true 2>/dev/null; then
    ensure_standby
  fi
  ts=$(date -u +%FT%TZ)
  out=$(flock -n /tmp/tpudfs-tpu.lock timeout 60 python -c \
        "import jax; d=jax.devices(); print(d[0].platform, len(d))" 2>&1)
  rc=$?
  if [ $rc -eq 0 ] && echo "$out" | grep -qi tpu; then
    echo "$ts LIVE $out" >> "$REPO/tpu_probe.log"
    # Window sprint: device windows first, results in BENCH_SPRINT.json
    # (and merged into a CPU-fallback round-end bench as "tpu_sprint").
    ( cd "$REPO" && timeout 1500 python bench.py --sprint \
        >> "$REPO/tpu_sprint.log" 2>&1 )
    src=$?   # capture BEFORE any command substitution clobbers $?
    echo "$(date -u +%FT%TZ) SPRINT rc=$src $(tail -n 1 "$REPO/tpu_sprint.log" | cut -c1-200)" >> "$REPO/tpu_probe.log"
  elif [ $rc -eq 1 ] && [ -z "$out" ]; then
    echo "$ts SKIP bench holds the TPU lock" >> "$REPO/tpu_probe.log"
  else
    echo "$ts WEDGED rc=$rc $(echo "$out" | tail -1 | cut -c1-120)" >> "$REPO/tpu_probe.log"
  fi
  sleep $PROBE_INTERVAL
done
