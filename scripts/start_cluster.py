#!/usr/bin/env python3
"""Local cluster launcher (reference start_cluster.sh HA topology).

Spawns, as separate OS processes: 1 config server, a master group (default
3-node HA Raft for shard-0) plus optional spare masters, N chunkservers, and
the S3 gateway. Prints every endpoint; Ctrl-C tears everything down.

  python scripts/start_cluster.py --masters 3 --chunkservers 5 --spares 1
"""

from __future__ import annotations

import argparse
import atexit
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
PROCS: list[subprocess.Popen] = []


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn(name: str, logdir: pathlib.Path, mod: str, *args: str,
          env: dict | None = None) -> subprocess.Popen:
    log = open(logdir / f"{name}.log", "w")
    p = subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        env={**os.environ, "PYTHONPATH": str(REPO), **(env or {})},
        stdout=log, stderr=subprocess.STDOUT,
    )
    PROCS.append(p)
    return p


def wait_ready(logdir: pathlib.Path, name: str, timeout: float = 60.0) -> None:
    deadline = time.time() + timeout
    path = logdir / f"{name}.log"
    while time.time() < deadline:
        if path.exists() and "READY" in path.read_text():
            return
        time.sleep(0.3)
    raise SystemExit(f"{name} failed to start; see {path}")


def cleanup() -> None:
    for p in PROCS:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + 5
    for p in PROCS:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def main() -> None:
    ap = argparse.ArgumentParser("tpudfs-start-cluster")
    ap.add_argument("--masters", type=int, default=3,
                    help="HA Raft group size for shard-0")
    ap.add_argument("--spares", type=int, default=0,
                    help="unassigned masters for auto-split adoption")
    ap.add_argument("--chunkservers", type=int, default=5)
    ap.add_argument("--data-dir", default="cluster-data")
    ap.add_argument("--s3-port", type=int, default=9000)
    ap.add_argument("--split-threshold-rps", type=float, default=100.0)
    args = ap.parse_args()

    root = pathlib.Path(args.data_dir).resolve()
    logdir = root / "logs"
    logdir.mkdir(parents=True, exist_ok=True)
    atexit.register(cleanup)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    cfg_port = free_port()
    cfg = f"127.0.0.1:{cfg_port}"
    spawn("config", logdir, "tpudfs.configserver", "--port", str(cfg_port),
          "--data-dir", str(root / "cfg"))
    wait_ready(logdir, "config")
    print(f"config server  {cfg}  (ops http://127.0.0.1:{cfg_port + 1000})")

    master_ports = [free_port() for _ in range(args.masters)]
    master_addrs = [f"127.0.0.1:{p}" for p in master_ports]
    # Register the shard before the masters boot so their first map refresh
    # sees the final layout.
    import asyncio  # noqa: E402

    from tpudfs.common.rpc import RpcClient  # noqa: E402

    async def add_shard():
        rpc = RpcClient()
        for _ in range(60):
            try:
                await rpc.call(cfg, "ConfigService", "AddShard",
                               {"shard_id": "shard-0",
                                "peers": master_addrs})
                break
            except Exception:
                await asyncio.sleep(0.5)
        await rpc.close()

    asyncio.run(add_shard())

    for i, port in enumerate(master_ports):
        peers = [a for a in master_addrs if a != f"127.0.0.1:{port}"]
        spawn(f"master{i}", logdir, "tpudfs.master", "--port", str(port),
              "--data-dir", str(root / f"m{i}"),
              "--peers", ",".join(peers), "--config-servers", cfg,
              "--split-threshold-rps", str(args.split_threshold_rps))
    for i in range(args.masters):
        wait_ready(logdir, f"master{i}")
        print(f"master{i}        {master_addrs[i]}  "
              f"(ops http://127.0.0.1:{master_ports[i] + 1000})")

    for i in range(args.spares):
        port = free_port()
        spawn(f"spare{i}", logdir, "tpudfs.master", "--port", str(port),
              "--data-dir", str(root / f"spare{i}"), "--shard-id", "",
              "--config-servers", cfg)
        wait_ready(logdir, f"spare{i}")
        print(f"spare{i}         127.0.0.1:{port}")

    for i in range(args.chunkservers):
        port = free_port()
        spawn(f"cs{i}", logdir, "tpudfs.chunkserver", "--port", str(port),
              "--data-dir", str(root / f"cs{i}"), "--rack-id", f"rack-{i % 3}",
              "--masters", ",".join(master_addrs), "--config-servers", cfg,
              "--heartbeat-interval", "2")
        wait_ready(logdir, f"cs{i}")
        print(f"chunkserver{i}   127.0.0.1:{port}  "
              f"(ops http://127.0.0.1:{port + 1000})")

    spawn("s3", logdir, "tpudfs.s3", env={
        "MASTER_ADDRS": ",".join(master_addrs), "CONFIG_SERVERS": cfg,
        "S3_PORT": str(args.s3_port), "S3_AUTH_ENABLED": "false",
    })
    print(f"s3 gateway     http://127.0.0.1:{args.s3_port}")
    print(f"\nCLI: python -m tpudfs.client.cli --config-servers {cfg} "
          f"--masters {','.join(master_addrs)} <cmd>")
    print("logs:", logdir)
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.path.insert(0, str(REPO))
    main()
