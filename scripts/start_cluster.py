#!/usr/bin/env python3
"""Local cluster launcher (reference start_cluster.sh / docker-compose.yml).

Spawns, as separate OS processes: 1 config server, one master Raft group per
shard, optional spare masters, N chunkservers, and the S3 gateway. The
topology comes either from CLI flags (single-shard) or from a declarative
JSON spec (deploy/topologies/*.json — the compose-file analogue):

  python scripts/start_cluster.py --masters 3 --chunkservers 5
  python scripts/start_cluster.py --topology deploy/topologies/two-shard.json

Prints every endpoint; Ctrl-C tears everything down. With --ready-file PATH,
writes a JSON endpoint map there once the whole topology is up (used by
scripts/run_all_tests.py and the chaos harness to drive a live cluster).
"""

from __future__ import annotations

import argparse
import atexit
import json
import pathlib
import signal
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tpudfs.testing import procs as procutil  # noqa: E402

PROCS: list[subprocess.Popen] = []


#: name -> {"pid": int, "addr": str} for the chaos harness's targeted kills.
PROC_MAP: dict[str, dict] = {}


def spawn(name: str, logdir: pathlib.Path, mod: str, *args: str,
          env: dict | None = None, addr: str = "") -> subprocess.Popen:
    p = procutil.spawn(PROCS, name, logdir, mod, *args, env=env)
    PROC_MAP[name] = {"pid": p.pid, "addr": addr}
    return p


def free_port() -> int:
    return procutil.free_port()


def wait_ready(logdir: pathlib.Path, name: str, timeout: float = 60.0) -> None:
    try:
        procutil.wait_ready(logdir, name, timeout)
    except RuntimeError as e:
        raise SystemExit(str(e))


def cleanup() -> None:
    procutil.terminate_all(PROCS)


def load_topology(args: argparse.Namespace) -> dict:
    if args.topology:
        spec = json.loads(pathlib.Path(args.topology).read_text())
    else:
        spec = {
            "name": "flags",
            "shards": [{"id": "shard-0", "masters": args.masters}],
            "spares": args.spares,
            "chunkservers": args.chunkservers,
            "racks": 3,
            "s3": True,
            "split_threshold_rps": args.split_threshold_rps,
        }
    spec.setdefault("name", pathlib.Path(args.topology).stem
                    if args.topology else "flags")
    spec.setdefault("spares", 0)
    spec.setdefault("racks", 3)
    spec.setdefault("s3", True)
    spec.setdefault("split_threshold_rps", 100.0)
    if not spec.get("shards"):
        raise SystemExit("topology needs at least one shard")
    return spec


def main() -> None:
    ap = argparse.ArgumentParser("tpudfs-start-cluster")
    ap.add_argument("--topology", default="",
                    help="declarative topology JSON (deploy/topologies/)")
    ap.add_argument("--masters", type=int, default=3,
                    help="HA Raft group size for shard-0 (no --topology)")
    ap.add_argument("--spares", type=int, default=0,
                    help="unassigned masters for auto-split adoption")
    ap.add_argument("--chunkservers", type=int, default=5)
    ap.add_argument("--data-dir", default="cluster-data")
    ap.add_argument("--s3-port", type=int, default=9000)
    ap.add_argument("--split-threshold-rps", type=float, default=100.0)
    ap.add_argument("--ready-file", default="",
                    help="write endpoint-map JSON here when fully up")
    ap.add_argument("--no-wait", action="store_true",
                    help="exit after starting (processes keep running)")
    ap.add_argument("--tls", action="store_true",
                    help="mint a cluster PKI and run EVERY transport over "
                         "TLS: gRPC listeners, raft peer channels, the raw "
                         "blockport (native engine included), and the S3 "
                         "gateway's backend client (reference security.rs: "
                         "TLS on every transport)")
    args = ap.parse_args()
    topo = load_topology(args)

    root = pathlib.Path(args.data_dir).resolve()
    logdir = root / "logs"
    logdir.mkdir(parents=True, exist_ok=True)
    if not args.no_wait:
        atexit.register(cleanup)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    tls_args: list[str] = []
    pki: dict = {}
    if args.tls:
        from tpudfs.testing.certs import make_test_pki

        pki = make_test_pki(root / "pki")
        tls_args = ["--tls-cert", pki["server_cert"],
                    "--tls-key", pki["server_key"],
                    "--tls-ca", pki["ca"]]

    cfg_port = free_port()
    cfg = f"127.0.0.1:{cfg_port}"
    spawn("config", logdir, "tpudfs.configserver", "--port", str(cfg_port),
          "--data-dir", str(root / "cfg"), *tls_args)
    wait_ready(logdir, "config")
    print(f"config server  {cfg}  (ops http://127.0.0.1:{cfg_port + 1000})")

    # Reserve every master address up front, then register all shards before
    # any master boots so their first shard-map refresh sees the final
    # layout (AddShard order defines the bootstrap range split: the second
    # shard takes keys < /m — common/sharding.py add_shard).
    shard_addrs: dict[str, list[str]] = {
        s["id"]: [f"127.0.0.1:{free_port()}" for _ in range(s["masters"])]
        for s in topo["shards"]
    }

    import asyncio  # noqa: E402

    from tpudfs.common.rpc import RpcClient  # noqa: E402

    async def add_shards():
        if pki:
            from tpudfs.common.rpc import ClientTls

            rpc = RpcClient(tls=ClientTls(ca_path=pki["ca"]))
        else:
            rpc = RpcClient()
        for s in topo["shards"]:
            for _ in range(60):
                try:
                    await rpc.call(cfg, "ConfigService", "AddShard",
                                   {"shard_id": s["id"],
                                    "peers": shard_addrs[s["id"]]})
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            else:
                raise SystemExit(f"could not register {s['id']} with {cfg}")
        await rpc.close()

    asyncio.run(add_shards())

    all_masters: list[str] = []
    endpoints: dict = {"config_server": cfg, "shards": {}, "chunkservers": [],
                       "topology": topo["name"]}
    for s in topo["shards"]:
        sid = s["id"]
        addrs = shard_addrs[sid]
        for i, addr in enumerate(addrs):
            port = int(addr.rsplit(":", 1)[1])
            peers = [a for a in addrs if a != addr]
            name = f"{sid}-m{i}"
            spawn(name, logdir, "tpudfs.master", "--port", str(port),
                  "--data-dir", str(root / name),
                  "--peers", ",".join(peers), "--shard-id", sid,
                  "--config-servers", cfg,
                  "--split-threshold-rps",
                  str(topo["split_threshold_rps"]), *tls_args, addr=addr)
        for i, addr in enumerate(addrs):
            wait_ready(logdir, f"{sid}-m{i}")
            print(f"{sid}-m{i}     {addr}  "
                  f"(ops http://127.0.0.1:{int(addr.rsplit(':', 1)[1]) + 1000})")
        all_masters.extend(addrs)
        endpoints["shards"][sid] = addrs

    for i in range(topo["spares"]):
        port = free_port()
        spawn(f"spare{i}", logdir, "tpudfs.master", "--port", str(port),
              "--data-dir", str(root / f"spare{i}"), "--shard-id", "",
              "--config-servers", cfg, *tls_args)
        wait_ready(logdir, f"spare{i}")
        print(f"spare{i}         127.0.0.1:{port}")

    for i in range(topo["chunkservers"]):
        port = free_port()
        spawn(f"cs{i}", logdir, "tpudfs.chunkserver", "--port", str(port),
              "--data-dir", str(root / f"cs{i}"),
              "--rack-id", f"rack-{i % topo['racks']}",
              "--masters", ",".join(all_masters), "--config-servers", cfg,
              "--heartbeat-interval", "2", *tls_args,
              addr=f"127.0.0.1:{port}")
        wait_ready(logdir, f"cs{i}")
        print(f"chunkserver{i}   127.0.0.1:{port}  "
              f"(ops http://127.0.0.1:{port + 1000})")
        endpoints["chunkservers"].append(f"127.0.0.1:{port}")

    if topo["s3"]:
        s3_env = {
            "MASTER_ADDRS": ",".join(all_masters), "CONFIG_SERVERS": cfg,
            "S3_PORT": str(args.s3_port), "S3_AUTH_ENABLED": "false",
        }
        if pki:
            s3_env["S3_BACKEND_TLS_CA"] = pki["ca"]
        spawn("s3", logdir, "tpudfs.s3", env=s3_env)
        wait_ready(logdir, "s3")
        print(f"s3 gateway     http://127.0.0.1:{args.s3_port}")
        endpoints["s3"] = f"http://127.0.0.1:{args.s3_port}"

    tls_hint = f" --tls-ca {pki['ca']}" if pki else ""
    print(f"\nCLI: python -m tpudfs.client.cli --config-servers {cfg} "
          f"--masters {','.join(all_masters)}{tls_hint} <cmd>")
    print("logs:", logdir)
    if pki:
        endpoints["tls"] = {"ca": pki["ca"],
                            "client_cert": pki["client_cert"],
                            "client_key": pki["client_key"],
                            # Harness use (e.g. membership_live's joiner
                            # master must serve the cluster's TLS).
                            "server_cert": pki["server_cert"],
                            "server_key": pki["server_key"]}
    if args.ready_file:
        endpoints["pids"] = [p.pid for p in PROCS]
        endpoints["procs"] = PROC_MAP
        pathlib.Path(args.ready_file).write_text(json.dumps(endpoints))
    if args.no_wait:
        return
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
