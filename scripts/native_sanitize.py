#!/usr/bin/env python3
"""Sanitizer gate for the native C++ data plane (native/dataplane.cc).

tpulint's TPL042/TPL043 prove lock discipline *statically*; this script is
the dynamic half of the contract: it builds the native library with
ThreadSanitizer (or ASan/UBSan via --sanitizer), LD_PRELOADs the sanitizer
runtime into a child Python, and stress-drives the streaming write engine
the way a hot chunkserver does — concurrent WriteStream connections,
mid-stream aborts, deliberately corrupt frames, a multi-tenant admission
flood against the QoS ladder (admits, queue parks, sheds, and config
re-pushes racing the serving path), and a second OS thread polling the
stats/term/bad-block/QoS exports the whole time. Any sanitizer report
anchored in native/ sources fails the gate.

Hosts that cannot run the sanitizer (no compiler, no libtsan, container
ASLR/mmap restrictions) print ``SKIP native-sanitize: <reason>`` and exit
0, so the CI stage degrades gracefully instead of flaking.

  python scripts/native_sanitize.py                       # TSan gate
  python scripts/native_sanitize.py --sanitizer address   # ASan instead
  python scripts/native_sanitize.py --keep-going --rounds 5

The instrumented .so is built into a temp directory via the Makefile's
tsan/asan/ubsan targets; native/libtpudfs_native.so is never touched.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import shutil
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
NATIVE_DIR = REPO / "native"

#: Per-sanitizer plumbing: Makefile target + output-path variable, runtime
#: libraries to try for LD_PRELOAD (newest-first sonames across gcc
#: versions), the options env var, and the report marker scanned for in
#: the child's output. exitcode=66 distinguishes "reports were emitted"
#: from an ordinary child crash.
SANITIZERS = {
    "thread": {
        "target": "tsan",
        "makevar": "TSAN_LIB",
        "runtimes": ("libtsan.so", "libtsan.so.2", "libtsan.so.0"),
        "opts_env": "TSAN_OPTIONS",
        "opts": "exitcode=66 halt_on_error=0 report_thread_leaks=0",
        "markers": ("WARNING: ThreadSanitizer",),
    },
    "address": {
        "target": "asan",
        "makevar": "ASAN_LIB",
        "runtimes": ("libasan.so", "libasan.so.8", "libasan.so.6",
                     "libasan.so.5"),
        "opts_env": "ASAN_OPTIONS",
        # detect_leaks=0: the interpreter "leaks" by design at exit;
        # verify_asan_link_order=0: the runtime arrives via LD_PRELOAD,
        # not as the first linked DSO.
        "opts": "exitcode=66 detect_leaks=0 verify_asan_link_order=0",
        "markers": ("ERROR: AddressSanitizer", "WARNING: AddressSanitizer"),
    },
    "undefined": {
        "target": "ubsan",
        "makevar": "UBSAN_LIB",
        "runtimes": ("libubsan.so", "libubsan.so.1"),
        "opts_env": "UBSAN_OPTIONS",
        "opts": "exitcode=66 print_stacktrace=1 halt_on_error=0",
        "markers": ("runtime error:",),
    },
}

#: A report is a *finding* only when a frame lands in our native sources —
#: the child interpreter and its C extensions are uninstrumented, and
#: races reported wholly inside them are noise this gate cannot act on.
NATIVE_MARKERS = ("dataplane.cc", "blockio.cc", "crc32c.cc", "crc64.cc",
                  "gf256.cc", "libtpudfs_native")


def skip(reason: str) -> None:
    print(f"SKIP native-sanitize: {reason}")
    raise SystemExit(0)


def fail(reason: str) -> None:
    print(f"FAIL native-sanitize: {reason}")
    raise SystemExit(1)


def _first_line(text: str) -> str:
    for line in text.splitlines():
        if line.strip():
            return line.strip()
    return "(no output)"


def find_runtime(cxx: str, names: tuple[str, ...]) -> str | None:
    """Resolve the sanitizer runtime .so for LD_PRELOAD via the compiler's
    search path (-print-file-name echoes the name back when not found)."""
    for name in names:
        try:
            r = subprocess.run([cxx, f"-print-file-name={name}"],
                               capture_output=True, text=True, timeout=30)
        except (subprocess.SubprocessError, OSError):
            return None
        path = r.stdout.strip()
        if path and path != name and pathlib.Path(path).exists():
            return str(pathlib.Path(path).resolve())
    return None


def probe(cxx: str, mode: str, runtime: str, tmp: pathlib.Path) -> None:
    """Prove the host can compile AND execute instrumented code under this
    interpreter before paying for the full build — every failure here is a
    host limitation, not a code finding, so it skips."""
    src = tmp / "probe.cc"
    so = tmp / "probe.so"
    src.write_text('extern "C" int tpudfs_sanitize_probe() { return 7; }\n')
    r = subprocess.run(
        [cxx, "-O1", "-g", "-fPIC", "-shared", "-std=c++17",
         f"-fsanitize={mode}", "-o", str(so), str(src)],
        capture_output=True, text=True, timeout=120)
    if r.returncode != 0:
        skip(f"{cxx} cannot link -fsanitize={mode}: "
             f"{_first_line(r.stderr)}")
    spec = SANITIZERS[mode]
    env = {**os.environ, "LD_PRELOAD": runtime, spec["opts_env"]: spec["opts"]}
    r = subprocess.run(
        [sys.executable, "-c",
         f"import ctypes; lib = ctypes.CDLL({str(so)!r}); "
         f"assert lib.tpudfs_sanitize_probe() == 7; "
         f"print('sanitizer-probe-ok')"],
        capture_output=True, text=True, timeout=120, env=env)
    if r.returncode != 0 or "sanitizer-probe-ok" not in r.stdout:
        skip(f"{mode} runtime cannot preload into this interpreter: "
             f"{_first_line(r.stderr or r.stdout)}")


def build_instrumented(mode: str, tmp: pathlib.Path) -> pathlib.Path:
    spec = SANITIZERS[mode]
    out = tmp / f"libtpudfs_native_{spec['target']}.so"
    r = subprocess.run(
        ["make", "-C", str(NATIVE_DIR), spec["target"],
         f"{spec['makevar']}={out}"],
        capture_output=True, text=True, timeout=240)
    if r.returncode != 0:
        # The probe proved the toolchain works, so a build break here is a
        # real finding in the sources (e.g. code that only compiles at -O3).
        fail(f"instrumented build failed:\n{r.stdout}\n{r.stderr}")
    return out


def split_reports(out: str, mode: str) -> list[str]:
    markers = SANITIZERS[mode]["markers"]
    if mode == "undefined":
        return [ln for ln in out.splitlines()
                if any(m in ln for m in markers)]
    reports: list[str] = []
    current: list[str] | None = None
    for line in out.splitlines():
        if any(m in line for m in markers):
            if current:
                reports.append("\n".join(current))
            current = [line]
        elif current is not None:
            current.append(line)
            if line.startswith("=================="):
                reports.append("\n".join(current))
                current = None
    if current:
        reports.append("\n".join(current))
    return reports


def gate(args: argparse.Namespace) -> int:
    mode = args.sanitizer
    spec = SANITIZERS[mode]
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        skip(f"no C++ compiler ({cxx} not on PATH)")
    if shutil.which("make") is None:
        skip("make not on PATH")
    runtime = find_runtime(cxx, spec["runtimes"])
    if runtime is None:
        skip(f"no {mode}-sanitizer runtime library "
             f"(tried {', '.join(spec['runtimes'])})")

    with tempfile.TemporaryDirectory(prefix="tpudfs-sanitize-") as tmpdir:
        tmp = pathlib.Path(tmpdir)
        probe(cxx, mode, runtime, tmp)
        lib_path = build_instrumented(mode, tmp)

        env = {
            **os.environ,
            "LD_PRELOAD": runtime,
            spec["opts_env"]: spec["opts"],
            "TPUDFS_NATIVE_LIB": str(lib_path),
            "PYTHONPATH": str(REPO),
            # Keep uninstrumented thread pools out of the child: every
            # extra runtime thread is pure report noise.
            "OPENBLAS_NUM_THREADS": "1",
            "OMP_NUM_THREADS": "1",
        }
        cmd = [sys.executable, "-u", str(pathlib.Path(__file__).resolve()),
               "--stress", "--sanitizer", mode,
               "--rounds", str(args.rounds), "--streams", str(args.streams)]
        try:
            r = subprocess.run(cmd, env=env, cwd=REPO, timeout=args.timeout,
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT, text=True)
        except subprocess.TimeoutExpired as e:
            out = (e.stdout or b"")
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            print(out[-4000:])
            fail(f"stress harness hung for {args.timeout}s under {mode} "
                 "sanitizer (possible deadlock)")
            return 1
        out = r.stdout or ""

        reports = split_reports(out, mode)
        relevant = [rep for rep in reports
                    if any(m in rep for m in NATIVE_MARKERS)]
        if relevant:
            for rep in relevant:
                print(rep)
                print()
            fail(f"{len(relevant)} {mode}-sanitizer report(s) in native/ "
                 f"sources (of {len(reports)} total)")
        if reports:
            print(f"native-sanitize: ignoring {len(reports)} report(s) "
                  "outside native/ sources (uninstrumented interpreter "
                  "noise)")
        if r.returncode not in (0, 66):
            print(out[-4000:])
            fail(f"stress harness exited rc={r.returncode} under {mode} "
                 "sanitizer")
        if r.returncode == 66 and not reports:
            print(out[-4000:])
            fail(f"{mode} sanitizer flagged the run (rc=66) but no report "
                 "could be parsed from the output above")
        summary = _first_line("\n".join(
            ln for ln in out.splitlines() if ln.startswith("stress:")))
        print(f"native-sanitize: PASS ({mode} sanitizer, {summary})")
    return 0


# ---------------------------------------------------------------------------
# Child: the stress harness (runs with LD_PRELOAD + TPUDFS_NATIVE_LIB set)
# ---------------------------------------------------------------------------

# Wire constants, mirrored from tpudfs/common/{blocknet,writestream}.py.
# The codec is inlined (rather than importing blocknet) so the instrumented
# child never loads grpc's uninstrumented C core; tpulint TPL041 pins the
# canonical values on both sides of the real protocol.
FRAME_SIZE = 256 * 1024


def _pack_frame(header: dict, payload) -> list[bytes]:
    import msgpack
    import struct

    if payload is not None:
        header["_d"] = 1
    h = msgpack.packb(header, use_bin_type=True)
    out = [struct.pack("<I", len(h)), h,
           struct.pack("<Q", len(payload) if payload else 0)]
    if payload:
        out.append(payload)
    return out


async def _read_frame(r):
    import msgpack
    import struct

    hlen = struct.unpack("<I", await r.readexactly(4))[0]
    header = msgpack.unpackb(await r.readexactly(hlen), raw=False,
                             strict_map_key=False)
    plen = struct.unpack("<Q", await r.readexactly(8))[0]
    payload = await r.readexactly(plen) if plen else b""
    return header, payload


def _begin(lib, block_id: str, data: bytes) -> dict:
    crc = int(lib.tpudfs_crc32c(0, data, len(data))) & 0xFFFFFFFF
    return {"m": "WriteStream", "block_id": block_id, "size": len(data),
            "frame_size": FRAME_SIZE, "expected_crc32c": crc,
            "master_term": 0, "master_shard": "", "next_servers": [],
            "next_data_ports": [], "_tn": "sanitize", "_db": 60.0}


async def _open_stream(port: int, lib, block_id: str, data: bytes):
    import asyncio

    r, w = await asyncio.open_connection("127.0.0.1", port)
    w.writelines(_pack_frame(_begin(lib, block_id, data), None))
    await w.drain()
    ready, _ = await _read_frame(r)
    if ready.get("ready") != 1:
        raise RuntimeError(f"no ready ack for {block_id}: {ready}")
    return r, w


def _frames(data: bytes):
    mv = memoryview(data)
    n = max(1, (len(data) + FRAME_SIZE - 1) // FRAME_SIZE)
    for seq in range(n):
        yield seq, bytes(mv[seq * FRAME_SIZE:(seq + 1) * FRAME_SIZE])


async def _full_stream(port: int, lib, block_id: str, size: int) -> None:
    """Happy path: stream every frame, then consume watermark acks through
    the final — asserting the engine acked a successful durable commit."""
    data = os.urandom(size)
    r, w = await _open_stream(port, lib, block_id, data)
    try:
        for seq, payload in _frames(data):
            crc = int(lib.tpudfs_crc32c(0, payload, len(payload)))
            w.writelines(_pack_frame({"q": seq, "c": crc}, payload))
        await w.drain()
        while True:
            ack, _ = await _read_frame(r)
            if not ack.get("ok"):
                raise RuntimeError(f"stream {block_id} failed: {ack}")
            if ack.get("final"):
                break
        if not ack.get("success"):
            raise RuntimeError(f"final nack for {block_id}: {ack}")
    finally:
        w.close()


async def _aborted_stream(port: int, lib, block_id: str, size: int) -> None:
    """Mid-stream torn connection: one good frame, then an RST — the
    engine's abort path (staged-file discard + stream teardown) races
    against concurrent happy-path streams."""
    data = os.urandom(size)
    r, w = await _open_stream(port, lib, block_id, data)
    seq, payload = next(_frames(data))
    crc = int(lib.tpudfs_crc32c(0, payload, len(payload)))
    w.writelines(_pack_frame({"q": seq, "c": crc}, payload))
    await w.drain()
    w.transport.abort()


async def _corrupt_stream(port: int, lib, block_id: str, size: int) -> None:
    """Frame-CRC mismatch: drives the quarantine/abort path and expects
    the engine's error frame back."""
    data = os.urandom(size)
    r, w = await _open_stream(port, lib, block_id, data)
    try:
        seq, payload = next(_frames(data))
        crc = int(lib.tpudfs_crc32c(0, payload, len(payload))) ^ 0xBAD
        w.writelines(_pack_frame({"q": seq, "c": crc}, payload))
        await w.drain()
        err, _ = await _read_frame(r)
        if err.get("ok") is not False:
            raise RuntimeError(f"corrupt frame not rejected: {err}")
    finally:
        w.close()


#: QoS config pushed into the instrumented engine (the wire shape of
#: resilience.qos_wire_config, inlined so the child never imports grpc).
#: Inflight stays generous and the rate bites only bursty NAMED tenants, so
#: the happy-path "sanitize" streams are admitted while the flood tenants
#: below drive the queue -> rate-limit -> shed ladder hard.
QOS_CONFIG = {
    "enabled": 1, "max_inflight": 64, "base_retry_after": 0.005,
    "rate": 10.0, "burst": 8.0, "queue_depth": 4, "queue_wait": 0.02,
    "default_weight": 1.0, "weights": ["flood0=2"], "jitter_seed": 7,
}


def _push_qos(lib, handle: int) -> None:
    import msgpack

    cfg = msgpack.packb(QOS_CONFIG, use_bin_type=True)
    lib.tpudfs_dataplane_set_qos(handle, cfg, len(cfg))


async def _tenant_flood(port: int, tenant: str, n: int) -> tuple[int, int]:
    """One tenant hammering ReadBlock on a missing block id, far past its
    rate: admitted requests come back NOT_FOUND, the rest park in the DRR
    queue and shed with a retry hint. Returns (admitted, shed)."""
    import asyncio

    r, w = await asyncio.open_connection("127.0.0.1", port)
    admitted = shed = 0
    try:
        for i in range(n):
            w.writelines(_pack_frame(
                {"m": "ReadBlock", "block_id": f"no-such-{tenant}",
                 "offset": 0, "length": 0, "_tn": tenant}, None))
            await w.drain()
            resp, _ = await _read_frame(r)
            if "retry_after" in resp:
                shed += 1
            else:
                admitted += 1
    finally:
        w.close()
    return admitted, shed


def stress(args: argparse.Namespace) -> int:
    import asyncio
    import ctypes
    import threading

    sys.path.insert(0, str(REPO))
    from tpudfs.common import native

    lib = native.get_lib()
    if lib is None:
        print("stress: instrumented library failed to load")
        return 1
    if not native.has_dataplane():
        print("stress: instrumented library has no current dataplane ABI")
        return 1

    with tempfile.TemporaryDirectory(prefix="tpudfs-stress-") as tmpdir:
        hot = pathlib.Path(tmpdir) / "hot"
        hot.mkdir()
        handle = lib.tpudfs_dataplane_start(
            b"127.0.0.1", str(hot).encode(), b"", 4 * 1024 * 1024, 0,
            32 << 20, b"", b"", b"", b"", b"", b"")
        if handle < 0:
            print(f"stress: dataplane failed to start ({handle})")
            return 1
        port = int(lib.tpudfs_dataplane_port(handle))

        # Stats poller on a second OS thread: every export that a live
        # chunkserver calls off the serving path, hammered concurrently
        # with the stream traffic below.
        stop_evt = threading.Event()

        def poll() -> None:
            vals6 = (ctypes.c_uint64 * 6)()
            vals8 = (ctypes.c_uint64 * 8)()
            qos8 = (ctypes.c_uint64 * 8)()
            buf = ctypes.create_string_buffer(4096)
            while not stop_evt.is_set():
                lib.tpudfs_dataplane_stats(handle, vals6)
                lib.tpudfs_dataplane_stream_stats(handle, vals8)
                lib.tpudfs_dataplane_stage_stats(handle, vals8)
                lib.tpudfs_dataplane_take_bad(handle, buf, len(buf))
                lib.tpudfs_dataplane_take_terms(handle, buf, len(buf))
                lib.tpudfs_dataplane_qos_stats(handle, qos8)
                lib.tpudfs_dataplane_take_qos(handle, buf, len(buf))
                lib.tpudfs_dataplane_term(handle, b"shard-0")
                stop_evt.wait(0.002)

        poller = threading.Thread(target=poll, name="stats-poller")
        poller.start()

        # Tenant QoS live for the whole run: stream begins and the flood
        # below go through the native admission ladder concurrently.
        _push_qos(lib, handle)
        flood_admitted = 0
        flood_shed = 0

        async def one_round(rnd: int) -> None:
            nonlocal flood_admitted, flood_shed
            size = FRAME_SIZE * 2 + 1031  # 3 frames, last one partial
            tasks = []
            for i in range(args.streams):
                tasks.append(_full_stream(
                    port, lib, f"san-{rnd}-ok{i}", size + i * 17))
            tasks.append(_aborted_stream(port, lib, f"san-{rnd}-torn0", size))
            tasks.append(_aborted_stream(port, lib, f"san-{rnd}-torn1", size))
            tasks.append(_corrupt_stream(port, lib, f"san-{rnd}-crc", size))
            # Multi-tenant admission flood: four tenants, each well past
            # its rate, racing the stream traffic through the QoS lock.
            floods = [_tenant_flood(port, f"flood{t}", 40) for t in range(4)]

            async def repush() -> None:
                # Config re-pushes mid-flood: configure() clears buckets
                # and re-seeds the rng while acquire()/shed run.
                for _ in range(3):
                    await asyncio.sleep(0.01)
                    _push_qos(lib, handle)

            results = await asyncio.gather(*tasks, *floods, repush())
            for res in results[len(tasks):len(tasks) + len(floods)]:
                flood_admitted += res[0]
                flood_shed += res[1]
            # Control-plane calls interleaved from the loop thread while
            # the poller thread reads the same state.
            lib.tpudfs_dataplane_invalidate(handle, f"san-{rnd}-ok0".encode())
            lib.tpudfs_dataplane_set_term(handle, b"shard-0", rnd + 1)

        try:
            for rnd in range(args.rounds):
                asyncio.run(one_round(rnd))
        finally:
            stop_evt.set()
            poller.join()

        vals8 = (ctypes.c_uint64 * 8)()
        lib.tpudfs_dataplane_stream_stats(handle, vals8)
        streams, aborts = int(vals8[5]), int(vals8[7])
        qos8 = (ctypes.c_uint64 * 8)()
        lib.tpudfs_dataplane_qos_stats(handle, qos8)
        qos_admitted, qos_shed = int(qos8[2]), int(qos8[3])
        rc_stop = int(lib.tpudfs_dataplane_stop(handle))
        expect = args.rounds * args.streams
        if streams < expect:
            print(f"stress: engine reports {streams} streams, "
                  f"expected >= {expect}")
            return 1
        if aborts < args.rounds:
            print(f"stress: engine reports {aborts} aborts, "
                  f"expected >= {args.rounds}")
            return 1
        # The flood must have driven BOTH admission outcomes, or the QoS
        # lock was never actually contended and the stage proved nothing.
        if flood_admitted == 0 or flood_shed == 0:
            print(f"stress: tenant flood admitted={flood_admitted} "
                  f"shed={flood_shed}; both must be > 0")
            return 1
        if qos_admitted < flood_admitted or qos_shed < flood_shed:
            print(f"stress: engine qos counters (admitted={qos_admitted}, "
                  f"shed={qos_shed}) below client-observed "
                  f"({flood_admitted}, {flood_shed})")
            return 1
        if rc_stop != 0:
            print(f"stress: dataplane_stop returned {rc_stop}")
            return 1
        print(f"stress: {streams} streams, {aborts} aborts, "
              f"{flood_admitted} flood admits, {flood_shed} flood sheds, "
              f"{args.rounds} rounds ok")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser("tpudfs-native-sanitize")
    ap.add_argument("--sanitizer", choices=sorted(SANITIZERS),
                    default="thread")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--streams", type=int, default=4,
                    help="happy-path streams per round (plus 2 aborted "
                         "and 1 corrupt)")
    ap.add_argument("--timeout", type=int, default=300,
                    help="stress child wall-clock limit, seconds")
    ap.add_argument("--stress", action="store_true",
                    help=argparse.SUPPRESS)  # internal: child mode
    args = ap.parse_args()
    if args.stress:
        return stress(args)
    return gate(args)


if __name__ == "__main__":
    raise SystemExit(main())
