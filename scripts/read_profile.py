"""Read-path breakdown probe (not part of the bench): times each stage of
the DFS→HBM sweep separately to locate the bottleneck.

Stages, each over the same 64 x 1 MiB dataset at concurrency 12:
  meta   — GetFileInfo only
  disk   — + verified pread (short-circuit local read), bytes stay on host
  h2d    — + device_put (verify=False: no CRC kernel dispatch)
  full   — + on-device CRC fold dispatch (verify="lazy", block_until_ready)
"""

from __future__ import annotations

import asyncio
import sys
import time

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

import bench  # noqa: E402

FILES = 64
CONC = 12


async def run() -> None:
    import tempfile

    import jax

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient
    from tpudfs.tpu.crc32c_pallas import bytes_to_words
    from tpudfs.tpu.hbm_reader import HbmReader

    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-prof-")
    maddr, cs_addrs, procs = bench._spawn_cluster(tmp.name)
    try:
        rpc = RpcClient()
        client = Client([maddr], rpc_client=rpc, block_size=1 << 20)
        deadline = asyncio.get_running_loop().time() + 60
        while True:
            try:
                await client.create_file("/p/probe", b"x")
                await client.delete_file("/p/probe")
                break
            except Exception:
                if asyncio.get_running_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.3)
        data = np.random.default_rng(0).integers(
            0, 256, 1 << 20, dtype=np.uint8
        ).tobytes()
        sem = asyncio.Semaphore(CONC)

        async def put(i):
            async with sem:
                await client.create_file(f"/p/f{i:04d}", data)

        await asyncio.gather(*(put(i) for i in range(FILES)))

        device = jax.devices()[0]
        reader = HbmReader(client, [device])
        warm = await reader.read_file_to_device_blocks("/p/f0000",
                                                       verify="lazy")
        jax.block_until_ready([warm[0].array, warm[0].pending_crc])

        async def sweep(fn):
            t0 = time.perf_counter()
            out = await asyncio.gather(*(fn(i) for i in range(FILES)))
            return out, time.perf_counter() - t0

        async def meta_one(i):
            async with sem:
                return await client.get_file_info(f"/p/f{i:04d}")

        metas, dt = await sweep(meta_one)
        print(f"meta : {dt:6.3f}s  {FILES / dt:7.1f} files/s")

        async def disk_one(i):
            async with sem:
                meta = metas[i]
                return [
                    await client._read_block_range(b, 0, 0)
                    for b in meta["blocks"]
                ]

        _, dt = await sweep(disk_one)
        print(f"disk : {dt:6.3f}s  {FILES * len(data) / dt / 1e9:6.3f} GB/s")

        async def h2d_one(i):
            # Mirror the "full" stage minus the CRC dispatch: unverified
            # fetch (local_verify=False, same as verify="lazy" would use)
            # + device_put — so full-h2d isolates the device fold cost.
            async with sem:
                meta = metas[i]
                out = []
                for b in meta["blocks"]:
                    data = await client._read_block_range(
                        b, 0, 0, local_verify=False
                    )
                    out.append(await asyncio.to_thread(
                        lambda d=data: jax.device_put(
                            bytes_to_words(d), device)
                    ))
                return out

        t0 = time.perf_counter()
        blocks = await asyncio.gather(*(h2d_one(i) for i in range(FILES)))
        jax.block_until_ready([a for bl in blocks for a in bl])
        dt = time.perf_counter() - t0
        print(f"h2d  : {dt:6.3f}s  {FILES * len(data) / dt / 1e9:6.3f} GB/s")

        async def full_one(i):
            async with sem:
                return await reader.read_file_to_device_blocks(
                    f"/p/f{i:04d}", verify="lazy"
                )

        t0 = time.perf_counter()
        blocks = await asyncio.gather(*(full_one(i) for i in range(FILES)))
        arrs = [b.array for bl in blocks for b in bl]
        arrs += [b.pending_crc for bl in blocks for b in bl
                 if b.pending_crc is not None]
        jax.block_until_ready(arrs)
        dt = time.perf_counter() - t0
        print(f"full : {dt:6.3f}s  {FILES * len(data) / dt / 1e9:6.3f} GB/s")

        # Fused rounds (read_combiner): the production infeed path —
        # native multi-pread + one device_put + one CRC per round.
        fused_reader = HbmReader(client, [device], batch_reads=16)
        fused_reader.warm_batches(len(data) // 512)
        fsem = asyncio.Semaphore(32)

        async def fused_one(i):
            async with fsem:
                return await fused_reader.read_file_to_device_blocks(
                    f"/p/f{i:04d}", verify="lazy"
                )

        t0 = time.perf_counter()
        blocks = await asyncio.gather(*(fused_one(i) for i in range(FILES)))
        jax.block_until_ready(
            [x for bl in blocks for b in bl for x in b.sync_arrays]
        )
        dt = time.perf_counter() - t0
        print(f"fused: {dt:6.3f}s  {FILES * len(data) / dt / 1e9:6.3f} GB/s")
        await rpc.close()
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


if __name__ == "__main__":
    asyncio.run(run())
