"""Read-sweep laboratory (not part of the bench): one cluster + dataset,
then N alternating cold/warm sweeps printed individually — fast iteration
on read-path changes and a view of the window-to-window distribution that
bench.py's median-of-3 summarizes.

Usage: JAX_PLATFORMS=cpu python scripts/sweep_lab.py [sweeps]
"""

from __future__ import annotations

import asyncio
import sys
import time

sys.path.insert(0, ".")

import bench  # noqa: E402

_numeric = [a for a in sys.argv[1:] if not a.startswith("-")]
SWEEPS = int(_numeric[0]) if _numeric else 6
FILES = bench.FILES


async def run() -> None:
    import tempfile

    import jax

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient
    from tpudfs.tpu.hbm_reader import HbmReader

    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-lab-")
    maddr, cs_addrs, procs = bench._spawn_cluster(tmp.name)
    try:
        rpc = RpcClient()
        client = Client([maddr], rpc_client=rpc,
                        block_size=bench.BLOCK_MB << 20, etag_mode="crc64")
        deadline = asyncio.get_running_loop().time() + 60
        while True:
            try:
                await client.create_file("/lab/probe", b"x")
                await client.delete_file("/lab/probe")
                break
            except Exception:
                if asyncio.get_running_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.3)
        import numpy as np

        data = np.random.default_rng(0).integers(
            0, 256, bench.BLOCK_MB << 20, dtype=np.uint8
        ).tobytes()
        sem = asyncio.Semaphore(bench.WRITE_CONCURRENCY)

        async def put(i):
            async with sem:
                await client.create_file(f"/lab/f{i:04d}", data)

        t0 = time.perf_counter()
        await asyncio.gather(*(put(i) for i in range(FILES)))
        print(f"dataset: {FILES} MiB in {time.perf_counter() - t0:.1f}s")

        multiset = "--multiset" in sys.argv
        if multiset:
            async def put_set(s, i):
                async with sem:
                    await client.create_file(f"/lab/s{s}/f{i:04d}", data)

            for s in range(3):
                await asyncio.gather(
                    *(put_set(s, i) for i in range(FILES)))
            print("3 extra sets written")

        import os

        device = jax.devices()[0]
        batch = int(os.environ.get("LAB_BATCH", bench.BATCH_READS))
        conc = int(os.environ.get("LAB_CONC", bench.FUSED_READ_CONCURRENCY))
        bench.FUSED_READ_CONCURRENCY = conc
        reader = HbmReader(client, [device], batch_reads=batch)
        # Warm BEFORE the profiling patch: warm-up transfers must not
        # count toward the profiled device_put stage total.
        reader.warm_batches((bench.BLOCK_MB << 20) // 512)

        stage_t = {"fill": 0.0, "put": 0.0, "rounds": 0}
        if "--profile" in sys.argv:
            # Wall-clock per combiner stage (both run off the event loop,
            # so their sum can exceed the sweep time only via overlap —
            # on one core it should roughly EQUAL sweep time; the
            # difference is Python staging/scheduling).
            from tpudfs.tpu.read_combiner import ReadCombiner

            real_fill = ReadCombiner._fill_buffer

            def timed_fill(self, reqs, buf):
                t0 = time.perf_counter()
                out = real_fill(self, reqs, buf)
                stage_t["fill"] += time.perf_counter() - t0
                stage_t["rounds"] += 1
                return out

            ReadCombiner._fill_buffer = timed_fill
            real_put = jax.device_put

            def timed_put(x, *a, **k):
                t0 = time.perf_counter()
                out = real_put(x, *a, **k)
                stage_t["put"] += time.perf_counter() - t0
                return out

            jax.device_put = timed_put
        metas = await asyncio.gather(
            *(client.get_file_info(f"/lab/f{i:04d}") for i in range(FILES))
        )

        async def sweep(read_fn, items, conc):
            semr = asyncio.Semaphore(conc)
            blocks: list = []

            async def one(item):
                async with semr:
                    bs = await read_fn(item)
                    blocks.extend(bs)
                    return sum(b.size for b in bs)

            t0 = time.perf_counter()
            sizes = await asyncio.gather(*(one(it) for it in items))
            jax.block_until_ready(
                [x for b in blocks for x in b.sync_arrays])
            gbps = sum(sizes) / (time.perf_counter() - t0) / 1e9
            await reader.confirm(blocks)
            return gbps

        if multiset:
            # Warm the process on /lab/f*, then time each NEVER-READ set.
            for _ in range(3):
                await sweep(
                    lambda p: reader.read_file_to_device_blocks(
                        p, verify="lazy"),
                    [f"/lab/f{j:04d}" for j in range(FILES)],
                    bench.FUSED_READ_CONCURRENCY)
            for s in range(3):
                c = await sweep(
                    lambda p: reader.read_file_to_device_blocks(
                        p, verify="lazy"),
                    [f"/lab/s{s}/f{j:04d}" for j in range(FILES)],
                    bench.FUSED_READ_CONCURRENCY)
                c2 = await sweep(
                    lambda p: reader.read_file_to_device_blocks(
                        p, verify="lazy"),
                    [f"/lab/s{s}/f{j:04d}" for j in range(FILES)],
                    bench.FUSED_READ_CONCURRENCY)
                print(f"set {s}: first {c:.3f} repeat {c2:.3f} GB/s")
            await rpc.close()
            return

        interleave = "--interleave" in sys.argv
        colds, warms = [], []
        for i in range(SWEEPS):
            if interleave:
                raw = bench._bench_raw_infeed(
                    device, bench.BLOCK_MB << 20, 16)
                client.local_reads = False
                import os as _os

                gconc = int(_os.environ.get("LAB_GRPC_CONC",
                                            bench.READ_CONCURRENCY))
                g = await sweep(
                    lambda p: reader.read_file_to_device_blocks(
                        p, verify="lazy"),
                    [f"/lab/f{j:04d}" for j in range(48)], gconc)
                client.local_reads = True
                print(f"  raw {raw:.3f} grpc {g:.3f}")
            c = await sweep(
                lambda p: reader.read_file_to_device_blocks(p, verify="lazy"),
                [f"/lab/f{j:04d}" for j in range(FILES)],
                bench.FUSED_READ_CONCURRENCY)
            w = await sweep(
                lambda m: reader.read_meta_blocks_fast(m, device),
                metas, bench.FUSED_READ_CONCURRENCY)
            colds.append(c)
            warms.append(w)
            print(f"sweep {i}: cold {c:.3f} warm {w:.3f} GB/s")
        import statistics

        print(f"cold median {statistics.median(colds):.3f} "
              f"[{min(colds):.3f},{max(colds):.3f}]  "
              f"warm median {statistics.median(warms):.3f} "
              f"[{min(warms):.3f},{max(warms):.3f}]")
        if stage_t["rounds"]:
            print(f"stages: fill {stage_t['fill']:.2f}s "
                  f"device_put {stage_t['put']:.2f}s over "
                  f"{stage_t['rounds']} rounds")
        await rpc.close()
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    asyncio.run(run())
