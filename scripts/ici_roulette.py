#!/usr/bin/env python3
"""Randomized soak for the collective write group (tpudfs/tpu/write_group):
each round boots a FRESH in-process cluster whose chunkservers form an
IciWriteGroup on the virtual CPU mesh, runs concurrent client puts, and
randomly injects the group's failure modes WHILE writes are in flight:

- ``detach``: a member leaves the group mid-stream (group unhealthy ->
  writes degrade to the TCP chain) and re-attaches later;
- ``device_fail``: the replicate call raises for a window (round
  failures -> per-write TCP fallback);
- ``verify_fail``: the replicate call returns short acks for a window
  (the round must fail ATOMICALLY — no partial persists).

Verification per round: every acked put reads back byte-exact through a
fresh client; counters are coherent (blocks served = sum of per-axis
accounting); and when the group was healthy at round end, a final put
rides a collective round again (recovery, not just degradation).

  python scripts/ici_roulette.py [rounds] [--seed N]
"""

from __future__ import annotations

import asyncio
import hashlib
import pathlib
import random
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import os  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

N_CS = 3
WRITERS = 4
FILES_PER_WRITER = 6
FILE_BYTES = 96 * 1024  # multi-block at 64 KiB blocks


async def run_round(rnd: int, rng: random.Random, rng_seed: int) -> None:
    from tpudfs.testing.inproc import InprocCluster
    from tpudfs.tpu.ici_replication import make_mesh
    from tpudfs.tpu.write_group import IciWriteGroup

    with tempfile.TemporaryDirectory(prefix="tpudfs-icirl-") as wd:
        c = InprocCluster(wd, n_masters=1, n_cs=N_CS)
        await c.start()
        mesh = make_mesh(jax.devices()[:N_CS])
        group = IciWriteGroup(
            mesh, [cs.address for cs in c.chunkservers], replication=3)
        for i, cs in enumerate(c.chunkservers):
            cs.attach_ici_group(group, i)
        try:
            await c.ready()
            client = c.client(block_size=64 * 1024)

            # Fault plan: 1-3 injections, ACTIVITY-triggered — each waits
            # for collective rounds to actually flow before striking, so
            # a loaded host (this box runs soaks concurrently) cannot
            # make every window miss the write stream.
            real_replicate = group.replicator.replicate
            plan = [rng.choice(["detach", "device_fail", "verify_fail"])
                    for _ in range(rng.randint(1, 3))]
            print(f"round {rnd}: plan = {plan}")
            bites = [False] * len(plan)  # per WINDOW, not per kind

            def attempts() -> int:
                return group.stats.rounds + group.stats.round_failures

            async def wait_for_activity(baseline: int) -> None:
                while attempts() <= baseline and not done.is_set():
                    await asyncio.sleep(0.02)

            done = asyncio.Event()

            async def hold_until_bite(probe, max_s: float = 3.0) -> bool:
                """Keep the fault in place until ``probe()`` shows it BIT
                (or the writers finished / cap expired) — time-boxed
                windows under heavy host load often closed before any
                round passed through them."""
                deadline = asyncio.get_running_loop().time() + max_s
                while (not probe() and not done.is_set()
                       and asyncio.get_running_loop().time() < deadline):
                    await asyncio.sleep(0.05)
                # Let an in-flight round resolve against the fault.
                await asyncio.sleep(0.1)
                return probe()

            async def injector():
                for w_i, kind in enumerate(plan):
                    await wait_for_activity(attempts())
                    if done.is_set():
                        return
                    mark = group.stats.round_failures
                    fb = sum(cs.ici_fallbacks for cs in c.chunkservers)
                    if kind == "detach":
                        pos = rng.randrange(N_CS)
                        group.detach(pos)
                        bit = await hold_until_bite(
                            lambda: sum(cs.ici_fallbacks
                                        for cs in c.chunkservers) > fb)
                        group.attach(c.chunkservers[pos], pos)
                        bites[w_i] = bit
                        print(f"  detach/reattach pos {pos} (bit={bit})")
                    elif kind == "device_fail":
                        def boom(*a, **k):
                            raise RuntimeError("injected device failure")
                        group.replicator.replicate = boom
                        bit = await hold_until_bite(
                            lambda: group.stats.round_failures > mark)
                        group.replicator.replicate = real_replicate
                        bites[w_i] = bit
                        print(f"  device_fail window (bit={bit})")
                    else:
                        def short(words, crcs):
                            replicas, ok, acks = real_replicate(words, crcs)
                            return replicas, ok, acks * 0  # zero acks
                        group.replicator.replicate = short
                        bit = await hold_until_bite(
                            lambda: group.stats.round_failures > mark)
                        group.replicator.replicate = real_replicate
                        bites[w_i] = bit
                        print(f"  verify_fail window (bit={bit})")

            written: dict[str, str] = {}

            async def writer(w: int):
                # Child RNG per writer: concurrent coroutines draining one
                # shared stream would make --seed non-reproducing (the
                # interleaving reorders draws); per-writer streams keep
                # every path's CONTENT deterministic for the printed seed.
                wrng = random.Random((rng_seed << 8) ^ (rnd << 4) ^ w)
                for i in range(FILES_PER_WRITER):
                    data = wrng.getrandbits(8 * FILE_BYTES).to_bytes(
                        FILE_BYTES, "little")
                    path = f"/icirl/w{w}/f{i}"
                    await client.create_file(path, data)
                    written[path] = hashlib.md5(data).hexdigest()
                    await asyncio.sleep(wrng.uniform(0.0, 0.15))

            async def all_writers():
                try:
                    await asyncio.gather(
                        *(writer(w) for w in range(WRITERS)))
                finally:
                    done.set()

            await asyncio.gather(injector(), all_writers())

            # Every acked write reads back byte-exact via a FRESH client.
            v = c.client(block_size=64 * 1024)
            for path, md5 in written.items():
                back = await v.get_file(path)
                assert hashlib.md5(back).hexdigest() == md5, \
                    f"round {rnd}: {path} corrupt; plan {plan}"

            # Recovery: with the group healthy again, a final put must
            # ride a collective round (not be stuck on TCP forever).
            assert group.healthy(), f"round {rnd}: group never re-healed"
            before = group.stats.rounds
            await client.create_file("/icirl/final",
                                     rng.getrandbits(8 * 65536).to_bytes(
                                         65536, "little"))
            assert group.stats.rounds > before, \
                f"round {rnd}: post-fault put did not ride ICI"
            bitten = [k for k, b in zip(plan, bites) if b]
            missed = [k for k, b in zip(plan, bites) if not b]
            print(f"  round {rnd}: {len(written)} puts byte-exact; "
                  f"rounds={group.stats.rounds} blocks={group.stats.blocks} "
                  f"round_failures={group.stats.round_failures} "
                  f"fallbacks={sum(cs.ici_fallbacks for cs in c.chunkservers)}"
                  f"; bit={bitten or 'none'}"
                  + (f" DEGENERATE(missed={missed})" if missed else ""))
        finally:
            await group.stop()
            await c.stop()


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser("ici-roulette")
    ap.add_argument("rounds", type=int, nargs="?", default=5)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()
    for rnd in range(1, args.rounds + 1):
        # Per-ROUND rng: a failed round replays from its own seed without
        # replaying everything before it (the injector/plan stream is
        # drawn only by the single injector coroutine, so it is
        # deterministic; writers get their own child streams).
        rng = random.Random((args.seed << 16) ^ rnd)
        asyncio.run(run_round(rnd, rng, args.seed))
    print(f"ICI ROULETTE PASSED ({args.rounds} rounds, seed {args.seed})")


if __name__ == "__main__":
    main()
