#!/usr/bin/env python3
"""Seed-sweep of the jepsen bank invariant: RANDOMIZED fault schedules
(partitions, leader crashes, heals at random offsets) across many seeds —
the committed test pins one schedule; this hunts rare interleavings with
the SAME shared checker (tests/test_raft_jepsen.py:run_bank_case, so the
sweep can never validate a stale copy of the invariants).

  python scripts/raft_fuzz_soak.py [n_seeds]    # default 100

Round-4 session evidence: 500 seeds, 0 invariant violations
(state-machine divergence / balance leak / lost acked op all clean).
"""

from __future__ import annotations

import pathlib
import random
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tests.raft_sim import SimCluster  # noqa: E402
from tests.test_raft_jepsen import run_bank_case  # noqa: E402

STEPS = 48


def random_schedule(rng: random.Random) -> dict[int, str]:
    """Partition/crash pairs with random offsets and durations."""
    sched: dict[int, str] = {}
    t = rng.randint(4, 10)
    while t < STEPS - 6:
        kind = rng.choice(["partition", "crash"])
        sched[t] = kind
        sched[t + rng.randint(4, 8)] = \
            "heal" if kind == "partition" else "restart"
        t += rng.randint(10, 16)
    return sched


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    bad = 0
    for seed in range(1000, 1000 + n):
        rng = random.Random(seed * 31 + 1)
        violation, _acked = run_bank_case(
            SimCluster(5, seed=seed), rng, random_schedule(rng), STEPS
        )
        if violation:
            bad += 1
            print(f"SEED {seed}: {violation}")
        if (seed - 999) % 20 == 0:
            print(f"...{seed - 999}/{n} done, {bad} failures", flush=True)
    print(f"RAFT-FUZZ-SOAK: {n} seeds, {bad} failures")
    if bad:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
