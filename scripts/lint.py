#!/usr/bin/env python3
"""tpulint runner — thin wrapper so CI and humans share one entry point.

    python scripts/lint.py                # == python -m tpudfs.analysis
    python scripts/lint.py --changed      # pre-commit: files changed vs main
    python scripts/lint.py --list-rules
    python scripts/lint.py --write-baseline
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tpudfs.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
