#!/usr/bin/env python3
"""Local block-engine microbenchmark (reference dfs/chunkserver/benches/
io_bench.rs:9-45 — criterion write/read at 4 KB / 64 KB / 1 MB).

Times the ChunkServer block engine in isolation — no RPC, no cluster — in
both modes:

- native: the C++ fused engine (native/blockio.cc — CRC + tmp/fsync/rename
  write, read + range-verify in one call);
- python: the numpy/std-lib fallback path.

Per (engine, size): durable write MB/s, verified read MB/s, ops/s. Output is
one JSON document; pass --json for machine-only output.

  python scripts/io_bench.py [--secs 1.0] [--json]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402

SIZES = [("4KB", 4 << 10), ("64KB", 64 << 10), ("1MB", 1 << 20)]


def _force_python_fallback() -> None:
    from tpudfs.common import native

    native._lib = None
    native._load_attempted = True


def _bench_engine(engine: str, secs: float) -> list[dict]:
    from tpudfs.chunkserver.blockstore import BlockStore
    from tpudfs.common import native

    if engine == "python":
        _force_python_fallback()
    else:
        if native.build_and_load() is None or not native.has_blockio():
            return [{"engine": engine, "error": "native engine unavailable"}]

    results = []
    with tempfile.TemporaryDirectory(prefix=f"iobench-{engine}-") as tmp:
        store = BlockStore(tmp)
        for label, size in SIZES:
            data = np.random.default_rng(size).integers(
                0, 256, size, dtype=np.uint8
            ).tobytes()
            # Warm-up (also populates one block for the read pass).
            store.write(f"warm-{label}", data)
            store.read_verified(f"warm-{label}")

            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < secs:
                store.write(f"w-{label}-{n % 64}", data)
                n += 1
            dt = time.perf_counter() - t0
            write_mbps = n * size / dt / 1e6
            write_ops = n / dt
            written = min(n, 64)

            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < secs:
                out = store.read_verified(f"w-{label}-{n % written}")
                n += 1
            dt = time.perf_counter() - t0
            assert out == data
            results.append({
                "engine": engine,
                "size": label,
                "write_MBps": round(write_mbps, 1),
                "write_ops_s": round(write_ops, 1),
                "read_verified_MBps": round(n * size / dt / 1e6, 1),
                "read_ops_s": round(n / dt, 1),
            })
    return results


def main() -> None:
    ap = argparse.ArgumentParser("tpudfs-io-bench")
    ap.add_argument("--secs", type=float, default=1.0,
                    help="measure window per (engine, size) op")
    ap.add_argument("--json", action="store_true", help="JSON only")
    args = ap.parse_args()

    # Native pass must run before the fallback pass poisons the loader cache.
    rows = _bench_engine("native", args.secs) + _bench_engine(
        "python", args.secs
    )
    doc = {"bench": "block-engine", "results": rows}
    if args.json:
        print(json.dumps(doc))
        return
    for r in rows:
        if "error" in r:
            print(f"{r['engine']:7s}  {r['error']}")
            continue
        print(
            f"{r['engine']:7s} {r['size']:>5s}  "
            f"write {r['write_MBps']:9.1f} MB/s ({r['write_ops_s']:8.1f} op/s)  "
            f"read+verify {r['read_verified_MBps']:9.1f} MB/s "
            f"({r['read_ops_s']:8.1f} op/s)"
        )
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
