// GF(2^8) arithmetic + Reed-Solomon matrix application.
//
// TPU-native twin of the reference's erasure path (dfs/common/src/erasure.rs:7-59,
// which uses the reed-solomon-erasure crate: GF(2^8) with polynomial 0x11D and a
// systematic Vandermonde code). Matrix construction/inversion lives in Python
// (tpudfs/common/erasure.py); this library provides the byte-crunching inner
// loop: out = M x shards over GF(2^8), used for both encode (M = parity rows)
// and decode (M = inverted surviving rows).
//
// Exported C ABI:
//   void tpudfs_gf256_matmul(const uint8_t* mat, size_t rows, size_t cols,
//                            const uint8_t* const* shards, size_t shard_len,
//                            uint8_t* const* out);
//   void tpudfs_gf256_mul_slice(uint8_t c, const uint8_t* in, size_t len,
//                               uint8_t* acc);   // acc ^= c * in
//   uint8_t tpudfs_gf256_mul(uint8_t a, uint8_t b);

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t kPoly = 0x11D;  // x^8 + x^4 + x^3 + x^2 + 1

struct Tables {
  uint8_t exp[512];
  uint8_t log[256];
  // mul[c] = 256-byte row: mul[c][x] = c*x in GF(2^8).
  uint8_t mul[256][256];
  Tables() {
    uint32_t x = 1;
    for (int i = 0; i < 255; i++) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = static_cast<uint8_t>(i);
      x <<= 1;
      if (x & 0x100) x ^= kPoly;
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    log[0] = 0;
    for (int c = 0; c < 256; c++) {
      for (int v = 0; v < 256; v++) {
        mul[c][v] = (c && v)
            ? exp[log[c] + log[v]]
            : 0;
      }
    }
  }
};

const Tables g;

}  // namespace

extern "C" {

uint8_t tpudfs_gf256_mul(uint8_t a, uint8_t b) { return g.mul[a][b]; }

// acc[i] ^= c * in[i] for i in [0, len). The RS inner loop.
void tpudfs_gf256_mul_slice(uint8_t c, const uint8_t* in, size_t len,
                            uint8_t* acc) {
  if (c == 0) return;
  if (c == 1) {
    for (size_t i = 0; i < len; i++) acc[i] ^= in[i];
    return;
  }
  const uint8_t* row = g.mul[c];
  size_t i = 0;
  // Unrolled by 8 so the compiler can vectorize the gather-free XOR tail;
  // the table gather itself is the bottleneck (no PSHUFB without intrinsics).
  for (; i + 8 <= len; i += 8) {
    acc[i] ^= row[in[i]];
    acc[i + 1] ^= row[in[i + 1]];
    acc[i + 2] ^= row[in[i + 2]];
    acc[i + 3] ^= row[in[i + 3]];
    acc[i + 4] ^= row[in[i + 4]];
    acc[i + 5] ^= row[in[i + 5]];
    acc[i + 6] ^= row[in[i + 6]];
    acc[i + 7] ^= row[in[i + 7]];
  }
  for (; i < len; i++) acc[i] ^= row[in[i]];
}

// out[r] = xor_c mat[r*cols + c] * shards[c], each shard `shard_len` bytes.
void tpudfs_gf256_matmul(const uint8_t* mat, size_t rows, size_t cols,
                         const uint8_t* const* shards, size_t shard_len,
                         uint8_t* const* out) {
  for (size_t r = 0; r < rows; r++) {
    std::memset(out[r], 0, shard_len);
    for (size_t c = 0; c < cols; c++)
      tpudfs_gf256_mul_slice(mat[r * cols + c], shards[c], shard_len, out[r]);
  }
}

}  // extern "C"
