// CRC-64/NVME — slice-by-8 implementation.
//
// Needed for AWS flexible-checksum trailers: modern AWS SDKs (including the
// C++ SDK behind pyarrow's S3FileSystem) default to sending uploads as
// aws-chunked streams with a trailing `x-amz-checksum-crc64nvme`, so the S3
// gateway must compute this CRC to validate upload integrity end-to-end.
//
// Parameters (CRC-64/NVME, a.k.a. CRC-64/Rocksoft): reflected polynomial
// 0x9A6C9329AC4BC9B5, init 0xFFFFFFFFFFFFFFFF, refin/refout, xorout
// 0xFFFFFFFFFFFFFFFF. Check("123456789") = 0xAE8B14860A799888.
//
// Exported C ABI (used from Python via ctypes, tpudfs/common/native.py):
//   uint64_t tpudfs_crc64nvme(uint64_t crc, const uint8_t* buf, size_t len);

#include <cstddef>
#include <cstdint>

namespace {

constexpr uint64_t kPoly64 = 0x9A6C9329AC4BC9B5ull;

struct Tables64 {
  uint64_t t[8][256];
  Tables64() {
    for (uint64_t i = 0; i < 256; i++) {
      uint64_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ kPoly64 : c >> 1;
      t[0][i] = c;
    }
    for (uint64_t i = 0; i < 256; i++) {
      uint64_t c = t[0][i];
      for (int s = 1; s < 8; s++) {
        c = t[0][c & 0xff] ^ (c >> 8);
        t[s][i] = c;
      }
    }
  }
};

const Tables64 g_tables64;

inline uint64_t crc64_update(uint64_t crc, const uint8_t* buf, size_t len) {
  const uint64_t(*t)[256] = g_tables64.t;
  while (len && (reinterpret_cast<uintptr_t>(buf) & 7)) {
    crc = t[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
    len--;
  }
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, buf, 8);
#if __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
    word = __builtin_bswap64(word);
#endif
    word ^= crc;
    crc = t[7][word & 0xff] ^ t[6][(word >> 8) & 0xff] ^
          t[5][(word >> 16) & 0xff] ^ t[4][(word >> 24) & 0xff] ^
          t[3][(word >> 32) & 0xff] ^ t[2][(word >> 40) & 0xff] ^
          t[1][(word >> 48) & 0xff] ^ t[0][(word >> 56) & 0xff];
    buf += 8;
    len -= 8;
  }
  while (len--) crc = t[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
  return crc;
}

}  // namespace

extern "C" {

// Incremental CRC-64/NVME. Pass crc=0 for a fresh checksum; pre/post
// inversion is handled internally.
uint64_t tpudfs_crc64nvme(uint64_t crc, const uint8_t* buf, size_t len) {
  return ~crc64_update(~crc, buf, len);
}

}  // extern "C"
