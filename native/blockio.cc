// Native block I/O engine: fused checksum+durable-write and pread+verify.
//
// TPU-host twin of the reference's Rust hot I/O (write_block_async /
// read_block_async / verify_partial_read, dfs/chunkserver/src/
// chunkserver.rs:192-351). One ctypes call per block operation: the GIL is
// released for the whole open/CRC/write/fsync/rename (or pread/verify)
// sequence instead of bouncing between Python-level read, numpy CRC, and
// os.* syscalls.
//
// Sidecar layout must match tpudfs/chunkserver/blockstore.py exactly:
//   <4sHHII little-endian: magic "TPUM", version=1, reserved, chunk_size,
//   count> followed by count little-endian u32 chunk CRCs.
//
// Exported C ABI (loaded in tpudfs/common/native.py):
//   int64_t tpudfs_block_write(const char* data_path, const char* meta_path,
//                              const uint8_t* data, uint64_t len,
//                              uint32_t chunk, uint32_t* out_crcs);
//     -> number of chunks, or -errno on I/O failure.
//   int64_t tpudfs_block_read_verify(const char* data_path,
//                                    const char* meta_path, uint64_t offset,
//                                    uint64_t length, uint8_t* out,
//                                    int verify, uint32_t expected_chunk);
//     -> bytes copied into out, TPUDFS_EBADMETA (-200001) on malformed or
//        chunk-size-mismatched sidecars, TPUDFS_ECORRUPT (-200002) on
//        checksum mismatch, TPUDFS_ENOMETA (-200003) when the sidecar file
//        is absent, or -errno on I/O failure. expected_chunk=0 skips the
//        store-chunk-size cross-check.
//   int64_t tpudfs_block_write_staged(...same as tpudfs_block_write...);
//     -> writes data_path/meta_path EXACTLY AS GIVEN, no fsync/rename —
//        group-commit staging: the caller passes its own per-writer tmp
//        paths (unique names, so concurrent same-block stagers can never
//        truncate each other) and publishes with renames + tpudfs_syncfs.
//   int64_t tpudfs_syncfs(const char* path);
//     -> syncfs(2) on the filesystem containing path (one syscall makes a
//        whole staged batch durable), or -errno.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

extern "C" uint32_t tpudfs_crc32c(uint32_t crc, const uint8_t* buf,
                                  size_t len);

namespace {

constexpr int64_t kBadMeta = -200001;
constexpr int64_t kCorrupt = -200002;
constexpr int64_t kNoMeta = -200003;   // sidecar file absent
constexpr char kMagic[4] = {'T', 'P', 'U', 'M'};
constexpr uint16_t kVersion = 1;
constexpr size_t kHeader = 16;  // 4s + u16 + u16 + u32 + u32

// Write whole buffer to exactly `tmp`; fsync iff `durable`.
int64_t write_tmp(const std::string& tmp, const uint8_t* data, uint64_t len,
                  bool durable) {
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = ::write(fd, data + done, len - done);
    if (n < 0) {
      if (errno == EINTR) continue;
      int e = errno;
      ::close(fd);
      ::unlink(tmp.c_str());
      return -e;
    }
    done += static_cast<uint64_t>(n);
  }
  if (durable && ::fsync(fd) != 0) {
    int e = errno;
    ::close(fd);
    ::unlink(tmp.c_str());
    return -e;
  }
  ::close(fd);
  return 0;
}

// Durable publish: write whole buffer to <path>.tmp, fsync, rename.
int64_t write_durable(const std::string& path, const uint8_t* data,
                      uint64_t len) {
  std::string tmp = path + ".tmp";
  int64_t rc = write_tmp(tmp, data, len, /*durable=*/true);
  if (rc != 0) return rc;
  if (::rename(tmp.c_str(), path.c_str()) != 0) return -errno;
  return 0;
}

void put_u16(uint8_t* p, uint16_t v) {
  p[0] = v & 0xff;
  p[1] = (v >> 8) & 0xff;
}
void put_u32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xff;
  p[1] = (v >> 8) & 0xff;
  p[2] = (v >> 16) & 0xff;
  p[3] = (v >> 24) & 0xff;
}
uint32_t get_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

}  // namespace

namespace {

int64_t block_write_impl(const char* data_path, const char* meta_path,
                         const uint8_t* data, uint64_t len, uint32_t chunk,
                         uint32_t* out_crcs, bool staged) {
  if (chunk == 0) return kBadMeta;
  uint64_t n = (len + chunk - 1) / chunk;
  std::vector<uint8_t> meta(kHeader + n * 4);
  std::memcpy(meta.data(), kMagic, 4);
  put_u16(meta.data() + 4, kVersion);
  put_u16(meta.data() + 6, 0);
  put_u32(meta.data() + 8, chunk);
  put_u32(meta.data() + 12, static_cast<uint32_t>(n));
  for (uint64_t i = 0; i < n; i++) {
    uint64_t off = i * chunk;
    uint64_t clen = (off + chunk <= len) ? chunk : len - off;
    uint32_t c = tpudfs_crc32c(0, data + off, clen);
    put_u32(meta.data() + kHeader + i * 4, c);
    if (out_crcs) out_crcs[i] = c;
  }
  int64_t rc;
  if (staged) {
    rc = write_tmp(data_path, data, len, /*durable=*/false);
    if (rc != 0) return rc;
    rc = write_tmp(meta_path, meta.data(), meta.size(), /*durable=*/false);
  } else {
    rc = write_durable(data_path, data, len);
    if (rc != 0) return rc;
    rc = write_durable(meta_path, meta.data(), meta.size());
  }
  if (rc != 0) return rc;
  return static_cast<int64_t>(n);
}

// Fused pread+CRC of one whole block file: reads up to `stride` bytes
// into dst in 256 KiB slices, folding the CRC32C over each slice while it
// is still cache-hot (a separate checksum pass would re-read from DRAM).
// Shared by tpudfs_blocks_read_crc and the sweep pump so the two read
// paths stay bit-identical by construction. On success *size_out = bytes
// read and *crc_out their CRC; on failure *size_out = -errno, *crc_out=0.
void read_block_crc_fused(const char* path, uint8_t* dst, uint64_t stride,
                          int64_t* size_out, uint32_t* crc_out) {
  constexpr uint64_t kSlice = 256 * 1024;
  *crc_out = 0;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) {
    *size_out = -errno;
    return;
  }
  uint64_t done = 0;
  uint32_t c = 0;
  int64_t err = 0;
  while (done < stride) {
    uint64_t want = stride - done;
    if (want > kSlice) want = kSlice;
    ssize_t r = ::pread(fd, dst + done, want, done);
    if (r < 0) {
      if (errno == EINTR) continue;
      err = -errno;
      break;
    }
    if (r == 0) break;  // EOF: block shorter than stride
    c = tpudfs_crc32c(c, dst + done, static_cast<uint64_t>(r));
    done += static_cast<uint64_t>(r);
  }
  ::close(fd);
  if (err != 0) {
    *size_out = err;
  } else {
    *size_out = static_cast<int64_t>(done);
    *crc_out = c;
  }
}

}  // namespace

extern "C" {

int64_t tpudfs_block_write(const char* data_path, const char* meta_path,
                           const uint8_t* data, uint64_t len, uint32_t chunk,
                           uint32_t* out_crcs) {
  return block_write_impl(data_path, meta_path, data, len, chunk, out_crcs,
                          /*staged=*/false);
}

int64_t tpudfs_block_write_staged(const char* data_path,
                                  const char* meta_path, const uint8_t* data,
                                  uint64_t len, uint32_t chunk,
                                  uint32_t* out_crcs) {
  return block_write_impl(data_path, meta_path, data, len, chunk, out_crcs,
                          /*staged=*/true);
}

// Batched unverified reads: pread N whole block files into one contiguous
// caller buffer (slot i at out + i*stride), releasing the GIL for the WHOLE
// batch — one ctypes call replaces N rounds of Python open/fstat/pread plus
// N thread-pool hops. Verification is the caller's business: the TPU read
// path checks the on-device CRC fold against the recorded whole-block
// checksum, so a host-side CRC pass here would be redundant work on the
// single bench core. sizes[i] = bytes read, or -errno for that slot (other
// slots still proceed). Returns the number of slots read without error.
int64_t tpudfs_blocks_read(const char** paths, uint64_t n, uint64_t stride,
                           uint8_t* out, int64_t* sizes) {
  int64_t ok = 0;
  for (uint64_t i = 0; i < n; i++) {
    uint8_t* dst = out + i * stride;
    int fd = ::open(paths[i], O_RDONLY);
    if (fd < 0) {
      sizes[i] = -errno;
      continue;
    }
    uint64_t done = 0;
    int64_t err = 0;
    while (done < stride) {
      ssize_t r = ::pread(fd, dst + done, stride - done, done);
      if (r < 0) {
        if (errno == EINTR) continue;
        err = -errno;
        break;
      }
      if (r == 0) break;  // EOF: block shorter than stride
      done += static_cast<uint64_t>(r);
    }
    ::close(fd);
    if (err != 0) {
      sizes[i] = err;
    } else {
      sizes[i] = static_cast<int64_t>(done);
      ok++;
    }
  }
  return ok;
}

// Fused variant: additionally computes each slot's WHOLE-block CRC32C
// (hardware-accelerated where available) so a host-verified batched read is
// one native call — the CPU-fallback twin of the on-device batch CRC fold
// (the caller compares crcs[i] against the CompleteFile-recorded checksum).
// The CRC is folded INTO the pread loop at 256 KiB slices, so the checksum
// pass reads L2-hot data instead of making a second trip through DRAM
// (measured on the bench host: two-pass 4.6 GB/s -> fused ~6 GB/s).
int64_t tpudfs_blocks_read_crc(const char** paths, uint64_t n,
                               uint64_t stride, uint8_t* out, int64_t* sizes,
                               uint32_t* crcs) {
  int64_t ok = 0;
  for (uint64_t i = 0; i < n; i++) {
    read_block_crc_fused(paths[i], out + i * stride, stride, &sizes[i],
                         &crcs[i]);
    if (sizes[i] >= 0) ok++;
  }
  return ok;
}

int64_t tpudfs_syncfs(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  int rc = ::syncfs(fd);
  int e = errno;
  ::close(fd);
  return rc == 0 ? 0 : -e;
}

int64_t tpudfs_block_read_verify(const char* data_path, const char* meta_path,
                                 uint64_t offset, uint64_t length,
                                 uint8_t* out, int verify,
                                 uint32_t expected_chunk) {
  int fd = ::open(data_path, O_RDONLY);
  if (fd < 0) return -errno;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  uint64_t total = static_cast<uint64_t>(st.st_size);
  if (offset >= total) {
    ::close(fd);
    return 0;
  }
  if (offset + length > total) length = total - offset;

  if (!verify) {
    uint64_t done = 0;
    while (done < length) {
      ssize_t n = ::pread(fd, out + done, length - done, offset + done);
      if (n < 0) {
        if (errno == EINTR) continue;
        int e = errno;
        ::close(fd);
        return -e;
      }
      if (n == 0) break;
      done += static_cast<uint64_t>(n);
    }
    ::close(fd);
    return static_cast<int64_t>(done);
  }

  // Verified read: load the sidecar, pread the chunk-aligned span covering
  // [offset, offset+length), CRC each affected chunk, then hand back the
  // requested subrange (reference verify_partial_read chunkserver.rs:296-351).
  int mfd = ::open(meta_path, O_RDONLY);
  if (mfd < 0) {
    int e = errno;
    ::close(fd);
    return e == ENOENT ? kNoMeta : -e;
  }
  struct stat mst;
  if (::fstat(mfd, &mst) != 0 ||
      static_cast<size_t>(mst.st_size) < kHeader) {
    ::close(mfd);
    ::close(fd);
    return kBadMeta;
  }
  std::vector<uint8_t> meta(mst.st_size);
  {
    uint64_t done = 0;
    while (done < meta.size()) {
      ssize_t n = ::pread(mfd, meta.data() + done, meta.size() - done, done);
      if (n <= 0) {
        if (n < 0 && errno == EINTR) continue;
        ::close(mfd);
        ::close(fd);
        return kBadMeta;
      }
      done += static_cast<uint64_t>(n);
    }
  }
  ::close(mfd);
  if (std::memcmp(meta.data(), kMagic, 4) != 0 ||
      (meta[4] | (meta[5] << 8)) != kVersion)
    { ::close(fd); return kBadMeta; }
  uint32_t chunk = get_u32(meta.data() + 8);
  uint32_t count = get_u32(meta.data() + 12);
  if (chunk == 0 || meta.size() < kHeader + static_cast<size_t>(count) * 4)
    { ::close(fd); return kBadMeta; }
  if (expected_chunk != 0 && chunk != expected_chunk)
    { ::close(fd); return kBadMeta; }  // mismatched store chunk size

  uint64_t first = offset / chunk;
  uint64_t last = (offset + length - 1) / chunk;
  if (last >= count) {
    ::close(fd);
    return kBadMeta;
  }
  uint64_t span_off = first * chunk;
  uint64_t span_len = (last - first + 1) * chunk;
  if (span_off + span_len > total) span_len = total - span_off;
  std::vector<uint8_t> span(span_len);
  {
    uint64_t done = 0;
    while (done < span_len) {
      ssize_t n =
          ::pread(fd, span.data() + done, span_len - done, span_off + done);
      if (n < 0) {
        if (errno == EINTR) continue;
        int e = errno;
        ::close(fd);
        return -e;
      }
      if (n == 0) break;
      done += static_cast<uint64_t>(n);
    }
    span_len = done;
  }
  ::close(fd);
  for (uint64_t i = first; i <= last; i++) {
    uint64_t off = (i - first) * chunk;
    if (off >= span_len) return kCorrupt;  // shorter than sidecar says
    uint64_t clen = (off + chunk <= span_len) ? chunk : span_len - off;
    uint32_t want = get_u32(meta.data() + kHeader + i * 4);
    if (tpudfs_crc32c(0, span.data() + off, clen) != want) return kCorrupt;
  }
  uint64_t rel = offset - span_off;
  if (rel >= span_len) return 0;
  uint64_t avail = span_len - rel;
  if (length > avail) length = avail;
  std::memcpy(out, span.data() + rel, length);
  return static_cast<int64_t>(length);
}

}  // extern "C"

// ---------------------------------------------------------- sweep pump
//
// The steady-state infeed loop, native end-to-end (round-4 verdict: the
// per-round Python between tpudfs_blocks_read and device_put was 30-50%
// of the read window on the one-core bench host). Python hands the WHOLE
// sweep over once — block paths, a ring of round-sized buffers, and the
// per-block sizes/crcs result arrays — and a producer thread fills round
// after round (fused pread+CRC, same slices as tpudfs_blocks_read_crc)
// ahead of the consumer. Python's per-round work shrinks to: one
// (usually already-satisfied) wait, one device_put of the filled buffer,
// one release. All waits release the GIL (ctypes), so the producer
// overlaps the device copies even on one core — no executor hops, no
// futures, no per-block staging.

#include <atomic>
#include <condition_variable>
#include <mutex>
#include <thread>

namespace {

struct SweepPump {
  std::vector<std::string> paths;
  uint64_t stride = 0;        // bytes per block slot
  uint64_t round_blocks = 0;  // slots per round
  std::vector<uint8_t*> bufs; // ring of round-sized buffers (caller-owned)
  int64_t* sizes = nullptr;   // n entries (caller-owned)
  uint32_t* crcs = nullptr;   // n entries (caller-owned)
  uint64_t n = 0;
  int64_t nrounds = 0;
  int64_t produced = 0;   // rounds fully filled
  int64_t released = 0;   // lowest round whose buffer is NOT yet released
  std::vector<bool> release_flags;
  bool stopping = false;
  std::mutex mu;
  std::condition_variable cv_producer, cv_consumer;
  std::thread worker;

  void run() {
    for (int64_t r = 0; r < nrounds; r++) {
      {
        // Wait until round r's ring buffer is free again (the consumer
        // released round r - nbufs).
        std::unique_lock<std::mutex> lk(mu);
        cv_producer.wait(lk, [&] {
          return stopping ||
                 r - released < static_cast<int64_t>(bufs.size());
        });
        if (stopping) return;
      }
      uint8_t* buf = bufs[r % bufs.size()];
      uint64_t lo = static_cast<uint64_t>(r) * round_blocks;
      uint64_t hi = lo + round_blocks;
      if (hi > n) hi = n;
      for (uint64_t i = lo; i < hi; i++) {
        // Same fused pread+CRC as tpudfs_blocks_read_crc, by construction.
        read_block_crc_fused(paths[i].c_str(), buf + (i - lo) * stride,
                             stride, &sizes[i], &crcs[i]);
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        produced = r + 1;
      }
      cv_consumer.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// -> opaque handle; caller keeps paths/bufs/sizes/crcs alive until
//    tpudfs_sweep_stop. Round r fills bufs[r % nbufs]; slot i of the
//    sweep lands at offset ((i - r*round_blocks) * stride) of its round's
//    buffer, with sizes[i] = bytes read (or -errno) and crcs[i] its
//    whole-block CRC32C.
int64_t tpudfs_sweep_start(const char** paths, uint64_t n, uint64_t stride,
                           uint64_t round_blocks, uint8_t** bufs,
                           uint64_t nbufs, int64_t* sizes, uint32_t* crcs) {
  if (n == 0 || round_blocks == 0 || nbufs == 0) return 0;
  auto* p = new SweepPump();
  p->paths.reserve(n);
  for (uint64_t i = 0; i < n; i++) p->paths.emplace_back(paths[i]);
  p->stride = stride;
  p->round_blocks = round_blocks;
  p->bufs.assign(bufs, bufs + nbufs);
  p->sizes = sizes;
  p->crcs = crcs;
  p->n = n;
  p->nrounds = static_cast<int64_t>((n + round_blocks - 1) / round_blocks);
  p->release_flags.assign(static_cast<size_t>(p->nrounds), false);
  p->worker = std::thread([p] { p->run(); });
  return reinterpret_cast<int64_t>(p);
}

// Blocks (GIL released by ctypes) until round_idx is filled. Returns the
// number of slots in that round, or -1 if the pump is stopping.
int64_t tpudfs_sweep_wait(int64_t handle, int64_t round_idx) {
  auto* p = reinterpret_cast<SweepPump*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_consumer.wait(lk, [&] {
    return p->stopping || p->produced > round_idx;
  });
  if (p->stopping && p->produced <= round_idx) return -1;
  uint64_t lo = static_cast<uint64_t>(round_idx) * p->round_blocks;
  uint64_t hi = lo + p->round_blocks;
  if (hi > p->n) hi = p->n;
  return static_cast<int64_t>(hi - lo);
}

// Consumer is done with round_idx's buffer; the producer may refill it.
// Rounds may be released out of order; the producer gate advances over
// the contiguous released prefix.
void tpudfs_sweep_release(int64_t handle, int64_t round_idx) {
  auto* p = reinterpret_cast<SweepPump*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    if (round_idx >= 0 && round_idx < p->nrounds)
      p->release_flags[static_cast<size_t>(round_idx)] = true;
    while (p->released < p->nrounds &&
           p->release_flags[static_cast<size_t>(p->released)])
      p->released++;
  }
  p->cv_producer.notify_all();
}

void tpudfs_sweep_stop(int64_t handle) {
  auto* p = reinterpret_cast<SweepPump*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stopping = true;
  }
  p->cv_producer.notify_all();
  p->cv_consumer.notify_all();
  if (p->worker.joinable()) p->worker.join();
  delete p;
}

}  // extern "C"
