// CRC32C (Castagnoli, reflected poly 0x82F63B78) — slice-by-8 implementation.
//
// TPU-native twin of the reference's checksum path (the reference computes
// per-512-byte-chunk CRC32C sidecars in dfs/chunkserver/src/chunkserver.rs:182-190
// with the crc32fast crate). This library is the host-side hot path; the device
// twin is tpudfs/tpu/crc32c_pallas.py which must match bit-exactly.
//
// Exported C ABI (used from Python via ctypes, tpudfs/common/native.py):
//   uint32_t tpudfs_crc32c(uint32_t crc, const uint8_t* buf, size_t len);
//   void     tpudfs_crc32c_chunks(const uint8_t* buf, size_t len,
//                                 size_t chunk, uint32_t* out);
//   void     tpudfs_crc32c_contrib_table(uint32_t* out, size_t positions);

#include <cstddef>
#include <cstdint>

namespace {

constexpr uint32_t kPoly = 0x82F63B78u;  // reflected 0x1EDC6F41

struct Tables {
  uint32_t t[8][256];
  Tables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ kPoly : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = t[0][i];
      for (int s = 1; s < 8; s++) {
        c = t[0][c & 0xff] ^ (c >> 8);
        t[s][i] = c;
      }
    }
  }
};

const Tables g_tables;

inline uint32_t crc_update_sw(uint32_t crc, const uint8_t* buf, size_t len) {
  const uint32_t(*t)[256] = g_tables.t;
  // Head: align to 8 bytes.
  while (len && (reinterpret_cast<uintptr_t>(buf) & 7)) {
    crc = t[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
    len--;
  }
  // Body: slice-by-8.
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, buf, 8);
#if __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
    word = __builtin_bswap64(word);
#endif
    word ^= crc;
    crc = t[7][word & 0xff] ^ t[6][(word >> 8) & 0xff] ^
          t[5][(word >> 16) & 0xff] ^ t[4][(word >> 24) & 0xff] ^
          t[3][(word >> 32) & 0xff] ^ t[2][(word >> 40) & 0xff] ^
          t[1][(word >> 48) & 0xff] ^ t[0][(word >> 56) & 0xff];
    buf += 8;
    len -= 8;
  }
  // Tail.
  while (len--) crc = t[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
// Hardware CRC32C: SSE4.2's crc32 instruction IS the Castagnoli polynomial,
// ~10x the slice-by-8 table walk — on the single-core bench host the
// checksum passes (write path, verified reads, fused batch reads) stop
// owning the CPU. Runtime-dispatched so the same .so runs anywhere.
__attribute__((target("sse4.2")))
uint32_t crc_update_hw(uint32_t crc, const uint8_t* buf, size_t len) {
  uint64_t c = crc;
  while (len && (reinterpret_cast<uintptr_t>(buf) & 7)) {
    c = __builtin_ia32_crc32qi(static_cast<uint32_t>(c), *buf++);
    len--;
  }
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, buf, 8);
    c = __builtin_ia32_crc32di(c, word);
    buf += 8;
    len -= 8;
  }
  while (len--)
    c = __builtin_ia32_crc32qi(static_cast<uint32_t>(c), *buf++);
  return static_cast<uint32_t>(c);
}

const bool g_have_hw = __builtin_cpu_supports("sse4.2");

inline uint32_t crc_update(uint32_t crc, const uint8_t* buf, size_t len) {
  return g_have_hw ? crc_update_hw(crc, buf, len)
                   : crc_update_sw(crc, buf, len);
}
#else
inline uint32_t crc_update(uint32_t crc, const uint8_t* buf, size_t len) {
  return crc_update_sw(crc, buf, len);
}
#endif

}  // namespace

extern "C" {

// Incremental CRC32C. Pass crc=0 for a fresh checksum; the pre/post inversion
// is handled internally (matches crc32fast / RFC 3720 semantics).
uint32_t tpudfs_crc32c(uint32_t crc, const uint8_t* buf, size_t len) {
  return ~crc_update(~crc, buf, len);
}

// Per-chunk CRC32C: out[i] = crc32c(buf[i*chunk : min((i+1)*chunk, len)]).
// Mirrors the reference's calculate_checksums (chunkserver.rs:182-190) which
// checksums each 512-byte chunk independently.
void tpudfs_crc32c_chunks(const uint8_t* buf, size_t len, size_t chunk,
                          uint32_t* out) {
  size_t n = (len + chunk - 1) / chunk;
  for (size_t i = 0; i < n; i++) {
    size_t off = i * chunk;
    size_t clen = (off + chunk <= len) ? chunk : len - off;
    out[i] = ~crc_update(0xFFFFFFFFu, buf + off, clen);
  }
}

// Positional contribution table for the vectorized (Pallas / numpy) twin:
// out[(positions-1-i)*256 + b] is the CRC register contribution of byte value
// b at distance i from the END of a `positions`-byte message, EXCLUDING the
// init/final inversions. A chunk CRC is then
//   ~( xor_i table[i][data[i]] ^ inv_contrib )
// where inv_contrib is the contribution of the initial 0xFFFFFFFF register,
// returned in out[positions*256] (one extra slot).
void tpudfs_crc32c_contrib_table(uint32_t* out, size_t positions) {
  // Contribution of byte b at position i (0-based from message start) in a
  // message of `positions` bytes, all other bytes zero, init register zero:
  // run crc_update over the one-hot message.
  for (size_t i = 0; i < positions; i++) {
    for (uint32_t b = 0; b < 256; b++) {
      uint32_t crc = 0;
      // Process byte b, then (positions-1-i) zero bytes.
      crc = g_tables.t[0][(crc ^ b) & 0xff] ^ (crc >> 8);
      for (size_t z = i + 1; z < positions; z++)
        crc = g_tables.t[0][crc & 0xff] ^ (crc >> 8);
      out[i * 256 + b] = crc;
    }
  }
  // Contribution of the init register 0xFFFFFFFF across `positions` bytes:
  // feed `positions` zero bytes starting from register 0xFFFFFFFF.
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t z = 0; z < positions; z++)
    crc = g_tables.t[0][crc & 0xff] ^ (crc >> 8);
  out[positions * 256] = crc;
}

}  // extern "C"
