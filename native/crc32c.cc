// CRC32C (Castagnoli, reflected poly 0x82F63B78) — slice-by-8 implementation.
//
// TPU-native twin of the reference's checksum path (the reference computes
// per-512-byte-chunk CRC32C sidecars in dfs/chunkserver/src/chunkserver.rs:182-190
// with the crc32fast crate). This library is the host-side hot path; the device
// twin is tpudfs/tpu/crc32c_pallas.py which must match bit-exactly.
//
// Exported C ABI (used from Python via ctypes, tpudfs/common/native.py):
//   uint32_t tpudfs_crc32c(uint32_t crc, const uint8_t* buf, size_t len);
//   void     tpudfs_crc32c_chunks(const uint8_t* buf, size_t len,
//                                 size_t chunk, uint32_t* out);
//   void     tpudfs_crc32c_contrib_table(uint32_t* out, size_t positions);

#include <cstddef>
#include <cstdint>

namespace {

constexpr uint32_t kPoly = 0x82F63B78u;  // reflected 0x1EDC6F41

struct Tables {
  uint32_t t[8][256];
  Tables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ kPoly : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = t[0][i];
      for (int s = 1; s < 8; s++) {
        c = t[0][c & 0xff] ^ (c >> 8);
        t[s][i] = c;
      }
    }
  }
};

const Tables g_tables;

inline uint32_t crc_update_sw(uint32_t crc, const uint8_t* buf, size_t len) {
  const uint32_t(*t)[256] = g_tables.t;
  // Head: align to 8 bytes.
  while (len && (reinterpret_cast<uintptr_t>(buf) & 7)) {
    crc = t[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
    len--;
  }
  // Body: slice-by-8.
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, buf, 8);
#if __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
    word = __builtin_bswap64(word);
#endif
    word ^= crc;
    crc = t[7][word & 0xff] ^ t[6][(word >> 8) & 0xff] ^
          t[5][(word >> 16) & 0xff] ^ t[4][(word >> 24) & 0xff] ^
          t[3][(word >> 32) & 0xff] ^ t[2][(word >> 40) & 0xff] ^
          t[1][(word >> 48) & 0xff] ^ t[0][(word >> 56) & 0xff];
    buf += 8;
    len -= 8;
  }
  // Tail.
  while (len--) crc = t[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
// Hardware CRC32C: SSE4.2's crc32 instruction IS the Castagnoli polynomial,
// ~10x the slice-by-8 table walk — on the single-core bench host the
// checksum passes (write path, verified reads, fused batch reads) stop
// owning the CPU. Runtime-dispatched so the same .so runs anywhere.
__attribute__((target("sse4.2")))
uint32_t crc_update_hw(uint32_t crc, const uint8_t* buf, size_t len) {
  uint64_t c = crc;
  while (len && (reinterpret_cast<uintptr_t>(buf) & 7)) {
    c = __builtin_ia32_crc32qi(static_cast<uint32_t>(c), *buf++);
    len--;
  }
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, buf, 8);
    c = __builtin_ia32_crc32di(c, word);
    buf += 8;
    len -= 8;
  }
  while (len--)
    c = __builtin_ia32_crc32qi(static_cast<uint32_t>(c), *buf++);
  return static_cast<uint32_t>(c);
}

const bool g_have_hw = __builtin_cpu_supports("sse4.2");

// ---- GF(2) shift-combine: raw-register semantics -------------------------
// reg(r, M1||M2) = shift(reg(r, M1), len(M2)) ^ reg(0, M2) — CRC is linear
// over GF(2), so a buffer can be checksummed as three independent
// instruction streams (hiding the crc32 instruction's 3-cycle latency,
// which serial chaining pays in full) and recombined with the zlib
// crc32_combine ladder. POLY is reflected CRC32C (Castagnoli).

uint32_t gf2_matrix_times(const uint32_t* mat, uint32_t vec) {
  uint32_t sum = 0;
  int i = 0;
  while (vec) {
    if (vec & 1) sum ^= mat[i];
    vec >>= 1;
    i++;
  }
  return sum;
}

void gf2_matrix_square(uint32_t* square, const uint32_t* mat) {
  for (int n = 0; n < 32; n++) square[n] = gf2_matrix_times(mat, mat[n]);
}

// shift(crc, len): the raw CRC register advanced over `len` zero bytes
// (zlib crc32_combine's ladder, reflected CRC32C polynomial).
uint32_t crc_shift(uint32_t crc, size_t len) {
  uint32_t even[32], odd[32];
  if (len == 0) return crc;
  odd[0] = 0x82F63B78u;  // reflected CRC32C poly: shift-by-one-bit operator
  uint32_t row = 1;
  for (int n = 1; n < 32; n++) {
    odd[n] = row;
    row <<= 1;
  }
  gf2_matrix_square(even, odd);  // shift by 2 bits
  gf2_matrix_square(odd, even);  // shift by 4 bits
  do {
    gf2_matrix_square(even, odd);  // 8, 32, 128... bit operators
    if (len & 1) crc = gf2_matrix_times(even, crc);
    len >>= 1;
    if (!len) break;
    gf2_matrix_square(odd, even);
    if (len & 1) crc = gf2_matrix_times(odd, crc);
    len >>= 1;
  } while (len);
  return crc;
}

// Cached shift OPERATOR (matrix column per register bit) for a fixed lane
// length — the hot loops checksum a fixed stride, so the ladder runs once.
struct ShiftCache {
  size_t len = 0;
  uint32_t mat[32];
};
thread_local ShiftCache g_shift_cache;

const uint32_t* shift_matrix(size_t len) {
  if (g_shift_cache.len != len) {
    for (int i = 0; i < 32; i++)
      g_shift_cache.mat[i] = crc_shift(1u << i, len);
    g_shift_cache.len = len;
  }
  return g_shift_cache.mat;
}

#if defined(__x86_64__)
// 3-lane interleaved hardware CRC: the serial crc32di chain retires 8
// bytes per ~3 cycles (latency-bound); three independent chains fill the
// pipeline (~2.5x measured on the bench host), recombined with two cached
// shift applications. Raw-register semantics like crc_update_hw.
__attribute__((target("sse4.2")))
uint32_t crc_update_hw_3way(uint32_t crc, const uint8_t* buf, size_t len) {
  size_t lb = (len / 3) & ~static_cast<size_t>(7);
  if (lb < 2048) return crc_update_hw(crc, buf, len);
  size_t la = len - 2 * lb;  // lane A takes the remainder (>= lb)
  const uint8_t* pa = buf;
  const uint8_t* pb = buf + la;
  const uint8_t* pc = buf + la + lb;
  uint64_t ca = crc, cb = 0, cc = 0;
  size_t k = lb / 8;
  for (size_t i = 0; i < k; i++) {
    uint64_t wa, wb, wc;
    __builtin_memcpy(&wa, pa + i * 8, 8);
    __builtin_memcpy(&wb, pb + i * 8, 8);
    __builtin_memcpy(&wc, pc + i * 8, 8);
    ca = __builtin_ia32_crc32di(ca, wa);
    cb = __builtin_ia32_crc32di(cb, wb);
    cc = __builtin_ia32_crc32di(cc, wc);
  }
  // Lane A's remainder (la - 8k bytes) continues its own chain.
  ca = crc_update_hw(static_cast<uint32_t>(ca), pa + k * 8, la - k * 8);
  const uint32_t* m = shift_matrix(lb);
  uint32_t r = gf2_matrix_times(m, static_cast<uint32_t>(ca)) ^
               static_cast<uint32_t>(cb);
  return gf2_matrix_times(m, r) ^ static_cast<uint32_t>(cc);
}
#endif

inline uint32_t crc_update(uint32_t crc, const uint8_t* buf, size_t len) {
#if defined(__x86_64__)
  if (g_have_hw)
    return len >= 8192 ? crc_update_hw_3way(crc, buf, len)
                       : crc_update_hw(crc, buf, len);
#endif
  return crc_update_sw(crc, buf, len);
}
#else
inline uint32_t crc_update(uint32_t crc, const uint8_t* buf, size_t len) {
  return crc_update_sw(crc, buf, len);
}
#endif

}  // namespace

extern "C" {

// Incremental CRC32C. Pass crc=0 for a fresh checksum; the pre/post inversion
// is handled internally (matches crc32fast / RFC 3720 semantics).
uint32_t tpudfs_crc32c(uint32_t crc, const uint8_t* buf, size_t len) {
  return ~crc_update(~crc, buf, len);
}

// Per-chunk CRC32C: out[i] = crc32c(buf[i*chunk : min((i+1)*chunk, len)]).
// Mirrors the reference's calculate_checksums (chunkserver.rs:182-190) which
// checksums each 512-byte chunk independently.
void tpudfs_crc32c_chunks(const uint8_t* buf, size_t len, size_t chunk,
                          uint32_t* out) {
  size_t n = (len + chunk - 1) / chunk;
  for (size_t i = 0; i < n; i++) {
    size_t off = i * chunk;
    size_t clen = (off + chunk <= len) ? chunk : len - off;
    out[i] = ~crc_update(0xFFFFFFFFu, buf + off, clen);
  }
}

// Positional contribution table for the vectorized (Pallas / numpy) twin:
// out[(positions-1-i)*256 + b] is the CRC register contribution of byte value
// b at distance i from the END of a `positions`-byte message, EXCLUDING the
// init/final inversions. A chunk CRC is then
//   ~( xor_i table[i][data[i]] ^ inv_contrib )
// where inv_contrib is the contribution of the initial 0xFFFFFFFF register,
// returned in out[positions*256] (one extra slot).
void tpudfs_crc32c_contrib_table(uint32_t* out, size_t positions) {
  // Contribution of byte b at position i (0-based from message start) in a
  // message of `positions` bytes, all other bytes zero, init register zero:
  // run crc_update over the one-hot message.
  for (size_t i = 0; i < positions; i++) {
    for (uint32_t b = 0; b < 256; b++) {
      uint32_t crc = 0;
      // Process byte b, then (positions-1-i) zero bytes.
      crc = g_tables.t[0][(crc ^ b) & 0xff] ^ (crc >> 8);
      for (size_t z = i + 1; z < positions; z++)
        crc = g_tables.t[0][crc & 0xff] ^ (crc >> 8);
      out[i * 256 + b] = crc;
    }
  }
  // Contribution of the init register 0xFFFFFFFF across `positions` bytes:
  // feed `positions` zero bytes starting from register 0xFFFFFFFF.
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t z = 0; z < positions; z++)
    crc = g_tables.t[0][crc & 0xff] ^ (crc >> 8);
  out[positions * 256] = crc;
}

}  // extern "C"
