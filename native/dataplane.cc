// Native data-plane engine: the blockport protocol served without Python.
//
// The TPU-host twin of the reference's compiled Rust chunkserver hot path
// (WriteBlock / ReplicateBlock / ReadBlock, dfs/chunkserver/src/
// chunkserver.rs:722-1087) — and the "unwired io_uring pool, done right"
// from SURVEY §2.2: on the single-core bench host the Python asyncio
// handler costs more per 1 MiB hop than the durable write itself, so the
// chunkserver starts this engine (a small threaded TCP server) on its
// blockport and the whole chain — in-flight CRC verify (hardware CRC32C),
// group-committed durable staging, downstream forward, ack aggregation —
// runs in C++. Python keeps every control path: heartbeats, healing,
// recovery, scrubbing, fencing-term distribution, and the gRPC fallback
// handlers (which remain byte-compatible with this engine's on-disk
// format — native/blockio.cc's staged sidecar layout).
//
// Wire protocol: identical to tpudfs/common/blocknet.py —
//   u32 header_len | msgpack(header) | u64 payload_len | payload
// with the "_d" header flag marking a real (possibly empty) data field.
// Methods: WriteBlock, ReplicateBlock (same handling), ReadBlock; others
// answer UNIMPLEMENTED so a client can fall back.
//
// Chain forwarding needs no discovery here: the sender resolves every
// chain member's data port (blocknet probe) and passes "next_data_ports"
// beside "next_servers"; a 0 port means "skip the forward, let the healer
// repair" (same degraded contract as a dead tail).
//
// Python integration (ctypes, tpudfs/common/native.py):
//   int64_t  tpudfs_dataplane_start(host, hot_dir, cold_dir, chunk_size,
//                                   port, cache_blocks,
//                                   srv_cert, srv_key, srv_client_ca,
//                                   out_ca, out_cert, out_key)
//                                   -> handle or -errno (TLS paths may all
//                                   be empty/null = plaintext; unusable
//                                   TLS material fails start, it never
//                                   silently downgrades)
//   int32_t  tpudfs_dataplane_port(handle)
//   void     tpudfs_dataplane_set_term(handle, shard, term) // heartbeats
//   uint64_t tpudfs_dataplane_term(handle, shard)      // learned from reqs
//   int64_t  tpudfs_dataplane_take_terms(handle, buf, cap)
//                                   // "shard\tterm\n" dump, see below
//   int64_t  tpudfs_dataplane_take_bad(handle, buf, cap) // '\n'-joined ids
//   void     tpudfs_dataplane_invalidate(handle, block_id) // cache drop
//   void     tpudfs_dataplane_stats(handle, uint64_t out[6])
//               // writes, reads, forwards, errors, cache_hits, cache_misses
//   void     tpudfs_dataplane_set_qos(handle, cfg, len)
//               // push the QosShedder config (msgpack flat map from
//               // resilience.qos_wire_config) — admission/fair-queue/
//               // rate-limit ladder, weights, jitter seed
//   void     tpudfs_dataplane_qos_stats(handle, uint64_t out[8])
//   int64_t  tpudfs_dataplane_take_qos(handle, buf, cap)
//               // per-tenant counter lines, take_terms contract
//   int64_t  tpudfs_dataplane_stop(handle)
//
// Fencing parity: reference chunkserver.rs:732-743 — requests carrying a
// stale master term are rejected FAILED_PRECONDITION; newer terms are
// learned per shard. Python pushes heartbeat-learned terms in (set_term)
// and drains request-learned terms back out (take_terms, polled from the
// heartbeat loop) so BOTH fencing planes converge — without the drain, a
// deposed master's stale write arriving on the gRPC plane would still be
// accepted until the next master heartbeat taught Python the new term.
//
// LRU block cache: full verified blocks, capacity in blocks (the native
// twin of the Python service's _LruCache, reference chunkserver.rs:67-76
// — without it the engine's hot read path re-reads + re-CRCs the disk on
// every repeated remote read). Writes and corrupt-read findings
// invalidate; Python invalidates through tpudfs_dataplane_invalidate on
// its own delete / tiering-move / recovery paths.

#include <algorithm>
#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <dlfcn.h>
#include <list>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <set>
#include <string>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <chrono>
#include <fcntl.h>
#include <memory>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {
uint32_t tpudfs_crc32c(uint32_t crc, const uint8_t* buf, size_t len);
int64_t tpudfs_block_write_staged(const char* data_path,
                                  const char* meta_path, const uint8_t* data,
                                  uint64_t len, uint32_t chunk,
                                  uint32_t* out_crcs);
int64_t tpudfs_block_read_verify(const char* data_path, const char* meta_path,
                                 uint64_t offset, uint64_t length,
                                 uint8_t* out, int verify,
                                 uint32_t expected_chunk);
int64_t tpudfs_syncfs(const char* path);
}

namespace {

constexpr int64_t kCorrupt = -200002;
constexpr uint64_t kMaxHeader = 1 << 20;
constexpr uint64_t kMaxPayload = 100ull * 1024 * 1024;
// Watermark ack cadence of the streaming write path — must match
// tpudfs/common/writestream.py ACK_EVERY.
constexpr uint64_t kAckEvery = 8;
// Streamed-block ceiling — must match tpudfs/common/writestream.py
// MAX_STREAM_BYTES (the per-frame kMaxPayload cap does not bound the
// whole stream; without this check a native hop would accept streams
// the Python side rejects, and a rogue begin header could stage
// unbounded bytes).
constexpr uint64_t kMaxStreamBytes = 1ull << 30;

// ----------------------------------------------------------- msgpack mini

struct Value {
  enum Kind { NIL, BOOL, INT, FLT, STR, ASTR, AINT } kind = NIL;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;
  std::vector<std::string> astr;
  std::vector<int64_t> aint;
};

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint8_t u8() {
    if (p >= end) { ok = false; return 0; }
    return *p++;
  }
  uint64_t be(int n) {
    uint64_t v = 0;
    for (int k = 0; k < n; k++) v = (v << 8) | u8();
    return v;
  }
  bool bytes(size_t n, std::string* out) {
    if (static_cast<size_t>(end - p) < n) { ok = false; return false; }
    out->assign(reinterpret_cast<const char*>(p), n);
    p += n;
    return true;
  }
};

bool parse_str(Reader& r, std::string* out) {
  uint8_t t = r.u8();
  size_t n;
  if ((t & 0xe0) == 0xa0) n = t & 0x1f;
  else if (t == 0xd9) n = r.be(1);
  else if (t == 0xda) n = r.be(2);
  else if (t == 0xdb) n = r.be(4);
  else if (t == 0xc4) n = r.be(1);   // bin accepted for str slots
  else if (t == 0xc5) n = r.be(2);
  else if (t == 0xc6) n = r.be(4);
  else { r.ok = false; return false; }
  return r.bytes(n, out);
}

bool parse_int(Reader& r, int64_t* out) {
  uint8_t t = r.u8();
  if (t <= 0x7f) { *out = t; return r.ok; }
  if (t >= 0xe0) { *out = static_cast<int8_t>(t); return r.ok; }
  switch (t) {
    case 0xcc: *out = static_cast<int64_t>(r.be(1)); return r.ok;
    case 0xcd: *out = static_cast<int64_t>(r.be(2)); return r.ok;
    case 0xce: *out = static_cast<int64_t>(r.be(4)); return r.ok;
    case 0xcf: *out = static_cast<int64_t>(r.be(8)); return r.ok;
    case 0xd0: *out = static_cast<int8_t>(r.be(1)); return r.ok;
    case 0xd1: *out = static_cast<int16_t>(r.be(2)); return r.ok;
    case 0xd2: *out = static_cast<int32_t>(r.be(4)); return r.ok;
    case 0xd3: *out = static_cast<int64_t>(r.be(8)); return r.ok;
    default: r.ok = false; return false;
  }
}

// Parse one value of the limited shapes our headers use.
bool parse_value(Reader& r, Value* v) {
  if (r.p >= r.end) { r.ok = false; return false; }
  uint8_t t = *r.p;
  if (t == 0xc0) { r.u8(); v->kind = Value::NIL; return true; }
  if (t == 0xc2 || t == 0xc3) {
    r.u8();
    v->kind = Value::BOOL;
    v->b = (t == 0xc3);
    return true;
  }
  if (t == 0xca || t == 0xcb) {
    // float32/float64 — advisory headers like the deadline budget `_db`
    // ride every hop; rejecting them would tear the whole connection.
    r.u8();
    v->kind = Value::FLT;
    if (t == 0xca) {
      uint32_t bits = static_cast<uint32_t>(r.be(4));
      float f32;
      std::memcpy(&f32, &bits, sizeof(f32));
      v->f = f32;
    } else {
      uint64_t bits = r.be(8);
      std::memcpy(&v->f, &bits, sizeof(v->f));
    }
    return r.ok;
  }
  if (t <= 0x7f || t >= 0xcc) {
    if (t <= 0x7f || (t >= 0xcc && t <= 0xd3) || t >= 0xe0) {
      v->kind = Value::INT;
      return parse_int(r, &v->i);
    }
  }
  if ((t & 0xe0) == 0xa0 || t == 0xd9 || t == 0xda || t == 0xdb ||
      t == 0xc4 || t == 0xc5 || t == 0xc6) {
    v->kind = Value::STR;
    return parse_str(r, &v->s);
  }
  size_t n;
  if ((t & 0xf0) == 0x90) { r.u8(); n = t & 0x0f; }
  else if (t == 0xdc) { r.u8(); n = r.be(2); }
  else if (t == 0xdd) { r.u8(); n = r.be(4); }
  else { r.ok = false; return false; }
  // Array of strings or ints (peek first element; empty -> ASTR).
  if (n == 0) { v->kind = Value::ASTR; return true; }
  if (r.p >= r.end) { r.ok = false; return false; }
  uint8_t et = *r.p;
  // Ints are fixint/uintN/intN ONLY — str8-32 (0xd9-0xdb) and bin
  // (0xc4-0xc6) live above 0xcc too and must classify as strings (long
  // FQDN-addressed peers encode as str8).
  if (et <= 0x7f || (et >= 0xcc && et <= 0xd3) || et >= 0xe0) {
    v->kind = Value::AINT;
    v->aint.resize(n);
    for (size_t k = 0; k < n; k++)
      if (!parse_int(r, &v->aint[k])) return false;
    return true;
  }
  v->kind = Value::ASTR;
  v->astr.resize(n);
  for (size_t k = 0; k < n; k++)
    if (!parse_str(r, &v->astr[k])) return false;
  return true;
}

bool parse_header(const uint8_t* buf, size_t len,
                  std::map<std::string, Value>* out) {
  Reader r{buf, buf + len};
  uint8_t t = r.u8();
  size_t n;
  if ((t & 0xf0) == 0x80) n = t & 0x0f;
  else if (t == 0xde) n = r.be(2);
  else if (t == 0xdf) n = r.be(4);
  else return false;
  for (size_t k = 0; k < n; k++) {
    std::string key;
    if (!parse_str(r, &key)) return false;
    Value v;
    if (!parse_value(r, &v)) return false;
    (*out)[key] = std::move(v);
  }
  return r.ok;
}

struct Writer {
  std::string out;
  void raw(uint8_t b) { out.push_back(static_cast<char>(b)); }
  void be(uint64_t v, int n) {
    for (int k = n - 1; k >= 0; k--) raw((v >> (8 * k)) & 0xff);
  }
  void str(const std::string& s) {
    if (s.size() < 32) raw(0xa0 | s.size());
    else if (s.size() < 256) { raw(0xd9); be(s.size(), 1); }
    else if (s.size() < 65536) { raw(0xda); be(s.size(), 2); }
    else { raw(0xdb); be(s.size() & 0xffffffffull, 4); }  // str32
    out += s;
  }
  void uint(uint64_t v) {
    if (v < 128) raw(static_cast<uint8_t>(v));
    else if (v < 256) { raw(0xcc); be(v, 1); }
    else if (v < 65536) { raw(0xcd); be(v, 2); }
    else if (v <= 0xffffffffull) { raw(0xce); be(v, 4); }
    else { raw(0xcf); be(v, 8); }
  }
  void boolean(bool b) { raw(b ? 0xc3 : 0xc2); }
  void flt(double v) {
    raw(0xcb);
    uint64_t bits;
    std::memcpy(&bits, &v, sizeof(bits));
    be(bits, 8);
  }
  void map_head(size_t n) {
    if (n < 16) raw(0x80 | n);
    else { raw(0xde); be(n, 2); }
  }
  void astr(const std::vector<std::string>& v) {
    if (v.size() < 16) raw(0x90 | v.size());
    else { raw(0xdc); be(v.size(), 2); }
    for (const auto& s : v) str(s);
  }
  void aint(const std::vector<int64_t>& v) {
    if (v.size() < 16) raw(0x90 | v.size());
    else { raw(0xdc); be(v.size(), 2); }
    for (int64_t x : v) uint(static_cast<uint64_t>(x < 0 ? 0 : x));
  }
};

// ------------------------------------------------------------------- tls
//
// Images ship an OpenSSL RUNTIME (libssl.so.3, or only libssl.so.1.1 on
// older bases) but no dev headers, so the needed entry points — a C ABI
// stable since 1.1.0 — are declared here and resolved with dlopen at
// first use. When libssl is absent or a context
// can't be built, engine start FAILS and the chunkserver falls back to
// the asyncio blockport (which wraps Python's ssl) — never to plaintext.
// Parity target: tpudfs/common/rpc.py ServerTls/ClientTls semantics
// (reference dfs/common/src/security.rs:33-105 — TLS on every transport).

constexpr int kPem = 1;            // SSL_FILETYPE_PEM
constexpr int kVerifyPeer = 1;     // SSL_VERIFY_PEER
constexpr int kVerifyFailNo = 2;   // SSL_VERIFY_FAIL_IF_NO_PEER_CERT

constexpr int kSslErrSyscall = 5;  // SSL_ERROR_SYSCALL

struct SslApi {
  void* (*tls_server_method)();
  void* (*tls_client_method)();
  void* (*ctx_new)(void*);
  void (*ctx_free)(void*);
  int (*ctx_use_cert_chain)(void*, const char*);
  int (*ctx_use_key)(void*, const char*, int);
  int (*ctx_load_verify)(void*, const char*, const char*);
  void (*ctx_set_verify)(void*, int, void*);
  void* (*ssl_new)(void*);
  void (*ssl_free)(void*);
  int (*set_fd)(void*, int);
  int (*accept)(void*);
  int (*connect)(void*);
  int (*read)(void*, void*, int);
  int (*write)(void*, const void*, int);
  int (*shutdown)(void*);
  int (*set1_host)(void*, const char*);
  void* (*get0_param)(void*);
  int (*param_set1_ip_asc)(void*, const char*);
  long (*verify_result)(void*);
  int (*get_error)(const void*, int);
};

const SslApi* ssl_api() {
  static const SslApi* api = []() -> const SslApi* {
    // RTLD_LOCAL + an explicit same-generation libcrypto handle: the
    // hosting process (Python) may map a DIFFERENT OpenSSL generation;
    // global-scope symbol resolution could then mix ABIs on one object.
    // Candidates are PAIRS for the same reason — every entry point bound
    // below is present and ABI-stable from 1.1.0 on, so 1.1 images work.
    static const char* kPairs[][2] = {
        {"libssl.so.3", "libcrypto.so.3"},
        {"libssl.so.1.1", "libcrypto.so.1.1"},
        {"libssl.so", "libcrypto.so"},
    };
    void* h = nullptr;
    void* hc = nullptr;
    for (const auto& pair : kPairs) {
      h = ::dlopen(pair[0], RTLD_NOW | RTLD_LOCAL);
      hc = ::dlopen(pair[1], RTLD_NOW | RTLD_LOCAL);
      if (h && hc) break;
      if (h) ::dlclose(h);
      if (hc) ::dlclose(hc);
      h = hc = nullptr;
    }
    if (!h || !hc) return nullptr;
    auto sym = [&](const char* n) { return ::dlsym(h, n); };
    auto csym = [&](const char* n) { return ::dlsym(hc, n); };
    auto* a = new SslApi();
    bool ok = true;
    auto bind = [&ok](auto& fp, void* p) {
      if (!p) { ok = false; return; }
      fp = reinterpret_cast<std::remove_reference_t<decltype(fp)>>(p);
    };
    bind(a->tls_server_method, sym("TLS_server_method"));
    bind(a->tls_client_method, sym("TLS_client_method"));
    bind(a->ctx_new, sym("SSL_CTX_new"));
    bind(a->ctx_free, sym("SSL_CTX_free"));
    bind(a->ctx_use_cert_chain, sym("SSL_CTX_use_certificate_chain_file"));
    bind(a->ctx_use_key, sym("SSL_CTX_use_PrivateKey_file"));
    bind(a->ctx_load_verify, sym("SSL_CTX_load_verify_locations"));
    bind(a->ctx_set_verify, sym("SSL_CTX_set_verify"));
    bind(a->ssl_new, sym("SSL_new"));
    bind(a->ssl_free, sym("SSL_free"));
    bind(a->set_fd, sym("SSL_set_fd"));
    bind(a->accept, sym("SSL_accept"));
    bind(a->connect, sym("SSL_connect"));
    bind(a->read, sym("SSL_read"));
    bind(a->write, sym("SSL_write"));
    bind(a->shutdown, sym("SSL_shutdown"));
    bind(a->set1_host, sym("SSL_set1_host"));
    bind(a->get0_param, sym("SSL_get0_param"));
    bind(a->param_set1_ip_asc, csym("X509_VERIFY_PARAM_set1_ip_asc"));
    bind(a->verify_result, sym("SSL_get_verify_result"));
    bind(a->get_error, sym("SSL_get_error"));
    if (!ok) { delete a; return nullptr; }
    return a;
  }();
  return api;
}

// One duplex connection: plaintext fd, or TLS over it. All frame I/O
// below goes through rd/wr so handlers are transport-agnostic.
struct Stream {
  int fd = -1;
  void* ssl = nullptr;  // SSL* (owned; freed by close())

  ssize_t rd(void* b, size_t n) {
    if (ssl) {
      const SslApi* api = ssl_api();
      for (;;) {
        int r = api->read(ssl, b,
                          static_cast<int>(std::min<size_t>(n, 1u << 30)));
        if (r > 0) return r;
        // Same-args retry on an EINTR'd blocking read is permitted.
        if (api->get_error(ssl, r) == kSslErrSyscall && errno == EINTR)
          continue;
        return r;
      }
    }
    return ::recv(fd, b, n, 0);
  }
  ssize_t wr(const void* b, size_t n) {
    if (ssl) {
      const SslApi* api = ssl_api();
      for (;;) {
        int r = api->write(ssl, b,
                           static_cast<int>(std::min<size_t>(n, 1u << 30)));
        if (r > 0) return r;
        if (api->get_error(ssl, r) == kSslErrSyscall && errno == EINTR)
          continue;
        return r;
      }
    }
    return ::send(fd, b, n, MSG_NOSIGNAL);
  }
  void free_ssl() {
    if (ssl) {
      ssl_api()->shutdown(ssl);  // best-effort close_notify
      ssl_api()->ssl_free(ssl);
      ssl = nullptr;
    }
  }
};

// ------------------------------------------------------------- socket io

// Pinning socket buffers disables kernel autotuning and clamps to
// net.core.{w,r}mem_max; only worth it when the caps allow >= 1 MiB —
// then one sendmsg hands a whole block to the kernel instead of
// trickling in lockstep with a (possibly same-core) reader.
int sock_buf_size() {
  static int cached = [] {
    long w = 0, r = 0;
    for (auto [path, out] : {std::pair<const char*, long*>{
             "/proc/sys/net/core/wmem_max", &w},
         std::pair<const char*, long*>{"/proc/sys/net/core/rmem_max", &r}}) {
      FILE* f = ::fopen(path, "r");
      if (f) {
        if (::fscanf(f, "%ld", out) != 1) *out = 0;
        ::fclose(f);
      }
    }
    long cap = static_cast<long>(4 << 20);
    if (w < cap) cap = w;
    if (r < cap) cap = r;
    return cap >= (1 << 20) ? static_cast<int>(cap) : 0;
  }();
  return cached;
}

void tune_buffers(int fd) {
  int buf = sock_buf_size();
  if (!buf) return;
  ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
}

bool read_exact(Stream& s, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = s.rd(p, n);
    if (r < 0) {
      if (!s.ssl && errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(Stream& s, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = s.wr(p, n);
    if (r <= 0) {
      if (!s.ssl && r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_frame(Stream& s, const std::string& header, const uint8_t* payload,
                uint64_t plen) {
  // Length prefixes are little-endian ("<I"/"<Q") — x86-64 is LE.
  uint32_t hl = static_cast<uint32_t>(header.size());
  if (!write_all(s, &hl, 4)) return false;
  if (!write_all(s, header.data(), header.size())) return false;
  if (!write_all(s, &plen, 8)) return false;
  if (plen && !write_all(s, payload, plen)) return false;
  return true;
}

bool recv_frame(Stream& s, std::map<std::string, Value>* header,
                std::vector<uint8_t>* payload) {
  uint32_t hl;
  if (!read_exact(s, &hl, 4)) return false;
  if (hl > kMaxHeader) return false;
  std::vector<uint8_t> hbuf(hl);
  if (!read_exact(s, hbuf.data(), hl)) return false;
  uint64_t pl;
  if (!read_exact(s, &pl, 8)) return false;
  if (pl > kMaxPayload) return false;
  payload->resize(pl);
  if (pl && !read_exact(s, payload->data(), pl)) return false;
  return parse_header(hbuf.data(), hl, header);
}

// Streaming variant: the payload lands in a caller-owned reusable buffer
// (the frame ring) instead of a fresh vector. A payload larger than `cap`
// cannot be consumed without losing the request boundary, so it reports a
// transport tear.
bool recv_frame_into(Stream& s, std::map<std::string, Value>* header,
                     uint8_t* buf, uint64_t cap, uint64_t* plen) {
  uint32_t hl;
  if (!read_exact(s, &hl, 4)) return false;
  if (hl > kMaxHeader) return false;
  std::vector<uint8_t> hbuf(hl);
  if (!read_exact(s, hbuf.data(), hl)) return false;
  uint64_t pl;
  if (!read_exact(s, &pl, 8)) return false;
  if (pl > cap) return false;
  if (pl && !read_exact(s, buf, pl)) return false;
  *plen = pl;
  return parse_header(hbuf.data(), hl, header);
}

// Relative deadline budget (`_db`, seconds) — float on the wire normally,
// but accept ints too (a client may send a whole-second budget).
bool deadline_budget(std::map<std::string, Value>& h, double* out) {
  auto it = h.find("_db");
  if (it == h.end()) return false;
  if (it->second.kind == Value::FLT) { *out = it->second.f; return true; }
  if (it->second.kind == Value::INT) {
    *out = static_cast<double>(it->second.i);
    return true;
  }
  return false;
}

bool write_fd_all(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += static_cast<size_t>(r);
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Sidecar for a streamed block, chunk CRCs accumulated frame-by-frame —
// byte-identical to blockio.cc block_write_impl's meta ("<4sHHII" + <u4
// array; x86-64 is LE so native-width stores match the wire layout).
bool write_meta_tmp(const std::string& path, uint32_t chunk,
                    const std::vector<uint32_t>& sums) {
  int fd = ::open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  uint8_t hdr[16];
  std::memcpy(hdr, "TPUM", 4);
  uint16_t ver = 1, reserved = 0;
  std::memcpy(hdr + 4, &ver, 2);
  std::memcpy(hdr + 6, &reserved, 2);
  uint32_t count = static_cast<uint32_t>(sums.size());
  std::memcpy(hdr + 8, &chunk, 4);
  std::memcpy(hdr + 12, &count, 4);
  bool ok = write_fd_all(fd, hdr, sizeof(hdr)) &&
            (sums.empty() ||
             write_fd_all(fd, sums.data(), sums.size() * sizeof(uint32_t)));
  ::close(fd);
  return ok;
}

// ---------------------------------------------------- crc32c GF(2) combine
//
// Mirror of tpudfs/common/checksum.py crc32c_combine/_zero_operator (the
// zlib crc32_combine structure): crc(A+B) = M_{len(B)} * crc(A) ^ crc(B),
// where M_n is the GF(2) matrix advancing a CRC register across n zero
// bytes. The streaming write path folds per-frame CRCs into the
// whole-block CRC with this — no second pass over the data.

constexpr uint32_t kCrcPoly = 0x82F63B78u;

uint32_t crc_matrix_times(const uint32_t mat[32], uint32_t vec) {
  uint32_t total = 0;
  for (int i = 0; vec; vec >>= 1, i++)
    if (vec & 1) total ^= mat[i];
  return total;
}

void crc_matrix_square(uint32_t out[32], const uint32_t mat[32]) {
  for (int i = 0; i < 32; i++) out[i] = crc_matrix_times(mat, mat[i]);
}

void crc_zero_operator(uint64_t len2, uint32_t result[32]) {
  uint32_t odd[32], even[32];
  odd[0] = kCrcPoly;  // operator for one zero bit
  for (int i = 1; i < 32; i++) odd[i] = 1u << (i - 1);
  crc_matrix_square(even, odd);  // two zero bits
  crc_matrix_square(odd, even);  // four zero bits
  for (int i = 0; i < 32; i++) result[i] = 1u << i;  // identity
  uint64_t n = len2;
  while (n) {
    crc_matrix_square(even, odd);  // next power-of-two byte count
    if (n & 1) {
      uint32_t tmp[32];
      for (int i = 0; i < 32; i++) tmp[i] = crc_matrix_times(even, result[i]);
      std::memcpy(result, tmp, sizeof(tmp));
    }
    std::memcpy(odd, even, sizeof(even));
    n >>= 1;
  }
}

// ------------------------------------------------------------- qos plane
//
// Thread-blocking twin of tpudfs/common/resilience.py's QosShedder: the
// same queue -> rate-limit -> shed degradation ladder, per-tenant
// time-refilled token buckets, deficit-round-robin fair queueing, and
// jittered retry_after hints. Python pushes the active QosShedder config
// in at start (and on change) via tpudfs_dataplane_set_qos — a msgpack
// flat map built by resilience.qos_wire_config() — and drains the
// per-tenant counters back out with tpudfs_dataplane_qos_stats /
// tpudfs_dataplane_take_qos, the same in/out pattern as set_term /
// take_terms.
//
// Determinism contract: both sides draw retry_after jitter from an
// identical SplitMix64 stream (seeded via the config's jitter_seed), and
// exactly ONE draw happens per rejection and ZERO per admission, so a
// fixed seed + fixed request schedule yields the same retry_after values
// from either engine (tests/test_qos.py holds this draw-for-draw).
//
// Failpoints (chaos injection) are re-read from TPUDFS_QOS_FAILPOINT at
// configure time, same grammar as resilience.QosFailpoints:
//   freeze_refill       — rate buckets stop refilling (clock frozen)
//   delay_admit=<secs>  — every admitted request stalls before dispatch
//   force_shed=<n>      — next n acquires (or in-flight stream frames)
//                         are refused unconditionally

// Deterministic jitter PRNG — algorithm-identical to
// resilience.SplitMix64 (same state advance, finalizer, and 53-bit
// double in [0, 1)).
struct SplitMix64 {
  uint64_t s = 0;
  double next() {
    s += 0x9E3779B97F4A7C15ull;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    z ^= z >> 31;
    return static_cast<double>(z >> 11) * 0x1.0p-53;
  }
};

// DRR per-visit credit — must match resilience.py QOS_DRR_QUANTUM.
constexpr int kQosDrrQuantum = 1;
// Per-tenant admission-queue bound — resilience.py QOS_QUEUE_DEPTH_DEFAULT.
constexpr int kQosQueueDepthDefault = 32;
// Rate-bucket burst floor — resilience.py QOS_MIN_BURST.
constexpr int kQosMinBurst = 1;
// Per-tenant latency ring capacity — resilience.py _LATENCY_RING.
constexpr int kQosLatencyRing = 256;

struct QosConfig {
  bool enabled = false;
  int64_t max_inflight = 64;
  double base_retry_after = 0.1;
  double rate = 0.0;    // per-tenant req/s; <= 0 = unlimited
  double burst = 1.0;   // resolved Python-side (QosShedder.burst)
  int64_t queue_depth = kQosQueueDepthDefault;
  double queue_wait = 0.25;
  double default_weight = 1.0;
  std::map<std::string, double> weights;
};

// One parked admission request (resilience._Waiter). Stack-allocated in
// Qos::acquire; the DRR holds pointers, and every state transition
// happens under Qos::mu_, so the pointer never outlives its frame.
struct QosWaiter {
  std::string tenant;
  int state = 0;  // 0 waiting, 1 admitted, 2 rejected
  std::string detail;
  double retry_after = 0.0;
  bool has_deadline = false;
  double deadline_s = 0.0;
};

// Deficit round-robin over per-tenant FIFOs — a faithful port of
// resilience.DeficitRoundRobin (Shreedhar & Varghese): quantum×weight
// credit per visit, a drained tenant forfeits leftover deficit, and an
// arbitrarily deep queue buys a tenant zero extra service.
class QosDrr {
 public:
  double quantum = static_cast<double>(kQosDrrQuantum);
  double default_weight = 1.0;
  std::map<std::string, double> weights;

  double weight(const std::string& t) const {
    auto it = weights.find(t);
    return std::max(it == weights.end() ? default_weight : it->second, 1e-6);
  }
  size_t size() const {
    size_t n = 0;
    for (const auto& kv : queues_) n += kv.second.size();
    return n;
  }
  size_t depth(const std::string& t) const {
    auto it = queues_.find(t);
    return it == queues_.end() ? 0 : it->second.size();
  }
  std::vector<std::string> tenants() const {
    return std::vector<std::string>(ring_.begin(), ring_.end());
  }
  void push(const std::string& t, QosWaiter* w) {
    ensure(t);
    queues_[t].push_back(w);
  }
  // Return an item to the head of its FIFO (dispatch backed out — the
  // tenant's rate bucket was empty at dispatch time).
  void push_front(const std::string& t, QosWaiter* w) {
    ensure(t);
    queues_[t].push_front(w);
  }
  // Next (tenant, item) by DRR order; {"", nullptr} when empty or every
  // queued tenant is in `skip` (rate-limited this dispatch round).
  std::pair<std::string, QosWaiter*> pop(const std::set<std::string>& skip) {
    if (ring_.empty()) return {std::string(), nullptr};
    // Termination: every eligible visit grows that tenant's deficit by
    // quantum*weight > 0, so within bounded cycles some head is served.
    double min_w = weight(ring_.front());
    for (const auto& t : ring_) min_w = std::min(min_w, weight(t));
    int visits = 0;
    const int max_visits = static_cast<int>(ring_.size()) *
                           (2 + static_cast<int>(1.0 / min_w));
    while (!ring_.empty() && visits <= max_visits) {
      visits++;
      const std::string tenant = ring_.front();
      if (!skip.empty() && skip.count(tenant)) {
        bool all = true;
        for (const auto& t : ring_)
          if (!skip.count(t)) { all = false; break; }
        if (all) return {std::string(), nullptr};
        rotate();
        continue;
      }
      auto& q = queues_[tenant];
      const double cost = 1.0;  // _Waiter.cost default — always 1.0 here
      if (deficit_[tenant] >= cost) {
        QosWaiter* item = q.front();
        q.pop_front();
        deficit_[tenant] -= cost;
        if (q.empty()) {
          // A drained tenant forfeits its leftover deficit: credit must
          // not accumulate while idle (classic DRR rule).
          deficit_[tenant] = 0.0;
          retire(tenant);
        }
        return {tenant, item};
      }
      deficit_[tenant] += quantum * weight(tenant);
      rotate();
    }
    return {std::string(), nullptr};
  }
  // Remove and return every queued item matching `pred` (expired
  // waiters); tenants left empty retire from the ring.
  template <typename Pred>
  std::vector<QosWaiter*> evict(Pred pred) {
    std::vector<QosWaiter*> out;
    std::vector<std::string> names;
    names.reserve(queues_.size());
    for (const auto& kv : queues_) names.push_back(kv.first);
    for (const auto& tenant : names) {
      auto& q = queues_[tenant];
      std::deque<QosWaiter*> kept;
      for (QosWaiter* w : q) {
        if (pred(w)) out.push_back(w);
        else kept.push_back(w);
      }
      q = std::move(kept);
      retire(tenant);
    }
    return out;
  }

 private:
  void ensure(const std::string& t) {
    if (queues_.find(t) == queues_.end()) {
      queues_[t];
      ring_.push_back(t);
      deficit_.emplace(t, 0.0);
    }
  }
  void rotate() {  // Python deque.rotate(-1): front -> back
    ring_.push_back(ring_.front());
    ring_.pop_front();
  }
  void retire(const std::string& t) {
    auto it = queues_.find(t);
    if (it != queues_.end() && it->second.empty()) {
      queues_.erase(it);
      deficit_.erase(t);
      for (auto rit = ring_.begin(); rit != ring_.end(); ++rit)
        if (*rit == t) { ring_.erase(rit); break; }
    }
  }
  std::map<std::string, std::deque<QosWaiter*>> queues_;
  std::deque<std::string> ring_;
  std::map<std::string, double> deficit_;
};

// Time-refilled token bucket (resilience.RateBucket): monotone refill —
// a clock that stalls (the freeze_refill failpoint) never drains tokens.
struct QosBucket {
  double rate = 0.0, burst = 0.0, tokens = 0.0, last = 0.0;
};

// The admission plane. Connection threads block in acquire() (the
// asyncio shedder parks a future; here the thread parks on a condition
// variable — same ladder, same counters, same jitter draws).
class Qos {
 public:
  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }

  void configure(const QosConfig& cfg, uint64_t seed) {
    std::lock_guard<std::mutex> lk(mu_);
    cfg_ = cfg;
    // System outweighs any single default-weight tenant unless the
    // operator explicitly pinned it (QosShedder.__init__).
    if (cfg_.weights.find("system") == cfg_.weights.end())
      cfg_.weights["system"] = std::max(4.0, cfg_.default_weight);
    drr_.default_weight = cfg_.default_weight;
    drr_.weights = cfg_.weights;
    if (seed != 0) {
      rng_.s = seed;
    } else {
      // Entropy-seeded Python side: decorrelate from other servers so a
      // shed wave never hands out lockstep retry hints.
      rng_.s ^= static_cast<uint64_t>(
          std::chrono::steady_clock::now().time_since_epoch().count());
    }
    fp_freeze_refill_ = false;
    fp_delay_admit_ = 0.0;
    fp_force_shed_ = 0;
    const char* raw = ::getenv("TPUDFS_QOS_FAILPOINT");
    if (raw != nullptr) parse_failpoints(raw);
    frozen_now_ = now_s();
    buckets_.clear();
    enabled_.store(cfg_.enabled, std::memory_order_relaxed);
    cv_.notify_all();
  }

  // Admit, queue, or refuse one request. Returns true when admitted
  // (pair with release()); false fills detail + retry_after. The ladder,
  // counter increments, and jitter-draw pattern mirror
  // QosShedder.acquire exactly.
  bool acquire(const std::string& tenant, bool has_db, double budget,
               std::string* detail, double* retry_after) {
    double delay = 0.0;
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (fp_force_shed_ > 0) {
        fp_force_shed_--;
        count_shed(tenant);
        *detail = "failpoint forced shed";
        *retry_after = retry_after_for(tenant);
        return false;
      }
      QosBucket* b = bucket(tenant);
      if (inflight_ < cfg_.max_inflight && drr_.size() == 0 &&
          (b == nullptr || try_spend(b))) {
        admit(tenant);
        delay = fp_delay_admit_;
      } else {
        // Contended (or over-rate): degrade to the fair queue.
        if (drr_.depth(tenant) >= static_cast<size_t>(cfg_.queue_depth)) {
          evict_expired_locked();
          if (drr_.depth(tenant) >= static_cast<size_t>(cfg_.queue_depth)) {
            count_shed(tenant);
            *detail = "tenant queue full";
            *retry_after = retry_after_for(tenant);
            return false;
          }
        }
        QosWaiter w;
        w.tenant = tenant;
        if (has_db) {
          w.has_deadline = true;
          w.deadline_s = now_s() + budget;
        }
        drr_.push(tenant, &w);
        queued_total_++;
        queued_by_tenant_[tenant]++;
        kick_locked();
        double wait = cfg_.queue_wait;
        if (has_db) wait = std::min(wait, std::max(budget, 0.0));
        const double give_up = now_s() + wait;
        while (w.state == 0) {
          double now = now_s();
          if (now >= give_up) break;
          double wake = give_up;
          if (refill_kick_at_ > 0 && refill_kick_at_ < wake)
            wake = refill_kick_at_;
          // wait_until on system_clock, NOT wait_for: wait_for rides the
          // steady clock through pthread_cond_clockwait, which TSan does
          // not intercept (gcc 10 / glibc 2.31) — the missed unlock
          // corrupts the whole mutex's happens-before state. The loop
          // re-derives its own deadline from now_s() every iteration, so
          // a wall-clock step only perturbs one wakeup.
          cv_.wait_until(
              lk, std::chrono::system_clock::now() +
                      std::chrono::microseconds(static_cast<int64_t>(
                          std::max(wake - now, 1e-4) * 1e6)));
          if (w.state == 0 && refill_kick_at_ > 0 &&
              now_s() >= refill_kick_at_) {
            // QosShedder._timer_kick twin: the first waiter past the
            // earliest bucket refill re-runs eviction + dispatch, so
            // rate-limited waiters don't rely on unrelated traffic.
            refill_kick_at_ = 0.0;
            evict_expired_locked();
            kick_locked();
          }
        }
        if (w.state == 0) {
          // Timed out parked (the asyncio TimeoutError path): reap our
          // queue slot now rather than waiting for a sweep.
          drr_.evict([&](QosWaiter* x) { return x == &w; });
          rate_limited_total_++;
          rate_limited_by_tenant_[tenant]++;
          count_shed(tenant);
          *detail = "rate limited";
          *retry_after = retry_after_for(tenant);
          return false;
        }
        if (w.state == 2) {
          *detail = w.detail;
          *retry_after = w.retry_after;
          return false;
        }
        delay = fp_delay_admit_;
      }
    }
    if (delay > 0)
      std::this_thread::sleep_for(std::chrono::duration<double>(delay));
    return true;
  }

  void release(const std::string& tenant, double elapsed) {
    std::lock_guard<std::mutex> lk(mu_);
    inflight_--;
    auto& ring = latency_by_tenant_[tenant];
    ring.push_back(elapsed);
    if (ring.size() > static_cast<size_t>(kQosLatencyRing))
      ring.pop_front();
    kick_locked();
  }

  // Mid-stream per-frame shed (force_shed failpoint re-armed by a config
  // re-push while a stream is in flight) — lets chaos abort an admitted
  // stream partway, exercising the client's Overloaded retry path.
  bool shed_frame(const std::string& tenant, double* retry_after) {
    if (!enabled()) return false;
    std::lock_guard<std::mutex> lk(mu_);
    if (fp_force_shed_ <= 0) return false;
    fp_force_shed_--;
    count_shed(tenant);
    *retry_after = retry_after_for(tenant);
    return true;
  }

  // inflight, peak_inflight, admitted_total, shed_total, queue_depth,
  // queued_total, rate_limited_total, evicted_total.
  void stats(uint64_t out[8]) {
    std::lock_guard<std::mutex> lk(mu_);
    out[0] = inflight_ > 0 ? static_cast<uint64_t>(inflight_) : 0;
    out[1] = static_cast<uint64_t>(peak_inflight_);
    out[2] = admitted_total_;
    out[3] = shed_total_;
    out[4] = static_cast<uint64_t>(drr_.size());
    out[5] = queued_total_;
    out[6] = rate_limited_total_;
    out[7] = evicted_total_;
  }

  // Per-tenant counter dump: "tenant\tadmitted\tshed\trate_limited\t
  // queue_depth\tp99_ns\n" lines. Non-destructive (counters only grow;
  // re-reading is idempotent). Returns bytes written, or -needed when
  // cap is short — the take_terms contract.
  int64_t take(char* buf, uint64_t cap) {
    std::lock_guard<std::mutex> lk(mu_);
    std::set<std::string> names;
    for (const auto& kv : admitted_by_tenant_) names.insert(kv.first);
    for (const auto& kv : shed_by_tenant_) names.insert(kv.first);
    for (const auto& kv : rate_limited_by_tenant_) names.insert(kv.first);
    for (const auto& kv : latency_by_tenant_) names.insert(kv.first);
    for (const auto& t : drr_.tenants()) names.insert(t);
    std::string joined;
    for (const auto& raw : names) {
      std::string t = raw;
      for (char& c : t)
        if (c == '\t' || c == '\n') c = '_';
      uint64_t p99_ns = 0;
      auto lit = latency_by_tenant_.find(raw);
      if (lit != latency_by_tenant_.end() && !lit->second.empty()) {
        std::vector<double> ordered(lit->second.begin(), lit->second.end());
        std::sort(ordered.begin(), ordered.end());
        size_t idx = std::min(ordered.size() - 1,
                              static_cast<size_t>(
                                  0.99 * (ordered.size() - 1)));
        p99_ns = static_cast<uint64_t>(ordered[idx] * 1e9);
      }
      joined += t + "\t" + std::to_string(counter(admitted_by_tenant_, raw)) +
                "\t" + std::to_string(counter(shed_by_tenant_, raw)) + "\t" +
                std::to_string(counter(rate_limited_by_tenant_, raw)) + "\t" +
                std::to_string(drr_.depth(raw)) + "\t" +
                std::to_string(p99_ns) + "\n";
    }
    if (joined.size() + 1 > cap)
      return -static_cast<int64_t>(joined.size() + 1);
    std::memcpy(buf, joined.c_str(), joined.size() + 1);
    return static_cast<int64_t>(joined.size());
  }

 private:
  static double now_s() {
    return std::chrono::duration<double>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }
  static uint64_t counter(const std::map<std::string, uint64_t>& m,
                          const std::string& t) {
    auto it = m.find(t);
    return it == m.end() ? 0 : it->second;
  }
  // tpulint: guarded-by(mu_)
  void parse_failpoints(const std::string& raw) {
    size_t pos = 0;
    while (pos <= raw.size()) {
      size_t comma = raw.find(',', pos);
      std::string part = raw.substr(
          pos, comma == std::string::npos ? std::string::npos : comma - pos);
      size_t a = part.find_first_not_of(" \t");
      size_t z = part.find_last_not_of(" \t");
      part = a == std::string::npos ? "" : part.substr(a, z - a + 1);
      size_t eq = part.find('=');
      std::string name = eq == std::string::npos ? part : part.substr(0, eq);
      std::string value = eq == std::string::npos ? "" : part.substr(eq + 1);
      if (name == "freeze_refill") fp_freeze_refill_ = true;
      else if (name == "delay_admit")
        fp_delay_admit_ = std::strtod(value.c_str(), nullptr);
      else if (name == "force_shed")
        fp_force_shed_ = std::strtol(value.c_str(), nullptr, 10);
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
  }
  // tpulint: guarded-by(mu_)
  double bucket_now() const { return fp_freeze_refill_ ? frozen_now_ : now_s(); }
  // tpulint: guarded-by(mu_)
  QosBucket* bucket(const std::string& tenant) {
    // The system tenant (control plane, untenanted clients) is never
    // rate-limited — QosShedder._bucket parity.
    if (cfg_.rate <= 0 || tenant == "system") return nullptr;
    auto it = buckets_.find(tenant);
    if (it == buckets_.end()) {
      QosBucket b;
      b.rate = cfg_.rate;
      b.burst = std::max(cfg_.burst, static_cast<double>(kQosMinBurst));
      b.tokens = b.burst;
      b.last = bucket_now();
      it = buckets_.emplace(tenant, b).first;
    }
    return &it->second;
  }
  void refill(QosBucket* b) const {
    double now = bucket_now();
    // now <= last: clock stall/regression — tokens unchanged, and last
    // keeps its high-water mark (RateBucket._refill).
    if (now > b->last) {
      b->tokens = std::min(b->burst, b->tokens + (now - b->last) * b->rate);
      b->last = now;
    }
  }
  bool try_spend(QosBucket* b) const {
    refill(b);
    if (b->tokens >= 1.0) {
      b->tokens -= 1.0;
      return true;
    }
    return false;
  }
  double bucket_retry_after(QosBucket* b) const {
    refill(b);
    if (b->tokens >= 1.0) return 0.0;
    return (1.0 - b->tokens) / b->rate;
  }
  // tpulint: guarded-by(mu_)
  double jittered(double seconds) {
    return std::max(0.0,
                    seconds * (1.0 + 0.25 * (2.0 * rng_.next() - 1.0)));
  }
  // Per-tenant retry-after: the tenant's refill schedule when it has
  // one, else the pressure-scaled global hint. Exactly one jitter draw —
  // QosShedder.retry_after_for parity.
  // tpulint: guarded-by(mu_)
  double retry_after_for(const std::string& tenant) {
    QosBucket* b = bucket(tenant);
    if (b != nullptr) {
      double hinted = bucket_retry_after(b);
      if (hinted > 0)
        return jittered(std::max(hinted, cfg_.base_retry_after));
    }
    int64_t over =
        std::max<int64_t>(0, inflight_ - cfg_.max_inflight + 1) +
        static_cast<int64_t>(drr_.size());
    double hint = cfg_.base_retry_after *
                  (1.0 + static_cast<double>(over) /
                             static_cast<double>(
                                 std::max<int64_t>(1, cfg_.max_inflight)));
    return jittered(hint);
  }
  // tpulint: guarded-by(mu_)
  void admit(const std::string& tenant) {
    inflight_++;
    admitted_total_++;
    if (inflight_ > peak_inflight_) peak_inflight_ = inflight_;
    admitted_by_tenant_[tenant]++;
  }
  // tpulint: guarded-by(mu_)
  void count_shed(const std::string& tenant) {
    shed_total_++;
    shed_by_tenant_[tenant]++;
  }
  // Drop queued waiters whose ambient deadline already expired —
  // admitting doomed work just burns an inflight slot. Caller holds mu_.
  // tpulint: guarded-by(mu_)
  void evict_expired_locked() {
    const double now = now_s();
    auto evicted = drr_.evict([&](QosWaiter* w) {
      return w->state != 0 || (w->has_deadline && now >= w->deadline_s);
    });
    uint64_t n = 0;
    for (QosWaiter* w : evicted) {
      if (w->state != 0) continue;
      n++;
      count_shed(w->tenant);
      w->state = 2;
      w->detail = "deadline expired in admission queue";
      w->retry_after = retry_after_for(w->tenant);
    }
    evicted_total_ += n;
    if (n) cv_.notify_all();
  }
  // Dispatch queued waiters into free inflight slots, DRR order
  // (QosShedder._kick). Tenants whose rate bucket is empty are skipped
  // this round (waiter returns to its FIFO head) and refill_kick_at_
  // arms the timer-kick twin above. Caller holds mu_.
  // tpulint: guarded-by(mu_)
  void kick_locked() {
    std::set<std::string> skip;
    double min_refill = -1.0;
    while (inflight_ < cfg_.max_inflight) {
      auto nxt = drr_.pop(skip);
      if (nxt.second == nullptr) break;
      const std::string& tenant = nxt.first;
      QosWaiter* w = nxt.second;
      if (w->state != 0) continue;  // timed out while parked
      if (w->has_deadline && now_s() >= w->deadline_s) {
        count_shed(tenant);
        evicted_total_++;
        w->state = 2;
        w->detail = "deadline expired in admission queue";
        w->retry_after = retry_after_for(tenant);
        continue;
      }
      QosBucket* b = bucket(tenant);
      if (b != nullptr && !try_spend(b)) {
        drr_.push_front(tenant, w);
        skip.insert(tenant);
        double refill_in = bucket_retry_after(b);
        if (min_refill < 0 || refill_in < min_refill)
          min_refill = refill_in;
        continue;
      }
      admit(tenant);
      w->state = 1;
    }
    if (min_refill >= 0 && drr_.size() > 0) {
      double at = now_s() + std::max(min_refill, 0.005);
      if (refill_kick_at_ <= 0 || at < refill_kick_at_)
        refill_kick_at_ = at;
    }
    cv_.notify_all();
  }

  std::mutex mu_;
  std::condition_variable cv_;
  QosConfig cfg_;
  std::atomic<bool> enabled_{false};
  SplitMix64 rng_;
  QosDrr drr_;
  std::map<std::string, QosBucket> buckets_;
  bool fp_freeze_refill_ = false;
  double fp_delay_admit_ = 0.0;
  int64_t fp_force_shed_ = 0;
  double frozen_now_ = 0.0;
  double refill_kick_at_ = 0.0;  // earliest pending timer-kick (0 = none)
  int64_t inflight_ = 0;
  int64_t peak_inflight_ = 0;
  uint64_t admitted_total_ = 0, shed_total_ = 0, queued_total_ = 0,
      rate_limited_total_ = 0, evicted_total_ = 0;
  std::map<std::string, uint64_t> admitted_by_tenant_, shed_by_tenant_,
      queued_by_tenant_, rate_limited_by_tenant_;
  std::map<std::string, std::deque<double>> latency_by_tenant_;
};

// --------------------------------------------------------------- engine

struct CommitEntry {
  std::string data_tmp, meta_tmp, data_final, meta_final;
  bool done = false;
  bool failed = false;
  std::string error;
};

class Engine {
 public:
  Engine(std::string host, std::string hot, std::string cold,
         uint32_t chunk, size_t cache_blocks)
      : host_(std::move(host)), hot_(std::move(hot)),
        cold_(std::move(cold)), chunk_(chunk), cache_cap_(cache_blocks) {}

  ~Engine() {
    const SslApi* api = ssl_api();
    if (api != nullptr) {
      if (srv_ctx_ != nullptr) api->ctx_free(srv_ctx_);
      if (cli_ctx_ != nullptr) api->ctx_free(cli_ctx_);
    }
  }

  // TLS config (all paths empty = plaintext). srv_*: this listener's cert
  // material, srv_client_ca non-empty = require + verify client certs
  // (mTLS, ServerTls.ca_path parity). out_*: chain-forward client side —
  // out_ca verifies downstream peers (with hostname/IP SAN matching like
  // BlockConnPool), out_cert/key presented when the cluster runs mTLS.
  // Returns false when libssl or the cert material is unusable — the
  // caller must NOT fall back to plaintext (it reports start failure and
  // Python uses the asyncio blockport instead).
  // Runs on the ctypes caller's thread before start() spawns the
  // accept/commit threads — srv_ctx_/cli_ctx_ are set-once config
  // after this returns.
  // tpulint: pre-start
  bool configure_tls(const std::string& srv_cert, const std::string& srv_key,
                     const std::string& srv_client_ca,
                     const std::string& out_ca, const std::string& out_cert,
                     const std::string& out_key) {
    if (srv_cert.empty() && srv_key.empty() && srv_client_ca.empty() &&
        out_ca.empty() && out_cert.empty() && out_key.empty())
      return true;  // plaintext: no libssl needed at all
    const SslApi* api = ssl_api();
    if (api == nullptr) return false;
    if (!srv_cert.empty()) {
      srv_ctx_ = api->ctx_new(api->tls_server_method());
      if (srv_ctx_ == nullptr) return false;
      if (api->ctx_use_cert_chain(srv_ctx_, srv_cert.c_str()) != 1 ||
          api->ctx_use_key(srv_ctx_, srv_key.c_str(), kPem) != 1)
        return false;
      if (!srv_client_ca.empty()) {
        if (api->ctx_load_verify(srv_ctx_, srv_client_ca.c_str(),
                                 nullptr) != 1)
          return false;
        api->ctx_set_verify(srv_ctx_, kVerifyPeer | kVerifyFailNo, nullptr);
      }
    }
    if (!out_ca.empty()) {
      cli_ctx_ = api->ctx_new(api->tls_client_method());
      if (cli_ctx_ == nullptr) return false;
      if (api->ctx_load_verify(cli_ctx_, out_ca.c_str(), nullptr) != 1)
        return false;
      api->ctx_set_verify(cli_ctx_, kVerifyPeer, nullptr);
      if (!out_cert.empty() && !out_key.empty()) {
        if (api->ctx_use_cert_chain(cli_ctx_, out_cert.c_str()) != 1 ||
            api->ctx_use_key(cli_ctx_, out_key.c_str(), kPem) != 1)
          return false;
      }
    }
    return true;
  }

  // tpulint: pre-start (listener setup; listen_fd_/port_ are written
  // only here, before the accept/commit threads spawn at the end)
  int64_t start(uint16_t port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -errno;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    tune_buffers(listen_fd_);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    // Bind the same interface the gRPC listener uses (resolve names via
    // getaddrinfo) so the advertised data port is reachable wherever the
    // control port is.
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    if (!host_.empty() && host_ != "localhost") {
      if (::inet_pton(AF_INET, host_.c_str(), &addr.sin_addr) != 1) {
        addrinfo hints{};
        hints.ai_family = AF_INET;
        hints.ai_socktype = SOCK_STREAM;
        addrinfo* res = nullptr;
        if (::getaddrinfo(host_.c_str(), nullptr, &hints, &res) == 0 &&
            res != nullptr) {
          addr.sin_addr =
              reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
          ::freeaddrinfo(res);
        }
      }
    }
    addr.sin_port = htons(port);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 128) != 0) {
      int e = errno;
      ::close(listen_fd_);
      return -e;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    running_.store(true);
    commit_thread_ = std::thread([this] { commit_loop(); });
    accept_thread_ = std::thread([this] { accept_loop(); });
    return port_;
  }

  // Returns true when every connection thread has exited; false means a
  // detached thread is still inside a handler (e.g. a slow disk stage) —
  // the caller must then LEAK the engine rather than delete it out from
  // under the thread.
  bool stop() {
    running_.store(false);
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> g(conns_mu_);
      for (int fd : conns_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    // Connection threads are detached; the shutdowns above unblock socket
    // waits immediately. Allow a generous window for in-flight disk work.
    for (int i = 0; i < 1000 && active_.load() > 0; i++)
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    {
      // Notify under commit_mu_: the commit loop's predicated wait
      // re-checks running_ with the mutex held, so pairing the notify
      // with the lock means it can never fire in the window between the
      // loop's predicate check and its block — the shutdown wakeup
      // cannot be lost.
      std::lock_guard<std::mutex> g(commit_mu_);
      commit_cv_.notify_all();
    }
    if (commit_thread_.joinable()) commit_thread_.join();
    return active_.load() == 0;
  }

  int32_t port() const { return port_; }

  // Epoch fencing is scoped per issuing Raft group (shard): one shard's
  // failover must not fence writes allocated by a different shard.
  void set_term(const std::string& shard, uint64_t t) {
    std::lock_guard<std::mutex> g(term_mu_);
    uint64_t& cur = terms_[shard];
    if (t > cur) cur = t;
  }
  uint64_t term(const std::string& shard) {
    std::lock_guard<std::mutex> g(term_mu_);
    auto it = terms_.find(shard);
    return it == terms_.end() ? 0 : it->second;
  }

  // Dump every (shard, term) pair as "shard\tterm\n" lines — the
  // heartbeat loop polls this so request-learned terms reach the Python
  // fencing plane too. Non-destructive (terms only ever grow; re-reading
  // is idempotent). Returns bytes written, or -needed when cap is short.
  int64_t take_terms(char* buf, uint64_t cap) {
    std::lock_guard<std::mutex> g(term_mu_);
    std::string joined;
    for (const auto& kv : terms_)
      joined += kv.first + "\t" + std::to_string(kv.second) + "\n";
    if (joined.size() + 1 > cap)
      return -static_cast<int64_t>(joined.size() + 1);
    std::memcpy(buf, joined.c_str(), joined.size() + 1);
    return static_cast<int64_t>(joined.size());
  }

  int64_t take_bad(char* buf, uint64_t cap) {
    // Drain as many WHOLE ids as fit; the rest stay for the next poll —
    // an oversized backlog must never wedge reporting.
    std::lock_guard<std::mutex> g(bad_mu_);
    std::string joined;
    auto it = bad_.begin();
    while (it != bad_.end()) {
      size_t need = joined.size() + (joined.empty() ? 0 : 1) + it->size() + 1;
      if (need > cap) break;
      if (!joined.empty()) joined += '\n';
      joined += *it;
      it = bad_.erase(it);
    }
    if (joined.empty() && !bad_.empty())
      return -static_cast<int64_t>(bad_.begin()->size() + 1);
    std::memcpy(buf, joined.c_str(), joined.size() + 1);
    return static_cast<int64_t>(joined.size());
  }

  void stats(uint64_t out[6]) const {
    out[0] = writes_.load();
    out[1] = reads_.load();
    out[2] = forwards_.load();
    out[3] = errors_.load();
    out[4] = cache_hits_.load();
    out[5] = cache_misses_.load();
  }

  // Write-path stage budget (round-5: isolate fsync scheduling from
  // protocol cost in the chain write). All nanoseconds except the counts.
  void stage_stats(uint64_t out[8]) const {
    out[0] = stage_ns_.load();        // tpudfs_block_write_staged wall
    out[1] = commit_wait_ns_.load();  // queued -> durable (group commit)
    out[2] = syncfs_ns_.load();       // commit loop's syncfs calls
    out[3] = fwd_ack_ns_.load();      // downstream ack recv wall
    out[4] = commit_batches_.load();
    out[5] = commit_entries_.load();
    out[6] = staged_bytes_.load();
    out[7] = rename_ns_.load();       // publish renames
  }

  // Streaming write pipeline occupancy — slot order MUST match the
  // Python service's _stream_stats keys (service.py stream_stage_stats
  // zips them): net_ns, crc_ns, disk_ns, fanout_ns, frames, streams,
  // stream_bytes, aborts.
  void stream_stage_stats(uint64_t out[8]) const {
    out[0] = stream_net_ns_.load();
    out[1] = stream_crc_ns_.load();
    out[2] = stream_disk_ns_.load();
    out[3] = stream_fanout_ns_.load();
    out[4] = stream_frames_.load();
    out[5] = streams_started_.load();
    out[6] = stream_bytes_.load();
    out[7] = stream_aborts_.load();
  }

  // ------------------------------------------------------------ qos plane

  // Parse + install a QoS config pushed from Python (resilience.
  // qos_wire_config() as a msgpack flat map — scalars and string arrays
  // only, which is all parse_header reads). Unknown keys are ignored; a
  // map with enabled=0 switches admission off for subsequent requests.
  void qos_configure(const uint8_t* buf, uint64_t len) {
    std::map<std::string, Value> h;
    if (!parse_header(buf, static_cast<size_t>(len), &h)) return;
    auto num = [&](const char* key, double dflt) {
      auto it = h.find(key);
      if (it == h.end()) return dflt;
      if (it->second.kind == Value::FLT) return it->second.f;
      if (it->second.kind == Value::INT)
        return static_cast<double>(it->second.i);
      return dflt;
    };
    QosConfig cfg;
    cfg.enabled = num("enabled", 0) != 0;
    cfg.max_inflight = static_cast<int64_t>(num("max_inflight", 64));
    cfg.base_retry_after = num("base_retry_after", 0.1);
    cfg.rate = num("rate", 0.0);
    cfg.burst = num("burst", 1.0);
    cfg.queue_depth =
        static_cast<int64_t>(num("queue_depth", kQosQueueDepthDefault));
    cfg.queue_wait = num("queue_wait", 0.25);
    cfg.default_weight = num("default_weight", 1.0);
    auto wit = h.find("weights");
    if (wit != h.end() && wit->second.kind == Value::ASTR) {
      // Weights travel flat as "tenant=weight" strings (the header
      // parser has no nested maps); split on the LAST '=' so tenant
      // names containing '=' still round-trip.
      for (const auto& pair : wit->second.astr) {
        size_t eq = pair.rfind('=');
        if (eq == std::string::npos || eq == 0) continue;
        cfg.weights[pair.substr(0, eq)] =
            std::strtod(pair.c_str() + eq + 1, nullptr);
      }
    }
    uint64_t seed = 0;
    auto sit = h.find("jitter_seed");
    if (sit != h.end() && sit->second.kind == Value::INT)
      seed = static_cast<uint64_t>(sit->second.i);
    qos_.configure(cfg, seed);
  }

  void qos_stats(uint64_t out[8]) { qos_.stats(out); }
  int64_t take_qos(char* buf, uint64_t cap) { return qos_.take(buf, cap); }

  // ------------------------------------------------------ LRU block cache

  using CacheData = std::shared_ptr<std::vector<uint8_t>>;

  CacheData cache_get(const std::string& id) {
    if (!cache_cap_) return nullptr;
    std::lock_guard<std::mutex> g(cache_mu_);
    auto it = cache_map_.find(id);
    if (it == cache_map_.end()) {
      cache_misses_.fetch_add(1);
      return nullptr;
    }
    cache_list_.splice(cache_list_.begin(), cache_list_, it->second);
    cache_hits_.fetch_add(1);
    return it->second->second;
  }

  // Invalidation generation for the insert-vs-invalidate race: a reader
  // captures cache_gen(id) BEFORE its pread; cache_put only inserts if no
  // invalidation landed in between (checked under cache_mu_, so an
  // invalidate can never slip between the check and the insert — the
  // re-stat signature alone leaves a window between its stat and the
  // put).
  uint64_t cache_gen(const std::string& id) {
    if (!cache_cap_) return 0;
    std::lock_guard<std::mutex> g(cache_mu_);
    auto it = inval_gen_.find(id);
    return it == inval_gen_.end() ? gen_floor_ : it->second;
  }

  void cache_put(const std::string& id, CacheData data, uint64_t gen) {
    if (!cache_cap_) return;
    std::lock_guard<std::mutex> g(cache_mu_);
    auto git = inval_gen_.find(id);
    if ((git == inval_gen_.end() ? gen_floor_ : git->second) != gen)
      return;  // a write/invalidate raced the read: don't pin old bytes
    auto it = cache_map_.find(id);
    if (it != cache_map_.end()) {
      it->second->second = std::move(data);
      cache_list_.splice(cache_list_.begin(), cache_list_, it->second);
      return;
    }
    cache_list_.emplace_front(id, std::move(data));
    cache_map_[id] = cache_list_.begin();
    while (cache_list_.size() > cache_cap_) {
      cache_map_.erase(cache_list_.back().first);
      cache_list_.pop_back();
    }
  }

  void cache_invalidate(const std::string& id) {
    if (!cache_cap_) return;
    std::lock_guard<std::mutex> g(cache_mu_);
    // Bound the generation map. Generations come from one monotone
    // counter and a clear raises the floor past every value ever issued,
    // so an id evicted from the map can never REUSE a generation a
    // concurrent reader captured earlier (a plain per-id counter reset
    // to zero could: capture 0 -> invalidate -> clear -> absent reads 0
    // again and the stale cache_put would pass).
    if (inval_gen_.size() > 65536) {
      inval_gen_.clear();
      gen_floor_ = ++gen_counter_;
    }
    inval_gen_[id] = ++gen_counter_;
    auto it = cache_map_.find(id);
    if (it != cache_map_.end()) {
      cache_list_.erase(it->second);
      cache_map_.erase(it);
    }
  }

  // Write-vs-read race guard for cache inserts: a block republished
  // between the pread and the cache_put must NOT be cached from the old
  // bytes (the concurrent writer's invalidate would land before our
  // insert, pinning stale data until the next write). The publish is a
  // rename (new inode), so re-statting and comparing (inode, mtime, size)
  // from before the read detects it — the same signature discipline the
  // Python service's cache uses (service.py _block_sig).
  static bool same_sig(const struct stat& a, const struct stat& b) {
    return a.st_ino == b.st_ino && a.st_size == b.st_size &&
           a.st_mtim.tv_sec == b.st_mtim.tv_sec &&
           a.st_mtim.tv_nsec == b.st_mtim.tv_nsec;
  }

 private:
  // ------------------------------------------------------------- accept

  void accept_loop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (errno == EINTR) continue;
        return;  // listener closed
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      tune_buffers(fd);
      {
        std::lock_guard<std::mutex> g(conns_mu_);
        conns_.insert(fd);
      }
      active_.fetch_add(1);
      std::thread([this, fd] {
        Stream s{fd, nullptr};
        bool handshake_ok = true;
        if (srv_ctx_ != nullptr) {
          const SslApi* api = ssl_api();
          s.ssl = api->ssl_new(srv_ctx_);
          handshake_ok = s.ssl != nullptr && api->set_fd(s.ssl, fd) == 1 &&
                         api->accept(s.ssl) == 1;
        }
        if (handshake_ok) conn_loop(s);
        s.free_ssl();
        {
          std::lock_guard<std::mutex> g2(conns_mu_);
          conns_.erase(fd);
        }
        ::close(fd);
        active_.fetch_sub(1);
      }).detach();
    }
  }

  void conn_loop(Stream& s) {
    // Per-connection cache of downstream chain streams.
    std::map<std::string, Stream> downstream;
    while (running_.load()) {
      std::map<std::string, Value> h;
      std::vector<uint8_t> payload;
      if (!recv_frame(s, &h, &payload)) break;
      const std::string method = h.count("m") ? h["m"].s : "";
      bool has_data = h.count("_d") && h["_d"].i;
      const bool known =
          method == "WriteBlock" || method == "ReplicateBlock" ||
          method == "WriteStream" || method == "ReadBlock" ||
          method == "ReadBlocks";
      if (!known) {
        respond_err(s, "UNIMPLEMENTED",
                    "no native blockport method " + method);
        continue;
      }
      // Central pre-execution deadline gate — the twin of
      // blocknet.BlockPortServer._handle's _db check, message included:
      // an already-expired budget is refused before admission charges
      // the QoS plane (or any handler touches the disk) for doomed work.
      double budget = 0.0;
      const bool has_db = deadline_budget(h, &budget);
      if (has_db && budget <= 0) {
        respond_err(s, "DEADLINE_EXCEEDED",
                    "deadline budget exhausted before blockport " + method +
                        " executed");
        continue;
      }
      const std::string tenant =
          (h.count("_tn") && !h["_tn"].s.empty()) ? h["_tn"].s : "system";
      bool admitted = false;
      uint64_t t_admit = 0;
      if (qos_.enabled()) {
        std::string detail;
        double retry_after = 0.0;
        if (!qos_.acquire(tenant, has_db, budget, &detail, &retry_after)) {
          respond_shed(s, tenant, detail, retry_after);
          continue;
        }
        admitted = true;
        t_admit = now_ns();
      }
      bool keep = true;
      if (method == "WriteBlock" || method == "ReplicateBlock") {
        handle_write(s, h, has_data ? &payload : nullptr, &downstream);
      } else if (method == "WriteStream") {
        // false = the stream aborted after the ready ack: pipelined
        // frames may still sit unread in the socket, so the request
        // boundary is lost and the connection must close.
        keep = handle_write_stream(s, h, &downstream);
      } else if (method == "ReadBlock") {
        handle_read(s, h);
      } else {
        handle_read_batch(s, h);
      }
      if (admitted)
        qos_.release(tenant,
                     static_cast<double>(now_ns() - t_admit) * 1e-9);
      if (!keep) break;
    }
    for (auto& kv : downstream) close_downstream(kv.second);
  }

  void close_downstream(Stream& d) {
    {
      std::lock_guard<std::mutex> g(conns_mu_);
      conns_.erase(d.fd);
    }
    d.free_ssl();
    ::close(d.fd);
    d.fd = -1;
  }

  // ------------------------------------------------------------ replies

  void respond_err(Stream& s, const std::string& code, const std::string& msg) {
    errors_.fetch_add(1);
    Writer w;
    w.map_head(3);
    w.str("ok");
    w.boolean(false);
    w.str("code");
    w.str(code);
    w.str("message");
    w.str(msg);
    send_frame(s, w.out, nullptr, 0);
  }

  // QoS refusal frame. Message parity with resilience.overloaded_message
  // as raised by admission_controlled — "Overloaded|<hint>|ChunkServer
  // <detail> (tenant=<t>)" — so client.py's text parser finds the hint,
  // and the explicit retry_after key is the structured twin blocknet.py
  // reads first.
  void respond_shed(Stream& s, const std::string& tenant,
                    const std::string& detail, double retry_after) {
    errors_.fetch_add(1);
    char hint[32];
    std::snprintf(hint, sizeof(hint), "%.3f", retry_after);
    Writer w;
    w.map_head(4);
    w.str("ok");
    w.boolean(false);
    w.str("code");
    w.str("RESOURCE_EXHAUSTED");
    w.str("message");
    w.str(std::string("Overloaded|") + hint + "|ChunkServer " + detail +
          " (tenant=" + tenant + ")");
    w.str("retry_after");
    w.flt(retry_after);
    send_frame(s, w.out, nullptr, 0);
  }

  void respond_write(Stream& s, bool success, const std::string& err,
                     int64_t replicas) {
    Writer w;
    w.map_head(4);
    w.str("ok");
    w.boolean(true);
    w.str("success");
    w.boolean(success);
    w.str("error_message");
    w.str(err);
    w.str("replicas_written");
    w.uint(static_cast<uint64_t>(replicas));
    send_frame(s, w.out, nullptr, 0);
  }

  // -------------------------------------------------------------- write

  void handle_write(Stream& s, std::map<std::string, Value>& h,
                    std::vector<uint8_t>* data,
                    std::map<std::string, Stream>* downstream) {
    writes_.fetch_add(1);
    const std::string block_id =
        h.count("block_id") ? h["block_id"].s : "";
    if (block_id.empty() || block_id[0] == '.' ||
        block_id.find('/') != std::string::npos || data == nullptr) {
      respond_err(s, "INVALID_ARGUMENT", "bad block id or missing data");
      return;
    }
    // QoS parity with the asyncio blockport: an already-expired deadline
    // budget is rejected before any disk work, and the remaining budget /
    // tenant header ride every chain hop (computed at the forward below).
    double budget = 0.0;
    const bool has_db = deadline_budget(h, &budget);
    if (has_db && budget <= 0) {
      respond_err(s, "DEADLINE_EXCEEDED",
                  "deadline budget exhausted before WriteBlock executed");
      return;
    }
    const uint64_t t_recv = now_ns();
    uint64_t req_term =
        h.count("master_term") ? static_cast<uint64_t>(h["master_term"].i) : 0;
    const std::string shard =
        h.count("master_shard") ? h["master_shard"].s : "";
    uint64_t known = term(shard);
    if (req_term > 0 && req_term < known) {
      respond_err(s, "FAILED_PRECONDITION",
                  "Stale master term: request has " +
                      std::to_string(req_term) + " but known term is " +
                      std::to_string(known));
      return;
    }
    if (req_term > known) set_term(shard, req_term);

    uint64_t expected =
        h.count("expected_crc32c")
            ? static_cast<uint64_t>(h["expected_crc32c"].i)
            : 0;
    if (expected != 0) {
      uint32_t actual = tpudfs_crc32c(0, data->data(), data->size());
      if (actual != static_cast<uint32_t>(expected)) {
        respond_write(s, false,
                      "Checksum mismatch: expected " +
                          std::to_string(expected) + ", actual " +
                          std::to_string(actual),
                      0);
        return;
      }
    }

    // Kick the downstream forward BEFORE the local durable write (the
    // overlapped pipeline the Python handler uses; in-flight CRC above
    // means forwarding can't propagate corruption).
    std::vector<std::string> next =
        h.count("next_servers") ? h["next_servers"].astr
                                : std::vector<std::string>{};
    std::vector<int64_t> next_ports =
        h.count("next_data_ports") ? h["next_data_ports"].aint
                                   : std::vector<int64_t>{};
    Stream* fwd = nullptr;
    std::string fwd_err;
    if (!next.empty()) {
      int64_t port = !next_ports.empty() ? next_ports[0] : 0;
      if (port <= 0) {
        fwd_err = "downstream " + next[0] + " has no data port";
      } else {
        std::string host = next[0].substr(0, next[0].rfind(':'));
        std::string key = host + ":" + std::to_string(port);
        double db_left = budget - (now_ns() - t_recv) * 1e-9;
        fwd = forward_request(downstream, key, host,
                              static_cast<uint16_t>(port), h, next,
                              next_ports, *data, has_db, db_left, &fwd_err);
      }
    }

    // Stage + group commit (ack only after durable). Any write attempt
    // invalidates the cached copy — the publish rename may have replaced
    // the bytes a cached reader would otherwise keep serving.
    std::string err;
    bool ok = stage_and_commit(block_id, *data, &err);
    cache_invalidate(block_id);

    int64_t replicas = ok ? 1 : 0;
    if (fwd != nullptr) {
      forwards_.fetch_add(1);
      std::map<std::string, Value> fh;
      std::vector<uint8_t> fp;
      uint64_t ta = now_ns();
      bool got = recv_frame(*fwd, &fh, &fp);
      fwd_ack_ns_.fetch_add(now_ns() - ta);
      if (got && fh.count("ok") && fh["ok"].b &&
          fh.count("success") && fh["success"].b) {
        replicas += fh.count("replicas_written") ? fh["replicas_written"].i : 0;
      } else {
        // Downstream failure: drop the cached stream (unknown state).
        for (auto it = downstream->begin(); it != downstream->end(); ++it) {
          if (&it->second == fwd) {
            close_downstream(it->second);
            downstream->erase(it);
            break;
          }
        }
      }
    }
    if (!ok) {
      respond_write(s, false, err, replicas);
      return;
    }
    respond_write(s, true, fwd_err, replicas);
  }

  // ------------------------------------------------ streaming write path
  //
  // WriteStream: the block arrives as sub-block frames (protocol spec:
  // tpudfs/common/writestream.py) and is CRC-folded, staged, and fanned
  // out hop-by-hop without ever materializing in memory. Stage overlap:
  // this (receiver) thread runs net read -> CRC fold -> fanout send over
  // a small ring of reusable frame buffers, a per-stream writer thread
  // drains the ring to the staged file, and the shared commit thread
  // makes the block durable (group commit) before the final ack.
  // Returns false when the connection must close: any post-ready failure
  // leaves pipelined frames unread in the socket, so the request boundary
  // is lost. Pre-ready rejections answer an error frame and return true
  // (the connection stays poolable).
  bool handle_write_stream(Stream& s, std::map<std::string, Value>& h,
                           std::map<std::string, Stream>* downstream) {
    writes_.fetch_add(1);
    const std::string block_id =
        h.count("block_id") ? h["block_id"].s : "";
    if (block_id.empty() || block_id[0] == '.' ||
        block_id.find('/') != std::string::npos) {
      respond_err(s, "INVALID_ARGUMENT", "bad block id");
      return true;
    }
    uint64_t req_term =
        h.count("master_term") ? static_cast<uint64_t>(h["master_term"].i) : 0;
    const std::string shard =
        h.count("master_shard") ? h["master_shard"].s : "";
    uint64_t known = term(shard);
    if (req_term > 0 && req_term < known) {
      respond_err(s, "FAILED_PRECONDITION",
                  "Stale master term: request has " +
                      std::to_string(req_term) + " but known term is " +
                      std::to_string(known));
      return true;
    }
    if (req_term > known) set_term(shard, req_term);
    int64_t size_i = h.count("size") ? h["size"].i : -1;
    int64_t fsz_i = h.count("frame_size") ? h["frame_size"].i : 0;
    if (size_i < 0 || fsz_i <= 0 ||
        static_cast<uint64_t>(size_i) > kMaxStreamBytes ||
        static_cast<uint64_t>(fsz_i) > kMaxPayload) {
      respond_err(s, "INVALID_ARGUMENT", "bad stream size or frame_size");
      return true;
    }
    const uint64_t size = static_cast<uint64_t>(size_i);
    const uint64_t frame_size = static_cast<uint64_t>(fsz_i);
    const uint64_t nframes =
        std::max<uint64_t>(1, (size + frame_size - 1) / frame_size);
    const uint32_t expected =
        h.count("expected_crc32c")
            ? static_cast<uint32_t>(h["expected_crc32c"].i)
            : 0;
    double budget = 0.0;
    const bool has_db = deadline_budget(h, &budget);
    if (has_db && budget <= 0) {
      respond_err(s, "DEADLINE_EXCEEDED",
                  "deadline budget exhausted before WriteStream started");
      return true;
    }
    const uint64_t t_start = now_ns();
    const uint64_t deadline_ns =
        has_db ? t_start + static_cast<uint64_t>(budget * 1e9) : 0;
    const std::string qos_tenant =
        (h.count("_tn") && !h["_tn"].s.empty()) ? h["_tn"].s : "system";

    // Open the staged file before acking ready; a failure here is still a
    // clean in-sync rejection.
    uint64_t token = token_seq_.fetch_add(1);
    std::string base = hot_ + "/" + block_id;
    auto entry = std::make_shared<CommitEntry>();
    entry->data_tmp = base + ".tmp-n" + std::to_string(token);
    entry->meta_tmp = base + ".meta.tmp-n" + std::to_string(token);
    entry->data_final = base;
    entry->meta_final = base + ".meta";
    int dfd = ::open(entry->data_tmp.c_str(),
                     O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (dfd < 0) {
      respond_err(s, "INTERNAL",
                  "stage open failed: " + std::string(::strerror(errno)));
      return true;
    }

    // Relay the stream when the next hop has a data port; port 0 or any
    // begin failure degrades like a dead tail (healer repairs) while the
    // local replica still lands. Downstream acks are deliberately NOT
    // read until the tail drain below — they are tiny (one watermark per
    // kAckEvery frames) and fit in socket buffers, so deferring them
    // keeps this thread off the ack path while frames flow.
    std::vector<std::string> next =
        h.count("next_servers") ? h["next_servers"].astr
                                : std::vector<std::string>{};
    std::vector<int64_t> next_ports =
        h.count("next_data_ports") ? h["next_data_ports"].aint
                                   : std::vector<int64_t>{};
    Stream* fwd = nullptr;
    std::string fwd_key;
    if (!next.empty() && !next_ports.empty() && next_ports[0] > 0) {
      std::string host = next[0].substr(0, next[0].rfind(':'));
      fwd_key = host + ":" + std::to_string(next_ports[0]);
      std::string dial_err;
      fwd = open_downstream(downstream, fwd_key, host,
                            static_cast<uint16_t>(next_ports[0]), &dial_err);
      if (fwd != nullptr) {
        forwards_.fetch_add(1);
        const std::string tenant = h.count("_tn") ? h["_tn"].s : "";
        Writer w;
        w.map_head(9 + (has_db ? 1 : 0) + (tenant.empty() ? 0 : 1));
        w.str("m");
        w.str("WriteStream");
        w.str("block_id");
        w.str(block_id);
        w.str("size");
        w.uint(size);
        w.str("frame_size");
        w.uint(frame_size);
        w.str("expected_crc32c");
        w.uint(expected);
        w.str("master_term");
        w.uint(req_term);
        w.str("master_shard");
        w.str(shard);
        w.str("next_servers");
        w.astr(std::vector<std::string>(next.begin() + 1, next.end()));
        w.str("next_data_ports");
        w.aint(next_ports.size() > 1
                   ? std::vector<int64_t>(next_ports.begin() + 1,
                                          next_ports.end())
                   : std::vector<int64_t>{});
        if (has_db) {
          w.str("_db");
          w.flt(budget - (now_ns() - t_start) * 1e-9);
        }
        if (!tenant.empty()) {
          w.str("_tn");
          w.str(tenant);
        }
        std::map<std::string, Value> rh;
        std::vector<uint8_t> rp;
        if (!send_frame(*fwd, w.out, nullptr, 0) ||
            !recv_frame(*fwd, &rh, &rp)) {
          close_downstream(*fwd);
          downstream->erase(fwd_key);
          fwd = nullptr;
        } else if (!(rh.count("ready") && rh["ready"].i)) {
          // Clean pre-ready rejection (e.g. an ICI collective member or
          // an older peer): the downstream connection stays in sync, so
          // keep it pooled and just skip the relay.
          fwd = nullptr;
        }
      }
    }

    {
      Writer w;
      w.map_head(2);
      w.str("ok");
      w.boolean(true);
      w.str("ready");
      w.uint(1);
      if (!send_frame(s, w.out, nullptr, 0)) {
        ::close(dfd);
        ::unlink(entry->data_tmp.c_str());
        if (fwd != nullptr) {
          close_downstream(*fwd);
          downstream->erase(fwd_key);
        }
        return false;
      }
    }
    streams_started_.fetch_add(1);

    // Ring of reusable frame buffers between this thread and the disk
    // writer thread; a slot is refilled only after its disk write
    // finished, so net/CRC/fanout of frame N overlap the write of N-1.
    constexpr size_t kRing = 4;
    struct Slot {
      std::vector<uint8_t> buf;
      uint64_t len = 0;
    };
    Slot ring[kRing];
    for (auto& sl : ring) sl.buf.resize(frame_size);
    std::mutex ring_mu;
    std::condition_variable ring_free_cv, ring_full_cv;
    size_t ring_head = 0, ring_tail = 0, ring_count = 0;
    bool ring_done = false, disk_failed = false;
    std::thread disk([&] {
      std::unique_lock<std::mutex> lk(ring_mu);
      for (;;) {
        ring_full_cv.wait(lk, [&] { return ring_count > 0 || ring_done; });
        if (ring_count == 0) return;
        Slot& sl = ring[ring_tail];
        bool prior_fail = disk_failed;
        lk.unlock();
        uint64_t t0 = now_ns();
        bool wrote =
            !prior_fail && write_fd_all(dfd, sl.buf.data(), sl.len);
        stream_disk_ns_.fetch_add(now_ns() - t0);
        lk.lock();
        if (!wrote) disk_failed = true;
        ring_tail = (ring_tail + 1) % kRing;
        ring_count--;
        ring_free_cv.notify_one();
      }
    });

    // Per-chunk sidecar CRCs carry across frame boundaries; the
    // whole-block CRC is folded from per-frame CRCs via the GF(2)
    // combine — one CRC pass per cache-hot frame, none over the
    // assembled block.
    std::vector<uint32_t> sums;
    sums.reserve(size / chunk_ + 2);
    uint32_t carry_crc = 0;
    uint64_t carry_len = 0;
    uint32_t whole = 0;
    uint32_t op_frame[32];
    crc_zero_operator(frame_size, op_frame);

    bool torn = false;
    std::string err_code, err_msg;
    uint64_t received = 0;
    for (uint64_t seq = 0; seq < nframes; seq++) {
      if (has_db && now_ns() > deadline_ns) {
        err_code = "DEADLINE_EXCEEDED";
        err_msg = "deadline budget exhausted at frame " +
                  std::to_string(seq);
        break;
      }
      // Mid-stream shed: the force_shed failpoint (re-armed by a config
      // re-push while this stream is in flight) aborts an ADMITTED
      // stream between frames — the rpc_write_stream twin of the
      // per-frame deadline abort above, driving the client's Overloaded
      // retry path from inside a stream.
      double shed_after = 0.0;
      if (qos_.shed_frame(qos_tenant, &shed_after)) {
        char hint[32];
        std::snprintf(hint, sizeof(hint), "%.3f", shed_after);
        err_code = "RESOURCE_EXHAUSTED";
        err_msg = std::string("Overloaded|") + hint +
                  "|ChunkServer stream shed at frame " +
                  std::to_string(seq) + " (tenant=" + qos_tenant + ")";
        break;
      }
      Slot* sl;
      {
        std::unique_lock<std::mutex> lk(ring_mu);
        ring_free_cv.wait(lk, [&] { return ring_count < kRing; });
        if (disk_failed) {
          err_code = "INTERNAL";
          err_msg = "staged stream write failed";
          break;
        }
        sl = &ring[ring_head];
      }
      uint64_t t0 = now_ns();
      std::map<std::string, Value> fh;
      uint64_t plen = 0;
      if (!recv_frame_into(s, &fh, sl->buf.data(), frame_size, &plen)) {
        torn = true;
        break;
      }
      uint64_t t1 = now_ns();
      stream_net_ns_.fetch_add(t1 - t0);
      uint64_t want = std::min(frame_size, size - received);
      int64_t fseq = fh.count("q") ? fh["q"].i : -1;
      if (static_cast<uint64_t>(fseq) != seq ||
          !(fh.count("_d") && fh["_d"].i) || plen != want) {
        err_code = "INVALID_ARGUMENT";
        err_msg = "unexpected frame " + std::to_string(fseq) +
                  " (want " + std::to_string(seq) + ")";
        break;
      }
      uint32_t fcrc = tpudfs_crc32c(0, sl->buf.data(), plen);
      uint32_t want_crc =
          fh.count("c") ? static_cast<uint32_t>(fh["c"].i) : 0;
      if (fcrc != want_crc) {
        err_code = "DATA_LOSS";
        err_msg = "frame " + std::to_string(seq) +
                  " CRC mismatch; staged block " + block_id +
                  " quarantined";
        break;
      }
      if (seq == 0) {
        whole = fcrc;
      } else if (plen == frame_size) {
        whole = crc_matrix_times(op_frame, whole) ^ fcrc;
      } else {
        uint32_t op_tail[32];
        crc_zero_operator(plen, op_tail);
        whole = crc_matrix_times(op_tail, whole) ^ fcrc;
      }
      uint64_t off = 0;
      if (carry_len) {
        uint64_t take = std::min<uint64_t>(chunk_ - carry_len, plen);
        carry_crc = tpudfs_crc32c(carry_crc, sl->buf.data(), take);
        carry_len += take;
        off = take;
        if (carry_len == chunk_) {
          sums.push_back(carry_crc);
          carry_crc = 0;
          carry_len = 0;
        }
      }
      while (off + chunk_ <= plen) {
        sums.push_back(tpudfs_crc32c(0, sl->buf.data() + off, chunk_));
        off += chunk_;
      }
      if (off < plen) {
        carry_crc = tpudfs_crc32c(0, sl->buf.data() + off, plen - off);
        carry_len = plen - off;
      }
      uint64_t t2 = now_ns();
      stream_crc_ns_.fetch_add(t2 - t1);
      // Fan out before handing the slot to the disk stage (the slot is
      // reused only after its disk write, so the send reads stable bytes).
      if (fwd != nullptr) {
        Writer w;
        w.map_head(3);
        w.str("q");
        w.uint(seq);
        w.str("c");
        w.uint(fcrc);
        w.str("_d");
        w.uint(1);
        if (!send_frame(*fwd, w.out, sl->buf.data(), plen)) {
          // Downstream died mid-stream: degrade like a dead tail, keep
          // the local replica going.
          close_downstream(*fwd);
          downstream->erase(fwd_key);
          fwd = nullptr;
        }
      }
      uint64_t t3 = now_ns();
      stream_fanout_ns_.fetch_add(t3 - t2);
      {
        std::lock_guard<std::mutex> lk(ring_mu);
        sl->len = plen;
        ring_head = (ring_head + 1) % kRing;
        ring_count++;
      }
      ring_full_cv.notify_one();
      received += plen;
      stream_frames_.fetch_add(1);
      stream_bytes_.fetch_add(plen);
      // Group-committed acks: per-frame progress coalesces into watermark
      // acks; the covering ack for the last frames is the final frame,
      // sent only after the durable commit below.
      if ((seq + 1) % kAckEvery == 0 && seq + 1 < nframes) {
        Writer w;
        w.map_head(2);
        w.str("ok");
        w.boolean(true);
        w.str("w");
        w.uint(seq + 1);
        if (!send_frame(s, w.out, nullptr, 0)) {
          torn = true;
          break;
        }
      }
    }

    // Drain the disk stage before touching the staged file.
    {
      std::lock_guard<std::mutex> lk(ring_mu);
      ring_done = true;
    }
    ring_full_cv.notify_all();
    disk.join();
    ::close(dfd);

    auto scrap = [&] {
      stream_aborts_.fetch_add(1);
      ::unlink(entry->data_tmp.c_str());
      ::unlink(entry->meta_tmp.c_str());
      if (fwd != nullptr) {
        // Tear the relay too so the abort propagates down the chain.
        close_downstream(*fwd);
        downstream->erase(fwd_key);
        fwd = nullptr;
      }
    };
    if (torn) {  // transport tear: nobody left to answer
      scrap();
      return false;
    }
    if (!err_code.empty()) {
      scrap();
      respond_err(s, err_code, err_msg);
      return false;
    }
    if (disk_failed) {
      scrap();
      respond_err(s, "INTERNAL", "staged stream write failed");
      return false;
    }

    if (carry_len) sums.push_back(carry_crc);
    bool success = true;
    std::string errmsg;
    if (expected != 0 && whole != expected) {
      // Every frame CRC-verified yet the whole disagrees (sender-side
      // corruption before framing): quarantine the staged bytes and
      // report a soft failure — all frames were consumed, so the
      // protocol stays in sync.
      ::unlink(entry->data_tmp.c_str());
      success = false;
      errmsg = "Checksum mismatch: expected " + std::to_string(expected) +
               ", actual " + std::to_string(whole);
    }
    if (success && !write_meta_tmp(entry->meta_tmp, chunk_, sums)) {
      ::unlink(entry->data_tmp.c_str());
      ::unlink(entry->meta_tmp.c_str());
      success = false;
      errmsg = "meta stage failed";
    }
    int64_t replicas = 0;
    if (success) {
      staged_bytes_.fetch_add(size);
      std::string cerr;
      if (commit_entry_and_wait(entry, &cerr)) {
        replicas = 1;
      } else {
        success = false;
        errmsg = cerr;
      }
      cache_invalidate(block_id);
    }

    if (fwd != nullptr) {
      // Drain the relay's coalesced watermarks down to its final verdict
      // (sent only after ITS durable commit and its own tail's final).
      uint64_t ta = now_ns();
      for (;;) {
        std::map<std::string, Value> ah;
        std::vector<uint8_t> ap;
        if (!recv_frame(*fwd, &ah, &ap)) {
          close_downstream(*fwd);
          downstream->erase(fwd_key);
          fwd = nullptr;
          break;
        }
        if (ah.count("final") && ah["final"].i) {
          if (ah.count("success") && ah["success"].b)
            replicas +=
                ah.count("replicas_written") ? ah["replicas_written"].i : 0;
          break;
        }
        if (!(ah.count("ok") && ah["ok"].b)) {
          // Error frame ends the downstream stream; the peer closes.
          close_downstream(*fwd);
          downstream->erase(fwd_key);
          fwd = nullptr;
          break;
        }
      }
      fwd_ack_ns_.fetch_add(now_ns() - ta);
    }

    // Final group-commit ack: the watermark covers the whole block and
    // the local replica (plus everything downstream reported) is durable.
    Writer w;
    w.map_head(6);
    w.str("ok");
    w.boolean(true);
    w.str("final");
    w.uint(1);
    w.str("w");
    w.uint(nframes);
    w.str("success");
    w.boolean(success);
    w.str("error_message");
    w.str(errmsg);
    w.str("replicas_written");
    w.uint(static_cast<uint64_t>(replicas));
    return send_frame(s, w.out, nullptr, 0);
  }

  // Dial (or reuse) the per-connection downstream stream for `key`,
  // including the outbound TLS policy (never plaintext off a secured
  // listener). Shared by the whole-block forward and the stream relay.
  Stream* open_downstream(std::map<std::string, Stream>* downstream,
                          const std::string& key, const std::string& host,
                          uint16_t port, std::string* err) {
    auto it = downstream->find(key);
    if (it == downstream->end()) {
      int dfd = dial(host, port);
      if (dfd < 0) {
        *err = "dial " + key + " failed";
        return nullptr;
      }
      Stream d{dfd, nullptr};
      if (cli_ctx_ != nullptr) {
        // TLS to the downstream peer, with the same target-name
        // verification the Python BlockConnPool applies (hostname or IP
        // SAN must match the dialed host).
        const SslApi* api = ssl_api();
        d.ssl = api->ssl_new(cli_ctx_);
        bool ok = d.ssl != nullptr && api->set_fd(d.ssl, dfd) == 1;
        if (ok) {
          in_addr tmp;
          if (::inet_pton(AF_INET, host.c_str(), &tmp) == 1)
            ok = api->param_set1_ip_asc(api->get0_param(d.ssl),
                                        host.c_str()) == 1;
          else
            ok = api->set1_host(d.ssl, host.c_str()) == 1;
        }
        ok = ok && api->connect(d.ssl) == 1 &&
             api->verify_result(d.ssl) == 0;
        if (!ok) {
          d.free_ssl();
          ::close(dfd);
          *err = "tls to " + key + " failed";
          return nullptr;
        }
      } else if (srv_ctx_ != nullptr) {
        // Secured listener but no outbound material: never forward in
        // plaintext — degrade like a dead tail (healer repairs).
        ::close(dfd);
        *err = "no outbound TLS material for " + key;
        return nullptr;
      }
      it = downstream->emplace(key, d).first;
      // Registered so stop() can shutdown a thread blocked on the
      // downstream ack recv (up to SO_RCVTIMEO otherwise — long past
      // stop()'s drain window, a use-after-free).
      std::lock_guard<std::mutex> g(conns_mu_);
      conns_.insert(dfd);
    }
    return &it->second;
  }

  Stream* forward_request(std::map<std::string, Stream>* downstream,
                          const std::string& key, const std::string& host,
                          uint16_t port, std::map<std::string, Value>& h,
                          const std::vector<std::string>& next,
                          const std::vector<int64_t>& next_ports,
                          const std::vector<uint8_t>& data,
                          bool has_db, double db_left,
                          std::string* err) {
    Stream* d = open_downstream(downstream, key, host, port, err);
    if (d == nullptr) return nullptr;
    const std::string tenant = h.count("_tn") ? h["_tn"].s : "";
    Writer w;
    w.map_head(8 + (has_db ? 1 : 0) + (tenant.empty() ? 0 : 1));
    w.str("m");
    w.str("ReplicateBlock");
    w.str("_d");
    w.uint(1);
    w.str("block_id");
    w.str(h["block_id"].s);
    w.str("next_servers");
    w.astr(std::vector<std::string>(next.begin() + 1, next.end()));
    w.str("next_data_ports");
    w.aint(next_ports.size() > 1
               ? std::vector<int64_t>(next_ports.begin() + 1,
                                      next_ports.end())
               : std::vector<int64_t>{});
    w.str("expected_crc32c");
    w.uint(h.count("expected_crc32c")
               ? static_cast<uint64_t>(h["expected_crc32c"].i)
               : 0);
    w.str("master_term");
    w.uint(h.count("master_term") ? static_cast<uint64_t>(h["master_term"].i)
                                  : 0);
    w.str("master_shard");
    w.str(h.count("master_shard") ? h["master_shard"].s : "");
    if (has_db) {
      w.str("_db");
      w.flt(db_left);
    }
    if (!tenant.empty()) {
      w.str("_tn");
      w.str(tenant);
    }
    if (!send_frame(*d, w.out, data.data(), data.size())) {
      close_downstream(*d);
      downstream->erase(key);
      *err = "forward to " + key + " failed";
      return nullptr;
    }
    return d;
  }

  static int dial(const std::string& host, uint16_t port) {
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      // Hostname-addressed peer (the asyncio path resolves these too).
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (::getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 ||
          res == nullptr)
        return -1;
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      ::freeaddrinfo(res);
    }
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(fd);
      return -1;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    tune_buffers(fd);
    timeval tv{30, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    return fd;
  }

  static uint64_t now_ns() {
    return static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count());
  }

  bool stage_and_commit(const std::string& block_id,
                        const std::vector<uint8_t>& data, std::string* err) {
    uint64_t token = token_seq_.fetch_add(1);
    std::string base = hot_ + "/" + block_id;
    auto entry = std::make_shared<CommitEntry>();
    entry->data_tmp = base + ".tmp-n" + std::to_string(token);
    entry->meta_tmp = base + ".meta.tmp-n" + std::to_string(token);
    entry->data_final = base;
    entry->meta_final = base + ".meta";
    uint64_t t0 = now_ns();
    int64_t rc = tpudfs_block_write_staged(
        entry->data_tmp.c_str(), entry->meta_tmp.c_str(), data.data(),
        data.size(), chunk_, nullptr);
    stage_ns_.fetch_add(now_ns() - t0);
    staged_bytes_.fetch_add(data.size());
    if (rc < 0) {
      *err = "stage failed: errno " + std::to_string(-rc);
      return false;
    }
    return commit_entry_and_wait(entry, err);
  }

  // Queue a staged entry for the group-commit loop and block until its
  // verdict — shared tail of the whole-block and streaming write paths.
  bool commit_entry_and_wait(const std::shared_ptr<CommitEntry>& entry,
                             std::string* err) {
    uint64_t tq = now_ns();
    std::unique_lock<std::mutex> lk(commit_mu_);
    commit_queue_.push_back(entry);
    commit_cv_.notify_one();
    // Wake either when the commit loop resolved this entry, or when the
    // engine is stopping AND the entry is still queued — in the latter
    // case WE dequeue it (under the lock, so the loop can never also take
    // it) and unlink the staged tmps, making "engine stopping" a DEFINITE
    // failure. An entry already taken into an in-flight batch is past the
    // point of no return (the loop drains its batch before exiting), so
    // we keep waiting for its real verdict instead of reporting a write
    // failure for data that durably published.
    bool dequeued = false;
    commit_done_cv_.wait(lk, [&] {
      if (entry->done) return true;
      if (!running_.load()) {
        auto it = std::find(commit_queue_.begin(), commit_queue_.end(),
                            entry);
        if (it != commit_queue_.end()) {
          commit_queue_.erase(it);
          dequeued = true;
          return true;
        }
      }
      return false;
    });
    commit_wait_ns_.fetch_add(now_ns() - tq);
    if (dequeued) {
      ::unlink(entry->data_tmp.c_str());
      ::unlink(entry->meta_tmp.c_str());
      *err = "engine stopping";
      return false;
    }
    if (entry->failed) {
      *err = entry->error;
      return false;
    }
    return true;
  }

  void commit_loop() {
    // No artificial accumulation window: the write pipeline is a closed
    // latency loop (fixed client concurrency), so delaying commits to
    // widen batches proportionally lowers the arrival rate instead —
    // measured round 5 (BENCH_NOTES): a 6 ms window moved batches only
    // 1.7 -> 2.1 entries at equal throughput. The stage budgets put the
    // chain at 75-93% of the disk's sustained fdatasync rate already;
    // arrivals during an in-flight sync batch naturally.
    std::unique_lock<std::mutex> lk(commit_mu_);
    while (running_.load() || !commit_queue_.empty()) {
      if (commit_queue_.empty()) {
        // Predicated wait, not a 50 ms wait_for poll: stop() notifies
        // under commit_mu_ after flipping running_, so the wakeup cannot
        // be lost — and wait() stays on pthread_cond_wait, which the
        // TSan gate (scripts/native_sanitize.py) can model (glibc's
        // pthread_cond_clockwait behind wait_for has no interceptor and
        // corrupts its lock state, drowning real races in noise).
        commit_cv_.wait(lk, [&] {
          return !commit_queue_.empty() || !running_.load();
        });
        continue;
      }
      std::deque<std::shared_ptr<CommitEntry>> batch;
      batch.swap(commit_queue_);
      lk.unlock();
      // One filesystem sync makes every staged file durable, renames
      // publish, a second sync persists the renames (the group-commit
      // batch path of tpudfs/chunkserver/blockstore.py).
      uint64_t t0 = now_ns();
      tpudfs_syncfs(hot_.c_str());
      uint64_t t1 = now_ns();
      syncfs_ns_.fetch_add(t1 - t0);
      for (auto& e : batch) {
        if (::rename(e->data_tmp.c_str(), e->data_final.c_str()) != 0 ||
            ::rename(e->meta_tmp.c_str(), e->meta_final.c_str()) != 0) {
          e->failed = true;
          e->error = "publish rename failed: " +
                     std::string(::strerror(errno));
        }
      }
      uint64_t t2 = now_ns();
      rename_ns_.fetch_add(t2 - t1);
      tpudfs_syncfs(hot_.c_str());
      syncfs_ns_.fetch_add(now_ns() - t2);
      commit_batches_.fetch_add(1);
      commit_entries_.fetch_add(batch.size());
      lk.lock();
      for (auto& e : batch) e->done = true;
      commit_done_cv_.notify_all();
    }
    // Drain-out on stop: wake any stragglers (they dequeue + unlink their
    // own staged entries under the lock — see stage_and_commit).
    commit_done_cv_.notify_all();
  }

  // --------------------------------------------------------------- read

  void handle_read(Stream& s, std::map<std::string, Value>& h) {
    reads_.fetch_add(1);
    const std::string block_id =
        h.count("block_id") ? h["block_id"].s : "";
    if (block_id.empty() || block_id[0] == '.' ||
        block_id.find('/') != std::string::npos) {
      respond_err(s, "INVALID_ARGUMENT", "bad block id");
      return;
    }
    uint64_t offset =
        h.count("offset") ? static_cast<uint64_t>(h["offset"].i) : 0;
    uint64_t length =
        h.count("length") ? static_cast<uint64_t>(h["length"].i) : 0;
    // Cache first: a hit serves straight from memory (bytes were verified
    // when cached; writes/corruption findings invalidate). Range reads
    // slice the cached block.
    if (CacheData cached = cache_get(block_id)) {
      uint64_t total = cached->size();
      if (offset >= total && !(offset == 0 && total == 0)) {
        respond_err(s, "OUT_OF_RANGE",
                    "Offset " + std::to_string(offset) +
                        " exceeds block size " + std::to_string(total));
        return;
      }
      uint64_t want = length == 0 ? total - offset
                                  : std::min(length, total - offset);
      Writer w;
      w.map_head(4);
      w.str("ok");
      w.boolean(true);
      w.str("_d");
      w.uint(1);
      w.str("bytes_read");
      w.uint(want);
      w.str("total_size");
      w.uint(total);
      send_frame(s, w.out, cached->data() + offset, want);
      return;
    }
    const uint64_t gen = cache_gen(block_id);  // before the pread
    std::string data_path = hot_ + "/" + block_id;
    struct stat st;
    if (::stat(data_path.c_str(), &st) != 0) {
      if (!cold_.empty()) {
        data_path = cold_ + "/" + block_id;
        if (::stat(data_path.c_str(), &st) != 0) {
          respond_err(s, "NOT_FOUND", "Block not found");
          return;
        }
      } else {
        respond_err(s, "NOT_FOUND", "Block not found");
        return;
      }
    }
    uint64_t total = static_cast<uint64_t>(st.st_size);
    if (length == 0) length = total > offset ? total - offset : 0;
    if (offset >= total && !(offset == 0 && total == 0)) {
      respond_err(s, "OUT_OF_RANGE",
                  "Offset " + std::to_string(offset) +
                      " exceeds block size " + std::to_string(total));
      return;
    }
    uint64_t want = std::min(length, total - offset);
    std::vector<uint8_t> buf(want);
    std::string meta_path = data_path + ".meta";
    int64_t rc = tpudfs_block_read_verify(
        data_path.c_str(), meta_path.c_str(), offset, want,
        buf.data(), 1, chunk_);
    if (rc == kCorrupt || rc < -200000) {
      // Corrupt or unreadable sidecar: flag for Python (heartbeat
      // bad-block report + recovery), serve the raw bytes for partial
      // reads (chunkserver.rs:893-911 parity) but fail full reads — the
      // caller's replica failover handles those.
      {
        std::lock_guard<std::mutex> g(bad_mu_);
        bad_.insert(block_id);
      }
      cache_invalidate(block_id);
      bool full = offset == 0 && want == total;
      if (full) {
        respond_err(s, "DATA_LOSS",
                    "Data corruption detected on native read");
        return;
      }
      rc = tpudfs_block_read_verify(data_path.c_str(), meta_path.c_str(),
                                    offset, want, buf.data(), 0, chunk_);
      if (rc < 0) {
        respond_err(s, "INTERNAL", "read failed after verify failure");
        return;
      }
    } else if (rc < 0) {
      respond_err(s, rc == -ENOENT ? "NOT_FOUND" : "INTERNAL",
                  rc == -ENOENT ? "Block not found"
                                : "native read error " + std::to_string(-rc));
      return;
    }
    CacheData keep;
    if (rc >= 0 && offset == 0 && want == total) {
      // Full block, freshly verified: cache for repeated readers — unless
      // a concurrent publish replaced the file mid-read (see same_sig).
      // Moving buf avoids a full-block copy on every miss; the response
      // is sent from the cached vector.
      struct stat st2;
      if (::stat(data_path.c_str(), &st2) == 0 && same_sig(st, st2)) {
        keep = std::make_shared<std::vector<uint8_t>>(std::move(buf));
        cache_put(block_id, keep, gen);
      }
    }
    Writer w;
    w.map_head(4);
    w.str("ok");
    w.boolean(true);
    w.str("_d");
    w.uint(1);
    w.str("bytes_read");
    w.uint(static_cast<uint64_t>(rc));
    w.str("total_size");
    w.uint(total);
    send_frame(s, w.out, keep ? keep->data() : buf.data(),
               static_cast<uint64_t>(rc));
  }

  // Batched UNVERIFIED full reads: header {"block_ids": [...]}; response
  // header carries "sizes" (bytes per slot, -1 = missing/unreadable/
  // over-budget — the caller falls back per block) and the payload
  // concatenates the successful blocks in request order. One frame
  // replaces N round trips for a remote reader's fused round. No sidecar
  // verify here: every consumer (the combiner's remote rounds)
  // re-verifies end-to-end against the recorded whole-block checksum and
  // routes mismatches to the per-block VERIFIED path, which detects the
  // rot, reports it, and triggers recovery.
  void handle_read_batch(Stream& s, std::map<std::string, Value>& h) {
    const std::vector<std::string> ids =
        h.count("block_ids") ? h["block_ids"].astr
                             : std::vector<std::string>{};
    std::vector<int64_t> sizes;
    std::vector<uint8_t> payload;
    sizes.reserve(ids.size());
    constexpr size_t kMaxSlots = 256;
    constexpr size_t kMaxBatchBytes = 96ull << 20;  // < 100 MiB frame caps
    // One allocation for the whole frame: growing block-by-block would
    // realloc-copy the accumulated payload several times per 16-48 MiB
    // round (round-5 remote-read budget).
    {
      size_t est = 0;
      struct stat st;
      for (const auto& block_id : ids) {
        if (est >= kMaxBatchBytes || block_id.empty()) continue;
        std::string p = hot_ + "/" + block_id;
        if (::stat(p.c_str(), &st) == 0 ||
            (!cold_.empty() &&
             ::stat((cold_ + "/" + block_id).c_str(), &st) == 0))
          est += static_cast<uint64_t>(st.st_size);
      }
      payload.reserve(est < kMaxBatchBytes ? est : kMaxBatchBytes);
    }
    for (const auto& block_id : ids) {
      reads_.fetch_add(1);
      if (sizes.size() >= kMaxSlots || payload.size() >= kMaxBatchBytes) {
        sizes.push_back(-1);  // over budget: caller falls back/re-requests
        continue;
      }
      if (block_id.empty() || block_id[0] == '.' ||
          block_id.find('/') != std::string::npos) {
        sizes.push_back(-1);
        continue;
      }
      if (CacheData cached = cache_get(block_id)) {
        if (payload.size() + cached->size() > kMaxBatchBytes) {
          sizes.push_back(-1);
          continue;
        }
        payload.insert(payload.end(), cached->begin(), cached->end());
        sizes.push_back(static_cast<int64_t>(cached->size()));
        continue;
      }
      std::string data_path = hot_ + "/" + block_id;
      struct stat st;
      if (::stat(data_path.c_str(), &st) != 0) {
        bool found = false;
        if (!cold_.empty()) {
          data_path = cold_ + "/" + block_id;
          found = ::stat(data_path.c_str(), &st) == 0;
        }
        if (!found) {
          sizes.push_back(-1);
          continue;
        }
      }
      uint64_t total = static_cast<uint64_t>(st.st_size);
      size_t base = payload.size();
      if (base + total > kMaxBatchBytes) {
        sizes.push_back(-1);
        continue;
      }
      payload.resize(base + total);
      // verify=0: every ReadBlocks consumer (the combiner's remote
      // rounds) re-verifies END-TO-END — host CRC against the recorded
      // whole-block checksum, or the on-device fold — and a mismatch
      // falls back to the per-block VERIFIED path, which detects rot,
      // reports it, and triggers recovery. A server-side sidecar verify
      // here would be a second full CRC pass on the hot sweep path.
      int64_t rc = tpudfs_block_read_verify(
          data_path.c_str(), (data_path + ".meta").c_str(), 0, total,
          payload.data() + base, 0, chunk_);
      if (rc < 0 || static_cast<uint64_t>(rc) != total) {
        payload.resize(base);
        sizes.push_back(-1);
        if (rc <= -200000) {
          {
            std::lock_guard<std::mutex> g(bad_mu_);
            bad_.insert(block_id);
          }
          cache_invalidate(block_id);
        }
        continue;
      }
      sizes.push_back(static_cast<int64_t>(total));
      // NOT cached: the batch read is unverified (consumers re-verify
      // end-to-end), and the LRU must only ever hold VERIFIED bytes —
      // caching here would let a corrupt replica poison later per-block
      // reads that trust cache hits. (The streaming sweep shouldn't wash
      // the cache anyway.)
    }
    Writer w;
    w.map_head(3);
    w.str("ok");
    w.boolean(true);
    w.str("_d");
    w.uint(1);
    w.str("sizes");
    {
      // Writer::aint clamps negatives to 0; hand-encode -1 slots.
      if (sizes.size() < 16) w.raw(0x90 | sizes.size());
      else { w.raw(0xdc); w.be(sizes.size(), 2); }
      for (int64_t v : sizes) {
        if (v < 0) w.raw(0xff);  // negative fixint -1
        else w.uint(static_cast<uint64_t>(v));
      }
    }
    send_frame(s, w.out, payload.data(), payload.size());
  }

  std::string host_, hot_, cold_;
  uint32_t chunk_;
  int listen_fd_ = -1;
  int32_t port_ = 0;
  std::atomic<bool> running_{false};
  std::mutex term_mu_;
  std::map<std::string, uint64_t> terms_;
  std::atomic<uint64_t> token_seq_{1};
  std::atomic<uint64_t> writes_{0}, reads_{0}, forwards_{0}, errors_{0};
  std::atomic<uint64_t> stage_ns_{0}, commit_wait_ns_{0}, syncfs_ns_{0},
      fwd_ack_ns_{0}, commit_batches_{0}, commit_entries_{0},
      staged_bytes_{0}, rename_ns_{0};
  std::atomic<uint64_t> stream_net_ns_{0}, stream_crc_ns_{0},
      stream_disk_ns_{0}, stream_fanout_ns_{0}, stream_frames_{0},
      streams_started_{0}, stream_bytes_{0}, stream_aborts_{0};
  std::thread accept_thread_, commit_thread_;
  std::atomic<int> active_{0};
  std::mutex conns_mu_;
  std::set<int> conns_;
  std::mutex commit_mu_;
  std::condition_variable commit_cv_, commit_done_cv_;
  std::deque<std::shared_ptr<CommitEntry>> commit_queue_;
  std::mutex bad_mu_;
  std::set<std::string> bad_;
  size_t cache_cap_;
  std::mutex cache_mu_;
  std::list<std::pair<std::string, CacheData>> cache_list_;  // front = MRU
  std::map<std::string, std::list<std::pair<std::string, CacheData>>::iterator>
      cache_map_;
  std::map<std::string, uint64_t> inval_gen_;  // see cache_gen/cache_put
  uint64_t gen_counter_ = 0;  // monotone source of every generation
  uint64_t gen_floor_ = 0;    // generation reported for absent ids
  std::atomic<uint64_t> cache_hits_{0}, cache_misses_{0};
  void* srv_ctx_ = nullptr;  // SSL_CTX*, set by configure_tls
  void* cli_ctx_ = nullptr;  // SSL_CTX* for chain forwards
  Qos qos_;  // tenant admission plane (off until set_qos enables it)
};

std::mutex g_engines_mu;
std::vector<Engine*> g_engines;

Engine* get_engine(int64_t h) {
  std::lock_guard<std::mutex> g(g_engines_mu);
  if (h < 0 || static_cast<size_t>(h) >= g_engines.size()) return nullptr;
  return g_engines[h];
}

}  // namespace

extern "C" {

// Bumped on any signature/behavior change of the dataplane C ABI; the
// Python loader refuses to bind mismatched prebuilt libraries
// (TPUDFS_NATIVE_LIB) instead of calling with wrong arity.
int64_t tpudfs_dataplane_abi(void) { return 6; }

int64_t tpudfs_dataplane_start(const char* host, const char* hot_dir,
                               const char* cold_dir, uint32_t chunk_size,
                               uint16_t port, uint64_t cache_blocks,
                               const char* srv_cert, const char* srv_key,
                               const char* srv_client_ca,
                               const char* out_ca, const char* out_cert,
                               const char* out_key) {
  auto* e = new Engine(host ? host : "", hot_dir,
                       cold_dir ? cold_dir : "", chunk_size,
                       static_cast<size_t>(cache_blocks));
  auto str = [](const char* c) { return std::string(c ? c : ""); };
  if (!e->configure_tls(str(srv_cert), str(srv_key), str(srv_client_ca),
                        str(out_ca), str(out_cert), str(out_key))) {
    delete e;
    return -EPROTO;  // caller falls back to the asyncio blockport
  }
  int64_t rc = e->start(port);
  if (rc < 0) {
    delete e;
    return rc;
  }
  std::lock_guard<std::mutex> g(g_engines_mu);
  g_engines.push_back(e);
  return static_cast<int64_t>(g_engines.size() - 1);
}

int32_t tpudfs_dataplane_port(int64_t h) {
  Engine* e = get_engine(h);
  return e ? e->port() : 0;
}

void tpudfs_dataplane_set_term(int64_t h, const char* shard,
                               uint64_t term) {
  Engine* e = get_engine(h);
  if (e) e->set_term(shard ? shard : "", term);
}

uint64_t tpudfs_dataplane_term(int64_t h, const char* shard) {
  Engine* e = get_engine(h);
  return e ? e->term(shard ? shard : "") : 0;
}

int64_t tpudfs_dataplane_take_bad(int64_t h, char* buf, uint64_t cap) {
  Engine* e = get_engine(h);
  return e ? e->take_bad(buf, cap) : -1;
}

int64_t tpudfs_dataplane_take_terms(int64_t h, char* buf, uint64_t cap) {
  Engine* e = get_engine(h);
  return e ? e->take_terms(buf, cap) : -1;
}

void tpudfs_dataplane_invalidate(int64_t h, const char* block_id) {
  Engine* e = get_engine(h);
  if (e && block_id) e->cache_invalidate(block_id);
}

void tpudfs_dataplane_stats(int64_t h, uint64_t out[6]) {
  Engine* e = get_engine(h);
  if (e) e->stats(out);
  else for (int i = 0; i < 6; i++) out[i] = 0;
}

// Write-path stage budgets: stage_ns, commit_wait_ns, syncfs_ns,
// fwd_ack_ns, commit_batches, commit_entries, staged_bytes, rename_ns.
void tpudfs_dataplane_stage_stats(int64_t h, uint64_t out[8]) {
  Engine* e = get_engine(h);
  if (e) e->stage_stats(out);
  else for (int i = 0; i < 8; i++) out[i] = 0;
}

// Streaming write pipeline occupancy: net_ns, crc_ns, disk_ns,
// fanout_ns, frames, streams, stream_bytes, aborts.
void tpudfs_dataplane_stream_stats(int64_t h, uint64_t out[8]) {
  Engine* e = get_engine(h);
  if (e) e->stream_stage_stats(out);
  else for (int i = 0; i < 8; i++) out[i] = 0;
}

// QoS control contract (ABI 6). Python pushes the QosShedder config in
// (a msgpack flat map built by resilience.qos_wire_config) at start and
// on every change — the set_term of the admission plane.
void tpudfs_dataplane_set_qos(int64_t h, const char* cfg, uint64_t len) {
  Engine* e = get_engine(h);
  if (e && cfg != nullptr)
    e->qos_configure(reinterpret_cast<const uint8_t*>(cfg), len);
}

// Aggregate QoS counters: inflight, peak_inflight, admitted_total,
// shed_total, queue_depth, queued_total, rate_limited_total,
// evicted_total.
void tpudfs_dataplane_qos_stats(int64_t h, uint64_t out[8]) {
  Engine* e = get_engine(h);
  if (e) e->qos_stats(out);
  else for (int i = 0; i < 8; i++) out[i] = 0;
}

// Per-tenant "tenant\tadmitted\tshed\trate_limited\tqueue_depth\tp99_ns"
// lines (non-destructive); returns bytes written, or -needed when cap is
// short — the take_terms contract.
int64_t tpudfs_dataplane_take_qos(int64_t h, char* buf, uint64_t cap) {
  Engine* e = get_engine(h);
  return e ? e->take_qos(buf, cap) : -1;
}

int64_t tpudfs_dataplane_stop(int64_t h) {
  Engine* e = get_engine(h);
  if (!e) return -1;
  bool drained = e->stop();
  {
    std::lock_guard<std::mutex> g(g_engines_mu);
    g_engines[h] = nullptr;
  }
  if (drained) {
    delete e;
    return 0;
  }
  // A connection thread is still alive inside the engine: leaking it is
  // the only memory-safe option (shutdown already unblocked its sockets;
  // it will exit soon and touch only still-valid memory).
  return 1;
}

}  // extern "C"
