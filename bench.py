"""tpudfs flagship benchmark (driver-run, one JSON line).

Metric (BASELINE.json): "chunk read GB/s/host into TPU HBM; 3x-replication
write GB/s over ICI" — BOTH sides are reported:

- read side: a live DFS — 1 master + 3 chunkservers, each its OWN OS process
  (as in the reference's docker-compose topology; servers must not share the
  client's GIL) — with 3x pipeline-replicated 1 MiB blocks, read through the
  client's concurrent fan-out into device memory via HbmReader: per-block
  device_put, per-512B-chunk CRC32C + GF(2) combine-fold ON the accelerator
  (block_crc_device), one host sync for the whole sweep (lazy verify +
  confirm). The dataset (128 x 1 MiB) far exceeds the chunkservers' LRU
  block cache (capped at 8 blocks here), so reads exercise the disk path.
- write side: (a) the DFS 3x pipeline-replicated write path (client -> CS1 ->
  CS2 -> CS3 chain over gRPC), logical GB/s; (b) the TPU-native replacement:
  `replicated_write_step` — ppermute chain + on-device CRC verify + ack psum
  — timed on the real chip (replication-degenerate on a 1-device mesh; the
  multi-device layout is validated by dryrun_multichip).

vs_baseline: the reference publishes no numbers (BASELINE.md), so the ratio
is against the BASELINE.json north-star target = 90% of this host's raw
host->device infeed bandwidth, measured honestly: one dispatcher thread
issues all device_puts of DISTINCT buffers back-to-back and blocks once on
the batch (no per-call thread hops or syncs).

Timing protocol (measured tunnel pathology): the FIRST device->host
transfer of the process — however small — permanently degrades BOTH
directions of the tunneled transport ~30-100x (H2D 1.1 GB/s -> 0.01-0.04
afterwards; no recovery with idle time). Every GB/s window below therefore
contains host->device transfers and on-device compute only, synchronized
with ``block_until_ready`` (completion wait, no readback): numerator and
denominator are measured under the SAME H2D-only protocol, so the ratio is
honest. The verification verdicts (0-d device CRCs) are fetched ONCE, after
every timed window, in a single batched transfer and asserted; its cost is
reported separately as ``confirm_s``, and ``raw_infeed_after_GBps`` shows
the post-D2H state of the transport for transparency.

Statistical protocol (round 4): the bench host has ONE core, and a single
timed window there can swing several-fold with scheduler noise (round 3's
recorded warm-infeed 0.117 vs 0.79-1.11 in repeated runs of the same
protocol — an artifact, not a regression: re-running the round-3 bench
unchanged reproduced warm 0.86 > cold 0.66). Every reported GB/s number is
therefore the MEDIAN of ``REPS`` interleaved windows — the rep loop cycles
raw-infeed -> gRPC sweep -> fused cold sweep -> warm sweep so a noise burst
lands on at most one window of each kind, and the raw-infeed DENOMINATOR
(measured swing 0.8-2.1 on this host) gets the same median treatment as the
numerators. Per-metric ``*_win`` = [min, max] spreads are published in the
JSON line alongside the medians.
"""

from __future__ import annotations

import asyncio
import json
import math
import statistics
import time
import urllib.request

import numpy as np

#: Mid-run wedge guard (measured 2026-07-31: the tunnel came LIVE, passed
#: the startup probe AND a 1 MiB device_put, then wedged during the ~10 min
#: of host-side write windows — the first real device touch hung forever
#: and the driver would have recorded nothing). Two defenses:
#: 1. the platform decision is RE-checked right before the first device
#:    touch (_decide_device below) — jax's backend is not initialized until
#:    then, so a mid-write wedge downgrades the run to the honest CPU
#:    fallback instead of hanging it;
#: 2. a watchdog thread emits whatever was measured so far as the one JSON
#:    line and exits hard if no window completes for WEDGE_TIMEOUT_S (a
#:    single TPU compile is 20-40 s; the 5-bucket warm-up ~200 s; nothing
#:    legitimate is silent for 10 min).
WEDGE_TIMEOUT_S = 600.0
WEDGE_POLL_S = 15.0
_progress = {"t": None, "stage": "start"}  # t None = watchdog disarmed
_partial: dict = {}
#: Set by main_sprint(): the watchdog persists PARTIAL captures to
#: BENCH_SPRINT.json so a mid-run wedge can't lose a window's data.
_sprint_mode = False
#: One-JSON-line contract: the watchdog and the normal completion path
#: race when the run finishes just as the timeout elapses — whichever
#: claims this flag first (under the lock) prints; the other stays silent.
import threading

_emit_lock = threading.Lock()
_emitted = False


def _emit_once(payload: dict) -> bool:
    """Print the final JSON line if nobody has yet. Returns True if this
    caller won the race."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return False
        _emitted = True
    print(json.dumps(payload), flush=True)
    return True


def _tick(stage: str) -> None:
    _progress["t"] = time.monotonic()
    _progress["stage"] = stage


def _start_watchdog() -> None:
    import os

    def watch() -> None:
        while True:
            time.sleep(WEDGE_POLL_S)
            t0 = _progress["t"]
            if t0 is None:
                continue
            if time.monotonic() - t0 > WEDGE_TIMEOUT_S:
                out = {
                    "metric": "PARTIAL (device wedged mid-run)",
                    "value": 0.0,
                    "unit": "GB/s",
                    "vs_baseline": 0.0,
                    **_partial,
                    "platform": f"tpu-wedged-midrun({_progress['stage']})",
                }
                if _emit_once(out):
                    if _sprint_mode:
                        # A PARTIAL real-TPU sprint capture (e.g. the raw
                        # window landed before the wedge) must still
                        # persist for the round-end merge — a lost window
                        # is exactly what the sprint exists to prevent.
                        try:
                            out["captured_at"] = time.strftime(
                                "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
                            with open(_repo_path("BENCH_SPRINT.json.tmp"),
                                      "w") as f:
                                json.dump(out, f, indent=1)
                            os.replace(_repo_path("BENCH_SPRINT.json.tmp"),
                                       _repo_path("BENCH_SPRINT.json"))
                        except OSError:
                            pass
                    os._exit(3)
                return  # normal path won the race; let it finish

    threading.Thread(target=watch, daemon=True).start()

FILES = 128
BLOCK_MB = 1
#: Interleaved timed windows per metric; medians + [min,max] are reported.
REPS = 3
#: Read-side windows get two extra reps: even with GC parked, ~1 window
#: per run still craters ~3x on an episodic host stall (driver process,
#: kernel housekeeping — debug_samples across runs show one random ~0.3 s
#: hit per minute of wall clock), and a median of 5 tolerates two. Write
#: windows stay at REPS: more of them would only push the median further
#: down the disk's burst-credit decay (see BENCH_NOTES round 4), which is
#: a property of the disk, not noise.
READ_REPS = 5
CS_CACHE_BLOCKS = 8  # << FILES so the read phase cannot ride the LRU cache
#: Dedicated cache sweep: working set that FITS the LRU, read repeatedly.
CACHE_FILES = 6
CACHE_PASSES = 4
# Measured on the single-core bench host: 4-6 concurrent read streams beat
# 12 on the per-block gRPC path (beyond ~6, thread/GIL scheduling churn on
# one core outweighs overlap). The FUSED local path inverts this: per-block
# Python work is tiny (requests just stage into combiner rounds), so more
# in-flight files = denser rounds — 32 measured best. Writes keep the
# reference harness's concurrency 10 (dfs_cli.rs:579-631) so
# write_pipeline_GBps stays comparable across rounds.
READ_CONCURRENCY = 6
FUSED_READ_CONCURRENCY = 32
#: Remote (non-colocated) fused sweep: 16 in-flight files batch into
#: denser per-origin ReadBlocks frames than 6 (measured round 5 with the
#: scatter receive: 0.39 -> 0.51 GB/s); past 16 the one-core loop churns.
REMOTE_SWEEP_CONCURRENCY = 16
#: Fused round cap (blocks). Kept at 16 so the batched-CRC bucket set is
#: {1,2,4,8,16} — five warm-up compiles, bounded on real TPU.
BATCH_READS = 16
WRITE_CONCURRENCY = 10
ICI_STEP_MB = 8
ICI_REPS = 16


def _bench_raw_infeed(device, nbytes_each: int, reps: int) -> float:
    """Raw host->HBM bandwidth, taken as the BEST of two honest harnesses so
    the denominator is strictly favorable: (a) one dispatcher issuing all
    device_puts back-to-back with a single final sync (pipelined), and
    (b) READ_CONCURRENCY persistent threads each pipelining its share (what
    the measured path's 8-way fan-out gets to use). Distinct FRESH buffers
    per transfer — no residency reuse. (Round 5 tried reusing host buffers
    across interleaved windows to cut allocator churn: the raw number
    DROPPED 40% and inflated vs_baseline without the measured path
    changing — reverted; the denominator must stay its fastest self.)"""
    import concurrent.futures

    import jax

    import gc

    bufs = [
        np.random.default_rng(i).integers(
            0, 256, nbytes_each, dtype=np.uint8
        ).reshape(-1, 512).view("<u4")
        for i in range(reps)
    ]
    # Warm-up transfer.
    jax.block_until_ready(jax.device_put(bufs[0], device))
    gc.collect()
    gc.disable()  # same GC discipline as timed_sweep — see its docstring
    try:
        t0 = time.perf_counter()
        arrs = [jax.device_put(b, device) for b in bufs]
        jax.block_until_ready(arrs)
        serial = nbytes_each * reps / (time.perf_counter() - t0) / 1e9

        def put_shard(shard):
            return [jax.device_put(b, device) for b in shard]

        shards = [bufs[i::READ_CONCURRENCY]
                  for i in range(READ_CONCURRENCY)]
        with concurrent.futures.ThreadPoolExecutor(READ_CONCURRENCY) as pool:
            t0 = time.perf_counter()
            out = list(pool.map(put_shard, shards))
            jax.block_until_ready(out)
            threaded = nbytes_each * reps / (time.perf_counter() - t0) / 1e9
    finally:
        gc.enable()
    return max(serial, threaded)


def _bench_ici_write_step(device) -> tuple:
    """On-chip 3x replication round: ppermute chain + Pallas CRC verify +
    ack psum. REPS timed windows of ICI_REPS rounds each (median + spread
    reported by the caller)."""
    import jax
    import jax.numpy as jnp

    from tpudfs.common.checksum import crc32c_chunks
    from tpudfs.tpu.crc32c_pallas import bytes_to_words
    from tpudfs.tpu.ici_replication import make_mesh, replicated_write_step

    mesh = make_mesh([device])
    step = replicated_write_step(mesh, replication=3)
    nbytes = ICI_STEP_MB << 20
    data = np.random.default_rng(7).integers(
        0, 256, nbytes, dtype=np.uint8
    ).tobytes()
    words = jax.device_put(bytes_to_words(data), device)
    crcs = jax.device_put(crc32c_chunks(data).astype(np.uint32), device)
    jax.block_until_ready(step(words, crcs))  # compile + warm up
    samples, ok_stacks = [], []
    for _ in range(REPS):
        t0 = time.perf_counter()
        outs = [step(words, crcs) for _ in range(ICI_REPS)]
        jax.block_until_ready(outs)
        samples.append(nbytes * ICI_REPS / (time.perf_counter() - t0) / 1e9)
        # Compact each window's verdicts to ICI_REPS scalars right away so
        # the full 8 MiB outputs don't stay live across later windows.
        ok_stacks.append(jnp.stack([o["ok"].reshape(-1)[0] for o in outs]))
    # Verdicts stay on device; the caller fetches them once after every
    # timed window (per-round fetches would cost 0.1-1 s each on a
    # degraded tunnel, and any D2H here would poison later H2D uploads).
    return samples, jnp.concatenate(ok_stacks)


def _spawn_cluster(root: str, cache_blocks: int = CS_CACHE_BLOCKS,
                   n_cs: int = 3, extra_env: dict | None = None,
                   http: bool = False):
    """1 master + ``n_cs`` chunkservers as separate OS processes (real
    sockets, real GIL isolation — the client must not time-share with the
    servers). The flagship read/write phases use 3 (a replication set);
    the checkpoint phase asks for 5 so RS(3,2) shards land on distinct
    servers and 2 can die; the tenant phase passes TPUDFS_QOS knobs via
    ``extra_env``. On failure every already-started process is torn down
    before raising."""
    import atexit
    import pathlib

    from tpudfs.testing.procs import free_port, spawn, terminate_all, wait_ready

    logdir = pathlib.Path(root) / "logs"
    logdir.mkdir(parents=True)
    procs = []
    atexit.register(terminate_all, procs)  # belt-and-braces orphan guard
    env = {"JAX_PLATFORMS": "cpu",  # servers never touch the TPU
           **(extra_env or {})}
    try:
        maddr = f"127.0.0.1:{free_port()}"
        spawn(procs, "master", logdir, "tpudfs.master",
              "--port", maddr.rsplit(":", 1)[1],
              "--data-dir", f"{root}/m0", "--http-port", "0", env=env)
        wait_ready(logdir, "master")
        cs_addrs = []
        for i in range(n_cs):
            port = free_port()
            # --scrub-interval 3600: this host has ONE core; the default
            # 60 s scrubber would re-CRC the whole 384 MiB dataset mid-sweep
            # and steal the core from the measured path.
            spawn(procs, f"cs{i}", logdir, "tpudfs.chunkserver",
                  "--port", str(port),
                  "--data-dir", f"{root}/cs{i}", "--masters", maddr,
                  "--rack-id", f"rack-{i}", "--heartbeat-interval", "0.5",
                  "--scrub-interval", "3600",
                  # -1 = ops HTTP at rpc port + 1000 (the tenant phase
                  # scrapes per-tenant QoS counters); 0 = disabled.
                  "--http-port", "-1" if http else "0",
                  env={**env, "BLOCK_CACHE_SIZE": str(cache_blocks)})
            wait_ready(logdir, f"cs{i}")
            cs_addrs.append(f"127.0.0.1:{port}")
    except BaseException:
        terminate_all(procs)
        raise
    return maddr, cs_addrs, procs


def _bench_ec_scatter_step(device) -> tuple:
    """On-chip RS(6,3) encode + shard scatter + CRC-verify round
    (replication-degenerate ring on 1 device; multi-device layout is
    validated by dryrun_multichip)."""
    import jax
    import jax.numpy as jnp

    from tpudfs.tpu.crc32c_pallas import bytes_to_words
    from tpudfs.tpu.ici_replication import EcShardScatter, make_mesh

    mesh = make_mesh([device])
    scatter = EcShardScatter(mesh, 6, 3)
    nbytes = ICI_STEP_MB << 20
    data = np.random.default_rng(9).integers(
        0, 256, nbytes, dtype=np.uint8
    ).tobytes()
    words = jax.device_put(bytes_to_words(data), device)
    jax.block_until_ready(scatter.scatter(words))  # compile + warm up
    samples, ack_stacks = [], []
    for _ in range(REPS):
        t0 = time.perf_counter()
        outs = [scatter.scatter(words) for _ in range(ICI_REPS)]
        jax.block_until_ready(outs)
        samples.append(nbytes * ICI_REPS / (time.perf_counter() - t0) / 1e9)
        ack_stacks.append(jnp.stack([a for _, _, a in outs]))
    # Fetched once by the caller, after every timed window.
    return samples, jnp.concatenate(ack_stacks)


async def _run() -> dict:
    import tempfile

    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-bench-")
    root = tmp.name
    maddr, cs_addrs, procs = _spawn_cluster(root)
    try:
        return await _run_against(maddr, cs_addrs)
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


# ------------------------------------------------- write-stage occupancy
#
# ``bench.py --write-stages``: drive the streamed 3x write path and emit
# per-stage occupancy (net / crc / disk / fanout wall-ns shares) from
# every chunkserver's ``stream_stages`` counters — the localizer for
# write-path regressions: a future slowdown shows up as ONE stage's
# share growing, instead of an opaque GB/s drop. Counters are summed
# across the native engine and the asyncio fallback (whichever plane
# served), so the breakdown is meaningful on any cluster.


async def _run_write_stages() -> dict:
    import tempfile

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient

    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-wstages-")
    maddr, cs_addrs, procs = _spawn_cluster(tmp.name)
    try:
        rpc = RpcClient()
        client = Client([maddr], rpc_client=rpc, block_size=BLOCK_MB << 20,
                        etag_mode="crc64")
        deadline = asyncio.get_event_loop().time() + 60
        while True:
            try:
                await client.create_file("/ws/probe", b"x")
                await client.delete_file("/ws/probe")
                break
            except Exception:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.3)
        data = np.random.default_rng(3).integers(
            0, 256, BLOCK_MB << 20, dtype=np.uint8
        ).tobytes()
        wsem = asyncio.Semaphore(WRITE_CONCURRENCY)

        async def put(rep: int, i: int) -> None:
            async with wsem:
                await client.create_file(f"/ws/r{rep}/f{i:04d}", data)

        samples = []
        for rep in range(REPS):
            t0 = time.perf_counter()
            await asyncio.gather(*(put(rep, i) for i in range(FILES)))
            samples.append(
                FILES * len(data) / (time.perf_counter() - t0) / 1e9)
            _tick(f"wstages-rep{rep}")

        stage_keys = ("net_ns", "crc_ns", "disk_ns", "fanout_ns")
        count_keys = ("frames", "streams", "stream_bytes", "aborts")
        totals = dict.fromkeys(stage_keys + count_keys, 0)
        per_cs = {}
        for addr in cs_addrs:
            stats = await rpc.call(addr, "ChunkServerService", "Stats", {},
                                   timeout=15.0)
            st = stats.get("stream_stages") or {}
            for k in totals:
                totals[k] += int(st.get(k, 0))
            busy = sum(int(st.get(k, 0)) for k in stage_keys)
            per_cs[addr] = {
                k.removesuffix("_ns"): round(int(st.get(k, 0)) / busy, 3)
                for k in stage_keys
            } if busy else {}
        await rpc.close()
        busy = sum(totals[k] for k in stage_keys)
        med = statistics.median
        return {
            "metric": ("streamed 3x write GB/s + per-stage occupancy "
                       "(net/crc/disk/fanout share of pipeline wall time, "
                       "summed across chunkservers and serving planes)"),
            "value": round(med(samples), 3),
            "unit": "GB/s",
            "windows": REPS,
            "write_pipeline_GBps": round(med(samples), 3),
            "write_pipeline_win": _winmm(samples),
            "stage_occupancy": {
                k.removesuffix("_ns"): round(totals[k] / busy, 3)
                for k in stage_keys
            } if busy else {},
            "stage_occupancy_per_cs": per_cs,
            "stream_frames": totals["frames"],
            "streams": totals["streams"],
            "stream_bytes": totals["stream_bytes"],
            "stream_aborts": totals["aborts"],
            "files": FILES,
            "platform": "cpu",
        }
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


def main_write_stages() -> None:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _tick("wstages-start")
    _start_watchdog()
    result = asyncio.run(_run_write_stages())
    _progress["t"] = None
    _emit_once(result)


# ----------------------------------------------------- checkpoint bench
#
# ``bench.py --ckpt``: the fault-tolerant sharded-checkpoint data path
# (tpudfs/tpu/checkpoint.py) as its own fast mode — 4-shard saves
# (hot 3x + RS(3,2) cold copy, two-phase atomic-manifest commit), host
# restores, and the DEGRADED restore: an EC-only checkpoint read back
# with 2 of 5 chunkservers SIGKILLed, so every shard comes out of
# RS(3,2) reconstruction, CRC-verified end-to-end. CPU-safe (host
# restore path; no device windows), so the numbers hold on the
# cpu-fallback host too. vs_baseline = save GB/s over plain 3x
# create_file GB/s of the same logical bytes measured in-run — the cost
# of checkpoint semantics (staging + EC cold copy + spec + verify +
# publish) relative to raw replicated writes.

CKPT_SHARDS = 4
CKPT_TREE_KIB = 4 * 1024  # ~3.25 MiB payload/shard (see ckpt_tree's mix)
CKPT_STEPS = 3            # one timed save window per step


async def _run_ckpt() -> dict:
    import signal as _signal
    import tempfile

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient
    from tpudfs.testing.ckptchaos import ckpt_tree, trees_equal
    from tpudfs.tpu.checkpoint import CheckpointManager

    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-ckptbench-")
    maddr, cs_addrs, procs = _spawn_cluster(tmp.name, n_cs=5)
    try:
        rpc = RpcClient()
        client = Client([maddr], rpc_client=rpc, block_size=BLOCK_MB << 20,
                        etag_mode="crc64")
        deadline = asyncio.get_event_loop().time() + 60
        while True:
            try:
                await client.create_file("/ckpt/probe", b"x")
                await client.delete_file("/ckpt/probe")
                break
            except Exception:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.3)

        trees = {step: {s: ckpt_tree(step, s, kib=CKPT_TREE_KIB)
                        for s in range(CKPT_SHARDS)}
                 for step in range(1, CKPT_STEPS + 1)}

        # Denominator: the same logical bytes as plain 3x-replicated
        # create_file puts (per-shard files, same concurrency as the
        # sharded save's gather) — what the payload writes would cost
        # without checkpoint semantics.
        plain_samples = []
        payloads = None
        for rep in range(REPS):
            from tpudfs.tpu.checkpoint import pack_shard

            if payloads is None:
                payloads = [pack_shard(trees[1][s])[0]
                            for s in range(CKPT_SHARDS)]
            t0 = time.perf_counter()
            await asyncio.gather(*(
                client.create_file(f"/ckpt/plain/r{rep}/s{i}", p)
                for i, p in enumerate(payloads)))
            plain_samples.append(
                sum(len(p) for p in payloads)
                / (time.perf_counter() - t0) / 1e9)
            _tick(f"ckpt-plain{rep}")

        mgr = CheckpointManager(client, "/ckpt/bench",
                                num_shards=CKPT_SHARDS, ec=(3, 2))
        save_samples, logical = [], 0
        for step in range(1, CKPT_STEPS + 1):
            t0 = time.perf_counter()
            manifest = await mgr.save(step, trees[step])
            dt = time.perf_counter() - t0
            logical = sum(s["size"] for s in manifest["shards"])
            save_samples.append(logical / dt / 1e9)
            _tick(f"ckpt-save{step}")

        restore_samples = []
        out = None
        for rep in range(REPS):
            step = (rep % CKPT_STEPS) + 1
            t0 = time.perf_counter()
            out = await mgr.restore(step)
            restore_samples.append(
                logical / (time.perf_counter() - t0) / 1e9)
            _tick(f"ckpt-restore{rep}")
        assert all(trees_equal(out[s], trees[step][s])
                   for s in range(CKPT_SHARDS)), "restore not bit-exact"

        # Degraded restore: EC-ONLY checkpoint (no hot copies to fail
        # over to), then 2 of 5 chunkservers SIGKILLed — every shard read
        # is forced through RS(3,2) reconstruction. One untimed warm
        # restore absorbs the dead-peer discovery (connection refusals,
        # stale location metadata) so the windows time the decode path.
        ec_mgr = CheckpointManager(client, "/ckpt/bench-ec",
                                   num_shards=CKPT_SHARDS, ec=(3, 2),
                                   hot_copies=False)
        await ec_mgr.save(1, trees[1])
        for p in procs[-2:]:  # procs[0] is the master; kill cs3, cs4
            p.send_signal(_signal.SIGKILL)
        _tick("ckpt-kill")
        await ec_mgr.restore(1)  # untimed warm (failover discovery)
        degraded_samples = []
        for rep in range(REPS):
            t0 = time.perf_counter()
            out = await ec_mgr.restore(1)
            degraded_samples.append(
                logical / (time.perf_counter() - t0) / 1e9)
            _tick(f"ckpt-degraded{rep}")
        assert all(trees_equal(out[s], trees[1][s])
                   for s in range(CKPT_SHARDS)), \
            "degraded restore not bit-exact"

        await rpc.close()
        med = statistics.median
        save, plain = med(save_samples), med(plain_samples)
        return {
            "metric": (
                "sharded-checkpoint save/restore GB/s (4 shards, hot 3x "
                "+ RS(3,2) cold copy, atomic manifest commit; degraded = "
                "EC-only restore with 2/5 chunkservers SIGKILLed)"
            ),
            "value": round(save, 3),
            "unit": "GB/s",
            "vs_baseline": round(save / plain, 3) if plain else 0.0,
            "windows": REPS,
            "ckpt_save_GBps": round(save, 3),
            "ckpt_save_win": _winmm(save_samples),
            "ckpt_restore_GBps": round(med(restore_samples), 3),
            "ckpt_restore_win": _winmm(restore_samples),
            "ckpt_restore_degraded_GBps": round(med(degraded_samples), 3),
            "ckpt_restore_degraded_win": _winmm(degraded_samples),
            "plain_write_GBps": round(plain, 3),
            "copies_per_byte": _ledger_copies_per_byte(),
            "ckpt_shards": CKPT_SHARDS,
            "ckpt_steps": CKPT_STEPS,
            "ckpt_logical_bytes_per_step": logical,
            "etag_mode": client.etag_mode,
            "platform": "cpu",  # host restore path; no device windows
        }
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


def main_ckpt() -> None:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _tick("ckpt-start")
    _start_watchdog()
    result = asyncio.run(_run_ckpt())
    _progress["t"] = None
    _emit_once(result)


# ------------------------------------------------------- tenant QoS bench
#
# ``bench.py --tenants``: the multi-tenant QoS data path as its own fast
# CPU-safe mode, run as a native-vs-asyncio A/B. For EACH serving engine
# (the C++ data plane, then the asyncio blockport via
# TPUDFS_PYTHON_DATA_PLANE=1) the cluster boots with TPUDFS_QOS=1
# (weighted-fair queueing + a per-tenant rate on every chunkserver and the
# master), a "fair" tenant's read p99 is measured uncontended and then
# again while an "abuser" tenant floods the same chunkservers at
# TENANT_FLOOD_CONCURRENCY (~10x the fair tenant's single-stream
# concurrency). The engine each chunkserver actually serves is verified
# through the DataPort handshake ("native": true/false) — a silent
# fallback fails the bench rather than A/B-ing the wrong plane. Headline
# numbers (from the native leg): tenant_fair_p99_ms (fair p99 UNDER the
# flood), vs_baseline = flood p99 / uncontended p99 (the noisy-neighbor
# acceptance bound is <= 3), tenant_abuser_shed_ratio (abuser ops
# throttled/shed by QoS), and read_gbps (uncontended fair-tenant
# single-stream throughput) — with the asyncio leg's numbers beside them
# under "engines". Reads run with the local short-circuit OFF —
# short-circuit reads bypass server admission entirely, and QoS must be
# in the measured path.

TENANT_FILES = 24
TENANT_FLOOD_CONCURRENCY = 32
TENANT_FAIR_READS = 40


async def _run_tenants_engine(engine: str) -> dict:
    import tempfile

    from tpudfs.client.client import Client, DfsError
    from tpudfs.common.rpc import RpcClient

    # Small admission window (4 inflight per chunkserver) so the flood
    # actually saturates the data path and the weighted-fair queue — not
    # raw capacity — decides who runs; fair=4 buys the fair tenant a 4:1
    # service share whenever both tenants are queued.
    qos_env = {"TPUDFS_QOS": "1", "TPUDFS_QOS_RATE": "150",
               "TPUDFS_QOS_BURST": "30", "TPUDFS_QOS_QUEUE_DEPTH": "6",
               "TPUDFS_QOS_QUEUE_WAIT": "0.2",
               "TPUDFS_QOS_WEIGHTS": "fair=8",
               "TPUDFS_CS_MAX_INFLIGHT": "6"}
    if engine == "asyncio":
        qos_env["TPUDFS_PYTHON_DATA_PLANE"] = "1"
    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-tenantbench-")
    maddr, cs_addrs, procs = _spawn_cluster(tmp.name, extra_env=qos_env,
                                            http=True)
    try:
        rpc = RpcClient()

        # The A/B is meaningless unless each leg actually serves from the
        # engine it claims: verify the DataPort handshake on every CS.
        want_native = engine == "native"
        for addr in cs_addrs:
            hello = await rpc.call(addr, "ChunkServerService", "DataPort",
                                   {}, timeout=10.0)
            if bool(hello.get("native")) is not want_native:
                raise RuntimeError(
                    f"chunkserver {addr} serves native={hello.get('native')}"
                    f" but the {engine} leg of the A/B requires "
                    f"native={want_native} (silent engine fallback)")

        def tenant_client(tenant: str, op_budget: float = 4.0) -> Client:
            return Client([maddr], rpc_client=rpc,
                          block_size=BLOCK_MB << 20, op_budget=op_budget,
                          rpc_timeout=1.0, initial_backoff=0.05,
                          etag_mode="crc64", local_reads=False,
                          tenant=tenant)

        fair = tenant_client("fair")
        # The abuser gets a short per-op budget: a throttled op surfaces as
        # a shed instead of being silently retried into a success.
        abuser = tenant_client("abuser", op_budget=1.2)
        deadline = asyncio.get_event_loop().time() + 60
        while True:
            try:
                await fair.create_file("/tenants/probe", b"x")
                await fair.delete_file("/tenants/probe")
                break
            except Exception:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.3)
        data = np.random.default_rng(3).integers(
            0, 256, BLOCK_MB << 20, dtype=np.uint8).tobytes()
        # Keep dataset writes inside the deliberately small admission
        # window (4 inflight/cs): contention here is not what's measured.
        wsem = asyncio.Semaphore(4)

        async def put(i: int) -> None:
            async with wsem:
                await fair.create_file(f"/tenants/f{i:04d}", data)

        await asyncio.gather(*(put(i) for i in range(TENANT_FILES)))
        # Let the per-tenant token buckets refill before timing anything:
        # every dataset write charged the head AND both forwarded replicas,
        # and a fast engine lands all of that inside one burst window, so
        # the first baseline reads would ride the LOAD phase's residual
        # rate debt (the slower the engine, the less debt — inverting the
        # A/B). burst/rate is 0.2 s here; 1 s is refill-complete for any
        # sane knob set. Applied to both legs equally.
        await asyncio.sleep(1.0)
        _tick("tenants-dataset")

        async def timed_read(client: Client, i: int, errors: list) -> float:
            t0 = time.perf_counter()
            try:
                got = await client.get_file(f"/tenants/f{i:04d}")
                assert len(got) == len(data)
            except DfsError as e:
                errors.append(e)
            return time.perf_counter() - t0

        def p99(xs: list) -> float:
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(0.99 * (len(xs) - 1)))]

        def fair_reads_in_thread(n: int) -> tuple[list, list]:
            """Sequential fair-tenant reads on a PRIVATE thread + event
            loop + RpcClient. The flood runs 32 coroutines on the main
            loop; timing the fair tenant there would charge it for the
            abuser's event-loop turns — exactly the contamination QoS
            exists to prevent. Separate loop = the wall clock measures
            the servers, not the shared client process."""
            walls: list = []
            errors: list = []

            def run() -> None:
                async def seq() -> None:
                    trpc = RpcClient()
                    cl = Client([maddr], rpc_client=trpc,
                                block_size=BLOCK_MB << 20, op_budget=4.0,
                                rpc_timeout=1.0, initial_backoff=0.05,
                                etag_mode="crc64", local_reads=False,
                                tenant="fair")
                    for i in range(n):
                        t0 = time.perf_counter()
                        try:
                            got = await cl.get_file(
                                f"/tenants/f{i % TENANT_FILES:04d}")
                            assert len(got) == len(data)
                        except DfsError as e:
                            errors.append(e)
                        walls.append(time.perf_counter() - t0)
                    await trpc.close()

                asyncio.run(seq())

            run()
            return walls, errors

        # Uncontended fair baseline (sequential single-stream reads — the
        # well-behaved-tenant pattern the flood must not break).
        base_walls, base_errors = await asyncio.to_thread(
            fair_reads_in_thread, TENANT_FAIR_READS)
        assert not base_errors, f"baseline reads failed: {base_errors}"
        _tick("tenants-baseline")

        stop = asyncio.Event()
        abuser_ok = 0
        abuser_shed = 0

        async def flood() -> None:
            nonlocal abuser_ok, abuser_shed

            async def one(i: int) -> None:
                nonlocal abuser_ok, abuser_shed
                try:
                    await abuser.get_file(
                        f"/tenants/f{i % TENANT_FILES:04d}")
                    abuser_ok += 1
                except DfsError:
                    # Throttled/shed (rate-limit, queue-full, or retry
                    # budget exhausted against Overloaded replies) — the
                    # QoS doing its job against this tenant.
                    abuser_shed += 1

            i = 0
            while not stop.is_set():
                await asyncio.gather(
                    *(one(i + k) for k in range(TENANT_FLOOD_CONCURRENCY)))
                i += TENANT_FLOOD_CONCURRENCY

        flood_task = asyncio.ensure_future(flood())
        await asyncio.sleep(0.5)  # let the flood build a backlog
        flood_walls, fair_errors = await asyncio.to_thread(
            fair_reads_in_thread, TENANT_FAIR_READS)
        stop.set()
        await flood_task
        # Server-side truth: replica failover hides most throttling from
        # the abuser CLIENT (a shed at one chunkserver fails over to the
        # next), so the shed ratio comes from the per-tenant admission
        # counters every chunkserver exports over ops HTTP.
        abuser_srv = {"admitted": 0.0, "shed": 0.0, "rate_limited": 0.0}
        for addr in cs_addrs:
            host, port = addr.rsplit(":", 1)
            url = f"http://{host}:{int(port) + 1000}/metrics"
            try:
                body = urllib.request.urlopen(url, timeout=5).read().decode()
            except OSError:
                continue
            for ln in body.splitlines():
                if ln.startswith("#"):
                    continue
                for k in abuser_srv:
                    if f"qos_tenant_abuser_{k}_total" in ln:
                        try:
                            abuser_srv[k] += float(ln.split()[-1])
                        except ValueError:
                            pass
        _tick("tenants-flood")

        # Recovery: flood over, tokens refill, BOTH tenants read clean —
        # throttling must never be a permanent penalty.
        rec_walls, rec_errors = await asyncio.to_thread(
            fair_reads_in_thread, 4)
        rec_walls += [await timed_read(abuser, i, rec_errors)
                      for i in range(4)]
        assert not rec_errors, f"post-flood reads failed: {rec_errors}"
        _tick("tenants-recovery")

        await rpc.close()
        base_p99 = p99(base_walls)
        flood_p99 = p99(flood_walls)
        throttled = abuser_srv["shed"] + abuser_srv["rate_limited"]
        srv_attempts = throttled + abuser_srv["admitted"]
        # Uncontended fair-tenant single-stream throughput: the engine
        # half of the A/B (sheds and p99 measure the ladder; this
        # measures the serving path the ladder guards).
        base_wall = sum(base_walls)
        read_gbps = (len(base_walls) * len(data) / base_wall / 1e9
                     if base_wall else 0.0)
        return {
            "metric": (
                "fair-tenant read p99 ms under a noisy-neighbor flood "
                f"({TENANT_FLOOD_CONCURRENCY}-way abuser vs single-stream "
                "fair tenant, per-tenant QoS on; vs_baseline = flood p99 "
                "over uncontended p99 — the chaos-tier acceptance is "
                "p99 <= max(3x uncontended, an absolute floor), this "
                "bench only tracks the trend)"
            ),
            "value": round(flood_p99 * 1000, 1),
            "unit": "ms",
            "vs_baseline": (round(flood_p99 / base_p99, 3)
                            if base_p99 else 0.0),
            "engine": engine,
            "read_gbps": round(read_gbps, 3),
            "tenant_fair_p99_ms": round(flood_p99 * 1000, 1),
            "tenant_fair_baseline_p99_ms": round(base_p99 * 1000, 1),
            "tenant_fair_error_rate": round(
                len(fair_errors) / len(flood_walls), 4),
            # Fraction of abuser admission attempts the chunkservers
            # throttled (queue-full/rate-limit sheds, from the per-tenant
            # server counters; client-side failover masks most of these).
            "tenant_abuser_shed_ratio": (round(throttled / srv_attempts, 3)
                                         if srv_attempts else 0.0),
            "tenant_abuser_ok": abuser_ok,
            "tenant_abuser_client_errors": abuser_shed,
            "tenant_abuser_server_throttled": int(throttled),
            "tenant_recovery_p99_ms": round(p99(rec_walls) * 1000, 1),
            "tenant_flood_concurrency": TENANT_FLOOD_CONCURRENCY,
            "files": TENANT_FILES,
            "qos_env": qos_env,
            "platform": "cpu",  # host data path; no device windows
        }
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


async def _run_tenants() -> dict:
    """Native leg first (the headline), then the asyncio blockport on a
    fresh cluster; the payload carries both legs plus the A/B ratios."""
    from tpudfs.common import native as native_mod

    legs: dict[str, dict] = {}
    engines = ["native", "asyncio"]
    if not native_mod.build_and_load() or not native_mod.has_dataplane():
        # No toolchain: the asyncio leg still measures the ladder, and
        # the payload says exactly why the A/B is missing.
        engines = ["asyncio"]
    for engine in engines:
        legs[engine] = await _run_tenants_engine(engine)
        _tick(f"tenants-{engine}-done")

    headline = dict(legs.get("native") or legs["asyncio"])
    ab_keys = ("read_gbps", "tenant_fair_p99_ms",
               "tenant_fair_baseline_p99_ms", "tenant_abuser_shed_ratio",
               "tenant_abuser_server_throttled", "vs_baseline")
    headline["engines"] = {
        eng: {k: leg[k] for k in ab_keys if k in leg}
        for eng, leg in legs.items()
    }
    if "native" in legs and "asyncio" in legs:
        n, a = legs["native"], legs["asyncio"]
        headline["native_vs_asyncio_gbps"] = (
            round(n["read_gbps"] / a["read_gbps"], 3)
            if a["read_gbps"] else 0.0)
        headline["native_vs_asyncio_fair_p99"] = (
            round(n["tenant_fair_p99_ms"] / a["tenant_fair_p99_ms"], 3)
            if a["tenant_fair_p99_ms"] else 0.0)
    elif "native" not in legs:
        headline["ab_skipped"] = "native dataplane unavailable on this host"
    return headline


def main_tenants() -> None:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _tick("tenants-start")
    _start_watchdog()
    result = asyncio.run(_run_tenants())
    _progress["t"] = None
    _emit_once(result)


#: Set by main(): the startup probe saw a live TPU, so the device phase
#: intends to use it — but must re-check, the tunnel can die mid-run.
_tpu_intended = False
_fell_back_midrun = False


def _decide_device():
    """The first device touch of the process — taken AFTER the host-side
    write windows, re-probing a TPU that was alive at startup. jax's
    backend is uninitialized until here, so a tunnel that wedged during
    the writes downgrades the run to the CPU fallback instead of hanging
    the first compile forever."""
    global _fell_back_midrun
    import jax

    if _tpu_intended and not _probe_tpu(timeout_s=60.0, attempts=2,
                                        retry_wait_s=20.0):
        _fell_back_midrun = True
        jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0]


async def _run_against(maddr: str, cs_addrs: list[str]) -> dict:
    import jax

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient
    from tpudfs.tpu.hbm_reader import HbmReader

    rpc = RpcClient()
    # etag_mode="crc64": hardware CRC-64/NVME ETags instead of md5 on the
    # put path (round-3 verdict item 4 — md5 at ~2 ms/MiB was ~30% of the
    # single-core protocol budget; the S3 gateway still does md5-ETag
    # conformance, it passes explicit etags). Recorded in the JSON as
    # etag_mode so cross-round write numbers are read with this in mind.
    client = Client([maddr], rpc_client=rpc, block_size=BLOCK_MB << 20,
                    etag_mode="crc64")

    # Wait until the master has left safe mode and all 3 chunkservers are
    # registered (first placement needs a full replication set).
    deadline = asyncio.get_event_loop().time() + 60
    while True:
        try:
            await client.create_file("/bench/probe", b"x")
            await client.delete_file("/bench/probe")
            break
        except Exception:
            if asyncio.get_event_loop().time() > deadline:
                raise
            await asyncio.sleep(0.3)
    data = np.random.default_rng(0).integers(
        0, 256, BLOCK_MB << 20, dtype=np.uint8
    ).tobytes()
    wsem = asyncio.Semaphore(WRITE_CONCURRENCY)

    async def put(rep, i):
        async with wsem:
            await client.create_file(f"/bench/r{rep}/f{i:04d}", data)

    # ---- metadata plane: creates/s at the reference harness config
    # (100 files, concurrency 10, dfs_cli.rs:131-146) — empty files, so
    # the number isolates the create -> allocate -> complete proposal
    # path (WAL group commit + fused first-block allocation).
    async def put_empty(rep, i):
        async with wsem:
            await client.create_file(f"/bench/meta{rep}/m{i:03d}", b"")

    # ---- write-side windows: each rep writes a DISTINCT file set (no
    # create-over-existing shortcuts), interleaving creates/s and the 3x
    # pipeline-replicated data writes (logical GB/s).
    meta_samples, meta_fused_samples, write_samples = [], [], []

    async def fused_create(rep: int, i: int) -> None:
        # The metadata PLANE alone: one fused create+alloc proposal (WAL
        # group commit), no data-plane stages. The legacy meta_creates
        # number spends ~3 of its ~4 ms/op in the empty 3x chain write +
        # CompleteFile — i.e., two data-plane fsync stages (round-5
        # breakdown in BENCH_NOTES).
        async with wsem:
            resp = await rpc.call(maddr, "MasterService", "CreateFile",
                                  {"path": f"/bench/metaf{rep}/m{i:03d}",
                                   "first_block": True}, timeout=15.0)
            # A degraded response (alloc skipped: no registered CS, lapsed
            # heartbeat) would silently time the create-ONLY proposal and
            # inflate the create+alloc metric — fail the window instead.
            if not resp.get("block"):
                raise RuntimeError(
                    f"fused alloc degraded: {resp.get('alloc_error')}")

    for rep in range(REPS):
        t0 = time.perf_counter()
        await asyncio.gather(*(put_empty(rep, i) for i in range(100)))
        meta_samples.append(100 / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        await asyncio.gather(*(fused_create(rep, i) for i in range(100)))
        meta_fused_samples.append(100 / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        await asyncio.gather(*(put(rep, i) for i in range(FILES)))
        write_samples.append(
            FILES * len(data) / (time.perf_counter() - t0) / 1e9
        )
        _tick(f"write-rep{rep}")
    _partial.update({
        "write_pipeline_GBps": round(statistics.median(write_samples), 3),
        "write_pipeline_win": _winmm(write_samples),
        "meta_creates_per_s": round(statistics.median(meta_samples), 1),
        "meta_fused_creates_per_s": round(
            statistics.median(meta_fused_samples), 1),
        "files": FILES,
        "etag_mode": client.etag_mode,
    })

    # Drain writeback BEFORE the read windows (untimed): the write phase
    # leaves ~1.2 GB dirty; the kernel flusher wakes ~30 s later — right
    # in the middle of the read windows on this one-core host — and the
    # crater pattern in debug_samples tracked it (later windows worse).
    # A sync here makes the flusher's work happen at a deterministic,
    # untimed point instead.
    import os as _os

    await asyncio.to_thread(_os.sync)
    _tick("sync")

    device = _decide_device()
    _tick("device-init")
    reader = HbmReader(client, [device], batch_reads=BATCH_READS)

    # See the module docstring's "Timing protocol": NO device->host
    # transfer happens before or inside any timed window below — the first
    # D2H of the process permanently degrades the tunneled transport in
    # both directions, so every window synchronizes with block_until_ready
    # (completion wait, no readback) and all verdicts are fetched once at
    # the very end.
    # Warm up kernels + compile caches without any D2H (not the CS block
    # cache: it holds CS_CACHE_BLOCKS blocks; the sweeps touch FILES).
    # warm_batches pre-compiles every fused-round CRC bucket (device-verify
    # platforms only; the host-verify CPU fallback dispatches none).
    reader.warm_batches((BLOCK_MB << 20) // 512)
    _tick("warm-batches")
    # Warm the REMOTE fused path (connection setup + the single-block
    # remote-round shapes) with short-circuit off, so the first gRPC sweep
    # window doesn't pay one-time costs. (The per-block path —
    # block_crc_device — is warmed separately right before the cache
    # sweep, the only consumer left on it.)
    client.local_reads = False
    warm = await reader.read_file_to_device_blocks("/bench/r0/f0000",
                                                   verify="lazy")
    client.local_reads = True
    _tick("warm-remote")
    grpc_files = min(48, FILES)

    async def timed_sweep(items, read_fn, concurrency=READ_CONCURRENCY):
        """Shared sweep harness: sem-gated concurrent per-item reads, one
        block_until_ready over every block's sync set — per-block arrays
        and 0-d CRCs on the unfused path, whole-round batch arrays and CRC
        vectors on the fused one (transfer + on-device fold complete — no
        readback; see Timing protocol).

        GC discipline (pyperf's): collect BEFORE the window, cyclic GC off
        DURING it. A gen-2 collection over this process's object graph
        costs ~0.3 s on the one-core host — landing inside a ~0.15 s sweep
        window craters it 3x (debug_samples showed exactly that shape:
        one random window per run at ~0.3 GB/s, the rest at ~1). The work
        the GC would do is unchanged — it runs between windows instead."""
        import gc

        sem = asyncio.Semaphore(concurrency)
        blocks: list = []

        async def one(item):
            async with sem:
                bs = await read_fn(item)
                blocks.extend(bs)
                return sum(b.size for b in bs)

        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            sizes = await asyncio.gather(*(one(it) for it in items))
            jax.block_until_ready(
                [x for b in blocks for x in b.sync_arrays])
            dt = time.perf_counter() - t0
        finally:
            gc.enable()
        return blocks, sum(sizes) / dt / 1e9

    async def timed_pump_sweep(fn):
        """Same window discipline (GC parked, completion wait in-window,
        no readback) for the native-pump sweeps, which return the whole
        block list in one call."""
        import gc

        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            blocks = await fn()
            jax.block_until_ready(
                [x for b in blocks for x in b.sync_arrays])
            dt = time.perf_counter() - t0
        finally:
            gc.enable()
        return blocks, sum(b.size for b in blocks) / dt / 1e9

    # ---- read-side windows, interleaved per rep (see "Statistical
    # protocol"): raw infeed -> gRPC sweep -> fused cold sweep -> warm
    # sweep. Each rep reads ITS OWN rep's file set, so window r of every
    # sweep covers files written in write-window r.
    raw_samples, grpc_samples, cold_samples, warm_samples = [], [], [], []
    keep_blocks: list = []
    local_blocks = 0

    def retain(blocks: list) -> None:
        """Keep only blocks whose verification is still pending (the final
        confirm needs them); already-verified blocks are asserted and
        DROPPED so ~REPS x 300 MiB of arrays don't stay live across later
        timed windows (allocator churn on the one-core host would skew the
        very medians this protocol stabilizes)."""
        for b in blocks:
            if b.pending_crc is not None or b.batch_pending:
                keep_blocks.append(b)
            else:
                assert b.verified, f"unverified block {b.block_id}"

    retain(warm)

    # One UNTIMED full-size REMOTE sweep: debug_samples show the gRPC
    # windows RAMPING across reps (0.32 -> 0.45) — connection pools,
    # per-peer frames, and the serving engines otherwise reach steady
    # state inside the timed windows (the single-file warm above only
    # compiles shapes and dials one peer). Same harness as the timed
    # window it pre-warms; the throughput is discarded.
    client.local_reads = False
    warm_remote_blocks, _ = await timed_sweep(
        range(grpc_files),
        lambda i: reader.read_file_to_device_blocks(
            f"/bench/r0/f{i:04d}", verify="lazy"),
        concurrency=REMOTE_SWEEP_CONCURRENCY,
    )
    retain(warm_remote_blocks)
    client.local_reads = True
    _tick("warm-remote-sweep")

    # Full-size UNTIMED warm-up sweeps (scripts/sweep_lab.py measurement,
    # idle host: the first fused sweep of a process runs ~3x below steady
    # state — from one-time host costs: allocator arenas growing to round
    # size, to_thread executor spin-up, jax dispatch caches). Two
    # cold-pattern + one warm-pattern pump passes over the rep-0 set reach
    # steady state before any timed window (still no D2H here). Page-cache
    # state is unaffected — the whole dataset was written moments ago and
    # this host caches it all — so this warms the PROCESS, not the data.
    for _ in range(2):
        blocks = await reader.sweep_paths_to_device(
            [f"/bench/r0/f{i:04d}" for i in range(FILES)])
        jax.block_until_ready([x for b in blocks for x in b.sync_arrays])
        retain(blocks)
    warm_metas = await asyncio.gather(
        *(client.get_file_info(f"/bench/r0/f{i:04d}") for i in range(FILES))
    )
    blocks = await reader.sweep_metas_to_device(warm_metas, device)
    jax.block_until_ready([x for b in blocks for x in b.sync_arrays])
    retain(blocks)
    _tick("warmup-sweeps")

    for rep_i in range(READ_REPS):
        # Read windows 3 and 4 re-read sets 0 and 1: per-set first-touch
        # is free (sweep_lab --multiset: never-read sets sweep at full
        # speed once the process is warm) and page-cache state is
        # identical, so cycling sets changes nothing but the name.
        rep = rep_i % REPS
        raw_samples.append(_bench_raw_infeed(device, len(data), 16))
        _tick(f"raw-rep{rep_i}")

        # Remote read path: short-circuit disabled — what a non-colocated
        # client gets over gRPC. Verification is dispatched in-window (the
        # CRC folds are part of the measured work), resolved at confirm.
        client.local_reads = False
        grpc_blocks, gbps = await timed_sweep(
            range(grpc_files),
            lambda i: reader.read_file_to_device_blocks(
                f"/bench/r{rep}/f{i:04d}", verify="lazy"),
            concurrency=REMOTE_SWEEP_CONCURRENCY,
        )
        client.local_reads = True
        grpc_samples.append(gbps)
        retain(grpc_blocks)
        _tick(f"grpc-rep{rep_i}")

        # Primary read path: short-circuit (client colocated with the
        # chunkservers — the north-star topology) via the NATIVE SWEEP
        # PUMP (hbm_reader.sweep_paths_to_device): metadata fan-out
        # in-window, then a native producer thread drives fused
        # pread+3-lane-CRC into ring buffers while Python's per-round
        # work is one device_put — the round-4 verdict's "move the
        # steady-state round loop out of Python".
        local_before = client.local_read_blocks
        comb_before = sum(c.blocks for c in reader._combiners.values())
        sweep_before = reader.sweep_blocks
        cold_blocks, gbps = await timed_pump_sweep(
            lambda: reader.sweep_paths_to_device(
                [f"/bench/r{rep}/f{i:04d}" for i in range(FILES)]))
        cold_samples.append(gbps)
        retain(cold_blocks)
        # Pump/fused rounds bypass client._read_local, so count their
        # served blocks alongside the classic short-circuit counter.
        local_blocks += (client.local_read_blocks - local_before
                         + sum(c.blocks for c in reader._combiners.values())
                         - comb_before
                         + reader.sweep_blocks - sweep_before)
        _tick(f"cold-rep{rep_i}")

        # Warm infeed sweep: the steady-state training-infeed pattern —
        # the immutable block layout cached ONCE outside the window
        # (exactly how the grain infeed reads), the pump doing the rest.
        metas = await asyncio.gather(
            *(client.get_file_info(f"/bench/r{rep}/f{i:04d}")
              for i in range(FILES))
        )
        warm_blocks, gbps = await timed_pump_sweep(
            lambda: reader.sweep_metas_to_device(metas, device))
        warm_samples.append(gbps)
        retain(warm_blocks)
        _tick(f"warm-rep{rep_i}")
        _partial.update({
            "raw_infeed_GBps": round(statistics.median(raw_samples), 3),
            "grpc_read_GBps": round(statistics.median(grpc_samples), 3),
            "value": round(statistics.median(cold_samples), 3),
            "warm_infeed_read_GBps": round(
                statistics.median(warm_samples), 3),
        })

    # ---- dedicated cache sweep: a working set that FITS the chunkserver
    # LRU (CACHE_FILES < CS_CACHE_BLOCKS), read CACHE_PASSES times over
    # per-block reads (batch_reads=0 — fused ReadBlocks frames and local
    # short-circuit both bypass the serving process's cache, which is why
    # rounds 1-3 recorded a constant 0.0 here). Passes run SEQUENTIALLY
    # (concurrent passes could double-miss a block whose first read is
    # still in flight), so the hit/miss delta of the serving processes is
    # deterministic: only window 0's first pass misses. REPS windows,
    # median + spread like every other GB/s number.
    cache_reader = HbmReader(client, [device], batch_reads=0)
    # Untimed per-block warm read (a file OUTSIDE the sweep's working set,
    # so the LRU contents stay deterministic): the fused sweeps above never
    # exercise the per-block path, so without this the cache sweep's first
    # window would pay the one-time block_crc_device XLA compile. Its lazy
    # CRC also seeds warm_confirm — EVERY per-block single reaching the
    # final confirm comes from this read + the cache sweep (all fused
    # blocks resolve through their batch vectors), so the confirm-stack
    # bucket is sized off the cache-sweep count, keeping that compile out
    # of the measured confirm_s.
    client.local_reads = False
    cache_warm = await cache_reader.read_file_to_device_blocks(
        "/bench/r0/f0010", verify="lazy")
    retain(cache_warm)
    sample = next(
        (b for b in cache_warm if b.pending_crc is not None), None)
    if sample is not None:
        reader.warm_confirm(
            sample, REPS * CACHE_PASSES * CACHE_FILES + len(cache_warm))
    before = []
    for addr in cs_addrs:
        s = await rpc.call(addr, "ChunkServerService", "Stats", {})
        before.append((s["cache_hits"], s["cache_misses"]))
    cache_samples = []
    # Per-op wall latency across every file read in the sweep: the
    # throughput median can hide a fat tail (one straggling replica, a
    # cache-miss stall), and the roadmap cache regression needs the
    # per-op distribution to tell "all reads slowed" from "a few reads
    # stalled". Ops run CACHE_FILES-wide, so this is latency under the
    # sweep's own concurrency — the number a training input pipeline
    # actually experiences.
    cache_lat: list[float] = []

    async def _timed_cache_read(path: str):
        t = time.perf_counter()
        blocks = await cache_reader.read_file_to_device_blocks(
            path, verify="lazy")
        cache_lat.append(time.perf_counter() - t)
        return blocks

    for _ in range(REPS):
        t0 = time.perf_counter()
        nbytes = 0
        for _pass in range(CACHE_PASSES):
            blocks_lists = await asyncio.gather(*(
                _timed_cache_read(f"/bench/r0/f{i:04d}")
                for i in range(CACHE_FILES)
            ))
            flat = [b for bs in blocks_lists for b in bs]
            jax.block_until_ready(
                [x for b in flat for x in b.sync_arrays]
            )
            nbytes += sum(b.size for b in flat)
            retain(flat)
        cache_samples.append(nbytes / (time.perf_counter() - t0) / 1e9)
        _tick("cache-rep")
    client.local_reads = True
    cache_hits = cache_misses = 0
    for addr, (h0, m0) in zip(cs_addrs, before):
        s = await rpc.call(addr, "ChunkServerService", "Stats", {})
        cache_hits += s["cache_hits"] - h0
        cache_misses += s["cache_misses"] - m0

    # ---- on-chip benches: pure device compute (H2D warm-up only), still
    # ahead of the first D2H so their inputs upload at full speed.
    ici_samples, ici_oks = _bench_ici_write_step(device)
    _tick("ici")
    ec_samples, ec_acks = _bench_ec_scatter_step(device)
    _tick("ec")

    # ---- end of timed windows: ONE batched verdict fetch resolves every
    # lazy verification (the process's first D2H), then assert.
    t0 = time.perf_counter()
    await reader.confirm(keep_blocks)
    confirm_s = time.perf_counter() - t0
    _tick("confirm")
    assert all(b.verified for b in keep_blocks)
    assert np.asarray(ici_oks).all(), "ICI write step verification failed"
    assert (np.asarray(ec_acks) == 1).all(), "EC scatter verification failed"

    raw_after = _bench_raw_infeed(device, len(data), 16)

    await rpc.close()

    med = statistics.median
    achieved = med(cold_samples)
    raw = med(raw_samples)  # the honest (unpoisoned) denominator
    target = 0.9 * raw
    return {
        "metric": (
            "1MiB-chunk read GB/s/host into TPU HBM (3x-replicated DFS, "
            "on-device CRC32C verify) + 3x-replication write GB/s over ICI"
        ),
        "value": round(achieved, 3),
        "unit": "GB/s",
        "vs_baseline": round(achieved / target, 3) if target else 0.0,
        "windows": READ_REPS,
        "write_windows": REPS,
        "value_win": _winmm(cold_samples),
        "grpc_read_GBps": round(med(grpc_samples), 3),
        "grpc_read_win": _winmm(grpc_samples),
        "warm_infeed_read_GBps": round(med(warm_samples), 3),
        "warm_infeed_win": _winmm(warm_samples),
        "local_read_blocks": local_blocks,
        "confirm_s": round(confirm_s, 3),
        "write_pipeline_GBps": round(med(write_samples), 3),
        "write_pipeline_win": _winmm(write_samples),
        "meta_creates_per_s": round(med(meta_samples), 1),
        "meta_creates_win": _winmm(meta_samples, 1),
        "meta_fused_creates_per_s": round(med(meta_fused_samples), 1),
        "meta_fused_creates_win": _winmm(meta_fused_samples, 1),
        "ici_write_GBps": round(med(ici_samples), 3),
        "ici_write_win": _winmm(ici_samples),
        "ici_ec_scatter_GBps": round(med(ec_samples), 3),
        "ici_ec_scatter_win": _winmm(ec_samples),
        "raw_infeed_GBps": round(raw, 3),
        "raw_infeed_win": _winmm(raw_samples),
        "raw_infeed_after_GBps": round(raw_after, 3),
        "files": FILES,
        "cache_read_GBps": round(med(cache_samples), 3),
        "cache_read_win": _winmm(cache_samples),
        # Static copies-per-byte per swept route, from the committed
        # copy_ledger.json — the budget the lint gate enforces, sitting
        # next to the GB/s it predicts (TPL06x, docs/static-analysis.md).
        "copies_per_byte": _ledger_copies_per_byte(),
        "cache_read_p50_ms": round(_pct(cache_lat, 0.50) * 1e3, 2),
        "cache_read_p99_ms": round(_pct(cache_lat, 0.99) * 1e3, 2),
        "cache_read_ops": len(cache_lat),
        "cs_cache_hit_rate": round(
            cache_hits / max(1, cache_hits + cache_misses), 3
        ),
        "etag_mode": client.etag_mode,
        # The pump verifies END-TO-END against the CompleteFile-recorded
        # whole-block checksums INSIDE the native producer (3-lane
        # hardware CRC32C fused into the pread) — host-side, overlapping
        # the device copies; the per-block/combiner paths still carry the
        # on-device fold where the platform wants it.
        "verify_mode": "host-crc32c(sweep-pump)",
        "platform": jax.devices()[0].platform,
        **({"debug_samples": {
            "raw": [round(x, 3) for x in raw_samples],
            "grpc": [round(x, 3) for x in grpc_samples],
            "cold": [round(x, 3) for x in cold_samples],
            "warm": [round(x, 3) for x in warm_samples],
            "write": [round(x, 3) for x in write_samples],
        }} if __import__("os").environ.get("BENCH_DEBUG") else {}),
    }


def _ledger_copies_per_byte() -> dict:
    """Static copies-per-byte column from the committed byte-cost ledger
    (tpudfs/analysis/copy_ledger.json, docs/static-analysis.md TPL06x),
    keyed by the bench column each route's GB/s lands in. Read straight
    from the committed file — the budget the CI gate enforces — so the
    bench path pays no call-graph build."""
    import os

    route_for_column = {
        "cache_read": "cache_hit_read",
        "warm_infeed_read": "warm_infeed_read",
        "write_pipeline": "chain_write",
        "ici_ec_scatter": "ec_encode_scatter",
        "ckpt": "ckpt_stage_publish",
    }
    try:
        with open(_repo_path(
                os.path.join("tpudfs", "analysis", "copy_ledger.json"))) as f:
            routes = json.load(f)["routes"]
    except (OSError, ValueError, KeyError):
        return {}
    return {col: routes[name]["copies"]
            for col, name in route_for_column.items() if name in routes}


def _winmm(xs: list, nd: int = 3) -> list:
    return [round(min(xs), nd), round(max(xs), nd)]


def _pct(xs: list, q: float) -> float:
    """Nearest-rank percentile (p99 of 80 samples = the worst sample, not
    an interpolated value that no op actually experienced)."""
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


def _probe_tpu(timeout_s: float = 90.0, attempts: int = 2,
               retry_wait_s: float = 45.0) -> bool:
    """The tunneled TPU sometimes wedges so hard that jax.devices() never
    returns — probe it in a DISPOSABLE subprocess so the bench itself can't
    hang, and fall back to CPU (honestly labeled) when the device is gone:
    a degraded JSON line beats a driver timeout with no data. Wedges are
    sometimes transient, so one short retry is worth the wait before
    conceding the whole run to the CPU."""
    import os
    import subprocess
    import sys
    import time as _time

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return False
    for attempt in range(attempts):
        if attempt:
            _time.sleep(retry_wait_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, numpy as np\n"
                 "d = jax.devices()[0]\n"
                 "jax.block_until_ready(jax.device_put(np.zeros(1024), d))\n"
                 "print(d.platform)"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode == 0 and "cpu" not in proc.stdout:
                return True
        except subprocess.TimeoutExpired:
            pass
    return False


# --------------------------------------------------------- window sprint
#
# The tunneled TPU comes alive for SHORT windows (minutes) between hours of
# wedge; round 4 lost its one window to ~10 min of host-side warm-up. The
# sprint protocol gets the first device touch within seconds of LIVE:
#
# - ``bench.py --standby`` (run by scripts/tpu_probe_loop.sh while the
#   tunnel is wedged, CPU-only): boots the bench cluster, pre-writes the
#   read-phase file set, records its addresses in standby.json, and stays
#   resident — all the host-side minutes are paid OUTSIDE the window.
# - ``bench.py --sprint`` (run by the probe loop the moment a probe sees
#   LIVE): connects to the standby cluster, touches the device
#   immediately, and runs the DEVICE-dependent windows first (raw infeed
#   -> fused cold sweep -> warm infeed -> ICI/EC kernels), emitting
#   partials as each lands so even a mid-run wedge leaves data. Results
#   persist to BENCH_SPRINT.json.
# - A round-end ``bench.py`` that has to fall back to CPU merges the
#   latest real-TPU sprint capture into its JSON tail as "tpu_sprint",
#   so the driver's BENCH_r{N}.json carries the real-TPU numbers even
#   when the tunnel is wedged at round end.

SPRINT_DIR = "/tmp/tpudfs-sprint"
SPRINT_READ_REPS = 3


def _repo_path(name: str) -> str:
    import os

    return os.path.join(os.path.dirname(os.path.abspath(__file__)), name)


def _read_standby():
    """(maddr, cs_addrs) of a live standby cluster, else None (verified:
    parent alive + master socket connectable)."""
    import os
    import socket

    path = os.path.join(SPRINT_DIR, "standby.json")
    try:
        with open(path) as f:
            info = json.load(f)
        if not info.get("ready"):
            return None  # mid-prep: sprint self-provisions instead
        os.kill(int(info["pid"]), 0)  # parent alive?
        host, port = info["maddr"].rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=2.0):
            pass
        return info["maddr"], list(info["cs_addrs"])
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return None


async def _prepare_r0_files(maddr: str) -> None:
    """Write the read-phase file set (/bench/r0/f0000..) if absent, then
    sync — the host-side minutes the sprint must not pay in-window."""
    import os as _os

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient

    rpc = RpcClient()
    client = Client([maddr], rpc_client=rpc, block_size=BLOCK_MB << 20,
                    etag_mode="crc64")
    deadline = asyncio.get_event_loop().time() + 60
    while True:
        try:
            await client.create_file("/bench/probe", b"x")
            await client.delete_file("/bench/probe")
            break
        except Exception:
            if asyncio.get_event_loop().time() > deadline:
                raise
            await asyncio.sleep(0.3)
    try:
        last = await client.get_file_info(f"/bench/r0/f{FILES - 1:04d}")
    except Exception:
        last = None
    if last is None:
        data = np.random.default_rng(0).integers(
            0, 256, BLOCK_MB << 20, dtype=np.uint8).tobytes()
        sem = asyncio.Semaphore(WRITE_CONCURRENCY)

        async def put(i):
            async with sem:
                try:
                    await client.create_file(f"/bench/r0/f{i:04d}", data)
                except Exception as e:
                    if "exists" not in str(e).lower():
                        raise

        await asyncio.gather(*(put(i) for i in range(FILES)))
        await asyncio.to_thread(_os.sync)
    await rpc.close()


async def _sprint_against(maddr: str, cs_addrs: list[str],
                          standby: bool) -> dict:
    """Device-first bench windows against a (pre-warmed) cluster. Same
    measurement discipline as the full run — GC parked during windows,
    D2H-free until the single confirm, median over SPRINT_READ_REPS —
    minus the host-side phases (writes/metadata/gRPC/cache), which the
    full protocol covers on CPU and a short window cannot afford."""
    import gc

    import jax

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient
    from tpudfs.tpu.hbm_reader import HbmReader

    await _prepare_r0_files(maddr)
    _tick("sprint-files")
    rpc = RpcClient()
    client = Client([maddr], rpc_client=rpc, block_size=BLOCK_MB << 20,
                    etag_mode="crc64")
    client.local_reads = True
    data_len = BLOCK_MB << 20

    # First device touch — seconds after LIVE, nothing host-side left.
    device = _decide_device()
    _tick("device-init")
    _partial["sprint_standby"] = standby
    raw_samples = [_bench_raw_infeed(device, data_len, 8)]
    _partial["raw_infeed_GBps"] = round(raw_samples[0], 3)
    _tick("sprint-raw0")

    reader = HbmReader(client, [device], batch_reads=BATCH_READS)
    # NO warm_batches here: the sweep pump verifies HOST-side (fused
    # hardware CRC in the native producer) and never dispatches the
    # batched on-device CRC buckets — on a real TPU those five compiles
    # cost ~100-200 s, which is the whole window (the per-block fallback
    # path can hit one uncompiled shape on a corrupt/missing block; the
    # persistent XLA cache amortizes that across windows).
    _tick("sprint-reader")
    keep_blocks: list = []

    def retain(blocks: list) -> None:
        for b in blocks:
            if b.pending_crc is not None or b.batch_pending:
                keep_blocks.append(b)

    paths = [f"/bench/r0/f{i:04d}" for i in range(FILES)]

    async def pump_sweep(fn, timed: bool):
        import gc

        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            blocks = await fn()
            jax.block_until_ready(
                [x for b in blocks for x in b.sync_arrays])
            dt = time.perf_counter() - t0
        finally:
            gc.enable()
        retain(blocks)
        return sum(b.size for b in blocks) / dt / 1e9 if timed else 0.0

    # One untimed pass reaches process steady state (full protocol uses
    # three; the sprint trades window time for a slightly cold first rep
    # — the median over 3 tolerates it).
    await pump_sweep(lambda: reader.sweep_paths_to_device(paths), False)
    _tick("sprint-warmup")

    cold_samples, warm_samples = [], []
    metas = await asyncio.gather(
        *(client.get_file_info(p) for p in paths))
    for rep_i in range(SPRINT_READ_REPS):
        cold_samples.append(await pump_sweep(
            lambda: reader.sweep_paths_to_device(paths), True))
        _tick(f"sprint-cold{rep_i}")
        warm_samples.append(await pump_sweep(
            lambda: reader.sweep_metas_to_device(metas, device), True))
        _tick(f"sprint-warm{rep_i}")
        if rep_i:
            raw_samples.append(_bench_raw_infeed(device, data_len, 8))
        _partial.update({
            "value": round(statistics.median(cold_samples), 3),
            "warm_infeed_read_GBps": round(
                statistics.median(warm_samples), 3),
            "raw_infeed_GBps": round(statistics.median(raw_samples), 3),
        })
        _tick(f"sprint-rep{rep_i}")

    ici_samples, ici_oks = _bench_ici_write_step(device)
    _tick("ici")
    ec_samples, ec_acks = _bench_ec_scatter_step(device)
    _tick("ec")

    t0 = time.perf_counter()
    await reader.confirm(keep_blocks)
    confirm_s = time.perf_counter() - t0
    _tick("confirm")
    assert all(b.verified for b in keep_blocks)
    assert np.asarray(ici_oks).all()
    await rpc.close()

    med = statistics.median
    achieved = med(cold_samples)
    raw = med(raw_samples)
    target = 0.9 * raw
    return {
        "metric": (
            "SPRINT: 1MiB-chunk read GB/s/host into TPU HBM "
            "(3x-replicated DFS, end-to-end CRC32C verify), device "
            "windows only (see bench.py window-sprint protocol)"
        ),
        "value": round(achieved, 3),
        "unit": "GB/s",
        "vs_baseline": round(achieved / target, 3) if target else 0.0,
        "windows": SPRINT_READ_REPS,
        "value_win": _winmm(cold_samples),
        "warm_infeed_read_GBps": round(med(warm_samples), 3),
        "warm_infeed_win": _winmm(warm_samples),
        "raw_infeed_GBps": round(raw, 3),
        "raw_infeed_win": _winmm(raw_samples),
        "ici_write_GBps": round(med(ici_samples), 3),
        "ici_ec_scatter_GBps": round(med(ec_samples), 3),
        "confirm_s": round(confirm_s, 3),
        "files": FILES,
        "sprint_standby": standby,
        "verify_mode": "host-crc32c(sweep-pump)",
        "platform": jax.devices()[0].platform,
    }


async def _run_sprint() -> dict:
    import tempfile

    standby = _read_standby()
    if standby:
        return await _sprint_against(*standby, standby=True)
    # No standby: self-provision (pays the write minutes in-window; the
    # probe loop normally has a standby up long before a LIVE probe).
    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-sprint-")
    maddr, cs_addrs, procs = _spawn_cluster(tmp.name)
    try:
        return await _sprint_against(maddr, cs_addrs, standby=False)
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


def main_standby() -> None:
    """Resident prep cluster for the window sprint (CPU-only; never
    touches the device). Fresh state every launch: stale master metadata
    would reference dead chunkserver ports."""
    import fcntl
    import os
    import shutil
    import signal

    from tpudfs.testing.procs import terminate_all

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.makedirs(SPRINT_DIR, exist_ok=True)
    # One standby owns the role for the machine: a duplicate launched
    # during the (minutes-long) file prep would rmtree the live one's
    # block stores out from under its running cluster.
    role_fd = os.open(os.path.join(SPRINT_DIR, "standby.lock"),
                      os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(role_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        print("standby already running; exiting", flush=True)
        return
    marker = os.path.join(SPRINT_DIR, "standby.json")
    tmp_path = os.path.join(SPRINT_DIR, ".standby.tmp")

    def write_marker(payload: dict) -> None:
        with open(tmp_path, "w") as f:
            json.dump(payload, f)
        os.replace(tmp_path, marker)

    # Provisional marker FIRST (before the multi-second cluster spawn):
    # the probe loop's liveness check and the full bench's _stop_standby
    # both key on this pid; the sprint side requires ready=true and
    # self-provisions until then.
    write_marker({"maddr": "", "cs_addrs": [],
                  "pid": os.getpid(), "ready": False})
    # SIGTERM during the spawn itself: exit via SystemExit so atexit
    # (which _spawn_cluster arms with terminate_all) reaps any children
    # already started — the default handler would orphan them.
    def exit_via_atexit(_sig, _frm):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, exit_via_atexit)
    root = os.path.join(SPRINT_DIR, "cluster")
    shutil.rmtree(root, ignore_errors=True)
    maddr, cs_addrs, procs = _spawn_cluster(root)

    def bail(_sig, _frm):
        terminate_all(procs)
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, bail)
    signal.signal(signal.SIGINT, bail)
    try:
        asyncio.run(_prepare_r0_files(maddr))
    except BaseException:
        terminate_all(procs)
        try:
            os.remove(marker)
        except OSError:
            pass
        raise
    write_marker({"maddr": maddr, "cs_addrs": cs_addrs,
                  "pid": os.getpid(), "ready": True})
    print(f"standby ready: {maddr} {cs_addrs}", flush=True)
    while True:
        time.sleep(60)
        if any(p.poll() is not None for p in procs):
            # A cluster process died; drop the marker so the probe loop
            # relaunches a healthy standby.
            try:
                os.remove(os.path.join(SPRINT_DIR, "standby.json"))
            except OSError:
                pass
            terminate_all(procs)
            raise SystemExit(1)


def main_sprint() -> None:
    """Window sprint: assumes a probe JUST saw LIVE. Exits quietly when
    the device is already gone (windows are short; a full probe retry
    cycle would eat one)."""
    import fcntl
    import os

    lock_fd = os.open("/tmp/tpudfs-tpu.lock", os.O_CREAT | os.O_RDWR, 0o644)
    fcntl.flock(lock_fd, fcntl.LOCK_EX)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        cpu_requested = True
    else:
        cpu_requested = False
        if not _probe_tpu(timeout_s=60.0, attempts=1):
            _emit_once({"metric": "SPRINT aborted", "value": 0.0,
                        "unit": "GB/s", "vs_baseline": 0.0,
                        "platform": "tpu-unreachable-at-sprint"})
            return
    import jax

    if cpu_requested:
        jax.config.update("jax_platforms", "cpu")
    else:
        global _tpu_intended
        _tpu_intended = True
    try:
        # Persistent XLA compile cache: the first window pays the
        # compiles, every later window reuses them from disk.
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(SPRINT_DIR, "xla-cache"))
    except Exception:
        pass
    global WEDGE_TIMEOUT_S, _sprint_mode
    WEDGE_TIMEOUT_S = 300.0  # sprint: concede faster, partials are out
    _sprint_mode = True
    _tick("sprint-start")
    _start_watchdog()
    result = asyncio.run(_run_sprint())
    result["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())
    _progress["t"] = None
    _emit_once(result)
    if "cpu" not in str(result.get("platform", "")):
        with open(_repo_path("BENCH_SPRINT.json.tmp"), "w") as f:
            json.dump(result, f, indent=1)
        os.replace(_repo_path("BENCH_SPRINT.json.tmp"),
                   _repo_path("BENCH_SPRINT.json"))


def _stop_standby() -> None:
    """Terminate the sprint standby cluster for the duration of a FULL
    bench run: 4 idle-but-heartbeating processes measurably depress the
    write/metadata windows on the one-core host. The probe loop relaunches
    the standby once the bench releases the TPU lock (it skips standby
    management while the lock is held).

    Discovery is flock-based, not marker-based: the standby writes
    standby.json only after its (minutes-long) cluster spawn, but it
    holds standby.lock from its first moments — so a standby launched
    just before we took the TPU lock is still caught. The role lock
    being ACQUIRABLE twice, a beat apart, is the all-clear (the second
    check closes the nohup -> python-startup window)."""
    import fcntl
    import os
    import signal
    import time as _time

    lock_path = os.path.join(SPRINT_DIR, "standby.lock")
    marker = os.path.join(SPRINT_DIR, "standby.json")

    def role_free() -> bool:
        try:
            fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        except OSError:
            return True
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            fcntl.flock(fd, fcntl.LOCK_UN)
            return True
        except OSError:
            return False
        finally:
            os.close(fd)

    deadline = _time.monotonic() + 30.0
    while _time.monotonic() < deadline:
        if role_free():
            _time.sleep(2.0)  # close the launch-in-progress window
            if role_free():
                return
            continue
        # A standby holds the role: its marker carries the pid (written
        # right after cluster spawn; poll until it appears).
        try:
            with open(marker) as f:
                pid = int(json.load(f)["pid"])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            _time.sleep(0.5)
            continue
        try:
            os.kill(pid, signal.SIGTERM)
            for _ in range(50):
                _time.sleep(0.1)
                os.kill(pid, 0)
        except OSError:
            pass
        try:
            os.remove(marker)
        except OSError:
            pass
        return


def _merge_sprint(result: dict) -> None:
    """A CPU-fallback round-end run carries the latest real-TPU sprint
    capture so BENCH_r{N}.json shows the device numbers."""
    import os

    try:
        with open(_repo_path("BENCH_SPRINT.json")) as f:
            sprint = json.load(f)
        if "cpu" not in str(sprint.get("platform", "")):
            result["tpu_sprint"] = {
                k: sprint[k] for k in (
                    "value", "value_win", "warm_infeed_read_GBps",
                    "warm_infeed_win", "raw_infeed_GBps", "ici_write_GBps",
                    "ici_ec_scatter_GBps", "vs_baseline", "windows",
                    "captured_at", "platform", "sprint_standby")
                if k in sprint}
    except (OSError, json.JSONDecodeError):
        pass


def main() -> None:
    import fcntl
    import os

    # Exclusive TPU lock for the whole run: the background probe loop
    # (scripts/tpu_probe_loop.sh) flocks the same file non-blockingly and
    # skips its probe while we hold it — otherwise its 60 s jax.devices()
    # hold could make OUR probe time out and silently demote a healthy-TPU
    # run to cpu-fallback (and its jax import would steal the one core
    # mid-timed-window).
    lock_fd = os.open("/tmp/tpudfs-tpu.lock", os.O_CREAT | os.O_RDWR, 0o644)
    fcntl.flock(lock_fd, fcntl.LOCK_EX)
    _stop_standby()  # its idle cluster still steals the one bench core

    requested_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
    fell_back = False
    if not requested_cpu and not _probe_tpu():
        fell_back = True
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["JAX_PLATFORM_NAME"] = "cpu"
    if requested_cpu or fell_back:
        # The env var alone is NOT enough: the preloaded axon TPU plugin
        # still wins the backend race (and hangs when the tunnel is
        # wedged) unless the platform is forced before first backend use.
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        global _tpu_intended
        _tpu_intended = True
    _tick("cluster-spawn")
    _start_watchdog()
    result = asyncio.run(_run())
    if fell_back:
        result["platform"] = "cpu-fallback(tpu unreachable)"
    elif _fell_back_midrun:
        result["platform"] = "cpu-fallback(tpu wedged mid-run)"
    if "cpu" in str(result["platform"]):
        _merge_sprint(result)
    _progress["t"] = None  # disarm the watchdog before the final line
    _emit_once(result)


if __name__ == "__main__":
    import sys

    if "--standby" in sys.argv:
        main_standby()
    elif "--sprint" in sys.argv:
        main_sprint()
    elif "--ckpt" in sys.argv:
        main_ckpt()
    elif "--write-stages" in sys.argv:
        main_write_stages()
    elif "--tenants" in sys.argv:
        main_tenants()
    else:
        main()
