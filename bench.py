"""tpudfs flagship benchmark (driver-run, one JSON line).

Metric (BASELINE.json): chunk read GB/s/host into TPU HBM with 3x-replicated
storage and end-to-end CRC32C verification running ON the device (Pallas).

Path measured: a live in-process DFS (1 master + 3 chunkservers over real
gRPC sockets, 3x pipeline-replicated 1 MiB blocks) read through the client's
concurrent fan-out into device memory via HbmReader — per-block device_put,
per-512B-chunk CRC32C on the accelerator, GF(2)-combine against the stored
block checksum.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the ratio
is against the BASELINE.json north-star target = 90% of this host's raw
host->device infeed bandwidth (measured in the same process with plain
device_put of identical buffers). vs_baseline = achieved / (0.9 * raw_infeed).
"""

from __future__ import annotations

import asyncio
import json
import time

import numpy as np

FILES = 48
BLOCK_MB = 1


async def _run() -> dict:
    import jax

    from tpudfs.chunkserver.blockstore import BlockStore
    from tpudfs.chunkserver.service import ChunkServer
    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient, RpcServer
    from tpudfs.master.service import Master
    from tpudfs.tpu.hbm_reader import HbmReader
    import socket
    import tempfile

    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-bench-")
    root = tmp.name

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    rpc = RpcClient()
    maddr = f"127.0.0.1:{free_port()}"
    master = Master(maddr, [], f"{root}/m0", rpc_client=rpc)
    mserver = RpcServer(port=int(maddr.rsplit(":", 1)[1]))
    master.attach(mserver)
    await mserver.start()
    await master.start(background_tasks=False)
    chunkservers = []
    for i in range(3):
        cs = ChunkServer(
            BlockStore(f"{root}/cs{i}/hot"), master_addrs=[maddr],
            rpc_client=rpc,
        )
        await cs.start(scrubber=False)
        chunkservers.append(cs)
    # Register CSes via one synthetic heartbeat each (no loop needed).
    for cs in chunkservers:
        await master.rpc_heartbeat({
            "chunk_server_address": cs.address,
            "used_space": 0, "available_space": 1 << 40, "chunk_count": 0,
            "bad_blocks": [], "rack_id": cs.address,
        })
    master.state.exit_safe_mode()

    client = Client([maddr], rpc_client=rpc, block_size=BLOCK_MB << 20)
    data = np.random.default_rng(0).integers(
        0, 256, BLOCK_MB << 20, dtype=np.uint8
    ).tobytes()
    sem = asyncio.Semaphore(8)

    async def put(i):
        async with sem:
            await client.create_file(f"/bench/f{i:04d}", data)

    await asyncio.gather(*(put(i) for i in range(FILES)))

    device = jax.devices()[0]
    reader = HbmReader(client, [device])

    # Warm up kernels + caches.
    await reader.read_file_to_device_blocks("/bench/f0000", verify=True)

    async def read_one(i):
        async with sem:
            blocks = await reader.read_file_to_device_blocks(
                f"/bench/f{i:04d}", verify=True
            )
            return sum(b.size for b in blocks)

    t0 = time.perf_counter()
    sizes = await asyncio.gather(*(read_one(i) for i in range(FILES)))
    wall = time.perf_counter() - t0
    total = sum(sizes)
    achieved = total / wall / 1e9

    # Raw host->HBM infeed bandwidth on identical buffers with the SAME
    # 8-way concurrency as the measured path (the north-star denominator:
    # target is 90% of this).
    buf = np.frombuffer(data, dtype=np.uint8).reshape(-1, 512).view("<u4")
    jax.device_put(buf, device).block_until_ready()
    reps = 32

    async def raw_put(_):
        async with sem:
            await asyncio.to_thread(
                lambda: jax.device_put(buf, device).block_until_ready()
            )

    t0 = time.perf_counter()
    await asyncio.gather(*(raw_put(i) for i in range(reps)))
    raw = (len(data) * reps) / (time.perf_counter() - t0) / 1e9

    for cs in chunkservers:
        await cs.stop()
    await master.stop()
    await mserver.stop()
    await rpc.close()
    tmp.cleanup()

    target = 0.9 * raw
    return {
        "metric": (
            "1MiB-chunk read GB/s/host into TPU HBM "
            "(3x-replicated DFS, on-device CRC32C verify)"
        ),
        "value": round(achieved, 3),
        "unit": "GB/s",
        "vs_baseline": round(achieved / target, 3) if target else 0.0,
        "raw_infeed_GBps": round(raw, 3),
        "files": FILES,
        "platform": jax.devices()[0].platform,
    }


def main() -> None:
    result = asyncio.run(_run())
    print(json.dumps(result))


if __name__ == "__main__":
    main()
