"""tpudfs flagship benchmark (driver-run, one JSON line).

Metric (BASELINE.json): "chunk read GB/s/host into TPU HBM; 3x-replication
write GB/s over ICI" — BOTH sides are reported:

- read side: a live DFS — 1 master + 3 chunkservers, each its OWN OS process
  (as in the reference's docker-compose topology; servers must not share the
  client's GIL) — with 3x pipeline-replicated 1 MiB blocks, read through the
  client's concurrent fan-out into device memory via HbmReader: per-block
  device_put, per-512B-chunk CRC32C + GF(2) combine-fold ON the accelerator
  (block_crc_device), one host sync for the whole sweep (lazy verify +
  confirm). The dataset (128 x 1 MiB) far exceeds the chunkservers' LRU
  block cache (capped at 8 blocks here), so reads exercise the disk path.
- write side: (a) the DFS 3x pipeline-replicated write path (client -> CS1 ->
  CS2 -> CS3 chain over gRPC), logical GB/s; (b) the TPU-native replacement:
  `replicated_write_step` — ppermute chain + on-device CRC verify + ack psum
  — timed on the real chip (replication-degenerate on a 1-device mesh; the
  multi-device layout is validated by dryrun_multichip).

vs_baseline: the reference publishes no numbers (BASELINE.md), so the ratio
is against the BASELINE.json north-star target = 90% of this host's raw
host->device infeed bandwidth, measured honestly: one dispatcher thread
issues all device_puts of DISTINCT buffers back-to-back and blocks once on
the batch (no per-call thread hops or syncs).

Timing protocol (measured tunnel pathology): the FIRST device->host
transfer of the process — however small — permanently degrades BOTH
directions of the tunneled transport ~30-100x (H2D 1.1 GB/s -> 0.01-0.04
afterwards; no recovery with idle time). Every GB/s window below therefore
contains host->device transfers and on-device compute only, synchronized
with ``block_until_ready`` (completion wait, no readback): numerator and
denominator are measured under the SAME H2D-only protocol, so the ratio is
honest. The verification verdicts (0-d device CRCs) are fetched ONCE, after
every timed window, in a single batched transfer and asserted; its cost is
reported separately as ``confirm_s``, and ``raw_infeed_after_GBps`` shows
the post-D2H state of the transport for transparency.
"""

from __future__ import annotations

import asyncio
import json
import time

import numpy as np

FILES = 128
BLOCK_MB = 1
CS_CACHE_BLOCKS = 8  # << FILES so the read phase cannot ride the LRU cache
# Measured on the single-core bench host: 4-6 concurrent read streams beat
# 12 on the per-block gRPC path (beyond ~6, thread/GIL scheduling churn on
# one core outweighs overlap). The FUSED local path inverts this: per-block
# Python work is tiny (requests just stage into combiner rounds), so more
# in-flight files = denser rounds — 32 measured best. Writes keep the
# reference harness's concurrency 10 (dfs_cli.rs:579-631) so
# write_pipeline_GBps stays comparable across rounds.
READ_CONCURRENCY = 6
FUSED_READ_CONCURRENCY = 32
#: Fused round cap (blocks). Kept at 16 so the batched-CRC bucket set is
#: {1,2,4,8,16} — five warm-up compiles, bounded on real TPU.
BATCH_READS = 16
WRITE_CONCURRENCY = 10
ICI_STEP_MB = 8
ICI_REPS = 16


def _bench_raw_infeed(device, nbytes_each: int, reps: int) -> float:
    """Raw host->HBM bandwidth, taken as the BEST of two honest harnesses so
    the denominator is strictly favorable: (a) one dispatcher issuing all
    device_puts back-to-back with a single final sync (pipelined), and
    (b) READ_CONCURRENCY persistent threads each pipelining its share (what
    the measured path's 8-way fan-out gets to use). Distinct buffers per
    transfer — no residency reuse."""
    import concurrent.futures

    import jax

    bufs = [
        np.random.default_rng(i).integers(
            0, 256, nbytes_each, dtype=np.uint8
        ).reshape(-1, 512).view("<u4")
        for i in range(reps)
    ]
    # Warm-up transfer.
    jax.block_until_ready(jax.device_put(bufs[0], device))
    t0 = time.perf_counter()
    arrs = [jax.device_put(b, device) for b in bufs]
    jax.block_until_ready(arrs)
    serial = nbytes_each * reps / (time.perf_counter() - t0) / 1e9

    def put_shard(shard):
        return [jax.device_put(b, device) for b in shard]

    shards = [bufs[i::READ_CONCURRENCY] for i in range(READ_CONCURRENCY)]
    with concurrent.futures.ThreadPoolExecutor(READ_CONCURRENCY) as pool:
        t0 = time.perf_counter()
        out = list(pool.map(put_shard, shards))
        jax.block_until_ready(out)
        threaded = nbytes_each * reps / (time.perf_counter() - t0) / 1e9
    return max(serial, threaded)


def _bench_ici_write_step(device) -> tuple:
    """On-chip 3x replication round: ppermute chain + Pallas CRC verify +
    ack psum, timed over ICI_REPS rounds of ICI_STEP_MB each."""
    import jax
    import jax.numpy as jnp

    from tpudfs.common.checksum import crc32c_chunks
    from tpudfs.tpu.crc32c_pallas import bytes_to_words
    from tpudfs.tpu.ici_replication import make_mesh, replicated_write_step

    mesh = make_mesh([device])
    step = replicated_write_step(mesh, replication=3)
    nbytes = ICI_STEP_MB << 20
    data = np.random.default_rng(7).integers(
        0, 256, nbytes, dtype=np.uint8
    ).tobytes()
    words = jax.device_put(bytes_to_words(data), device)
    crcs = jax.device_put(crc32c_chunks(data).astype(np.uint32), device)
    jax.block_until_ready(step(words, crcs))  # compile + warm up
    t0 = time.perf_counter()
    outs = [step(words, crcs) for _ in range(ICI_REPS)]
    jax.block_until_ready(outs)
    dt = time.perf_counter() - t0
    # Verdicts stay on device; the caller fetches them once after every
    # timed window (per-round fetches would cost 0.1-1 s each on a
    # degraded tunnel, and any D2H here would poison later H2D uploads).
    oks = jnp.stack([o["ok"].reshape(-1)[0] for o in outs])
    return nbytes * ICI_REPS / dt / 1e9, oks


def _spawn_cluster(root: str, cache_blocks: int = CS_CACHE_BLOCKS):
    """1 master + 3 chunkservers as separate OS processes (real sockets,
    real GIL isolation — the client must not time-share with the servers).
    On failure every already-started process is torn down before raising."""
    import atexit
    import pathlib

    from tpudfs.testing.procs import free_port, spawn, terminate_all, wait_ready

    logdir = pathlib.Path(root) / "logs"
    logdir.mkdir(parents=True)
    procs = []
    atexit.register(terminate_all, procs)  # belt-and-braces orphan guard
    env = {"JAX_PLATFORMS": "cpu"}  # servers never touch the TPU
    try:
        maddr = f"127.0.0.1:{free_port()}"
        spawn(procs, "master", logdir, "tpudfs.master",
              "--port", maddr.rsplit(":", 1)[1],
              "--data-dir", f"{root}/m0", "--http-port", "0", env=env)
        wait_ready(logdir, "master")
        cs_addrs = []
        for i in range(3):
            port = free_port()
            # --scrub-interval 3600: this host has ONE core; the default
            # 60 s scrubber would re-CRC the whole 384 MiB dataset mid-sweep
            # and steal the core from the measured path.
            spawn(procs, f"cs{i}", logdir, "tpudfs.chunkserver",
                  "--port", str(port),
                  "--data-dir", f"{root}/cs{i}", "--masters", maddr,
                  "--rack-id", f"rack-{i}", "--heartbeat-interval", "0.5",
                  "--scrub-interval", "3600",
                  "--http-port", "0",
                  env={**env, "BLOCK_CACHE_SIZE": str(cache_blocks)})
            wait_ready(logdir, f"cs{i}")
            cs_addrs.append(f"127.0.0.1:{port}")
    except BaseException:
        terminate_all(procs)
        raise
    return maddr, cs_addrs, procs


def _bench_ec_scatter_step(device) -> tuple:
    """On-chip RS(6,3) encode + shard scatter + CRC-verify round
    (replication-degenerate ring on 1 device; multi-device layout is
    validated by dryrun_multichip)."""
    import jax
    import jax.numpy as jnp

    from tpudfs.tpu.crc32c_pallas import bytes_to_words
    from tpudfs.tpu.ici_replication import EcShardScatter, make_mesh

    mesh = make_mesh([device])
    scatter = EcShardScatter(mesh, 6, 3)
    nbytes = ICI_STEP_MB << 20
    data = np.random.default_rng(9).integers(
        0, 256, nbytes, dtype=np.uint8
    ).tobytes()
    words = jax.device_put(bytes_to_words(data), device)
    jax.block_until_ready(scatter.scatter(words))  # compile + warm up
    t0 = time.perf_counter()
    outs = [scatter.scatter(words) for _ in range(ICI_REPS)]
    jax.block_until_ready(outs)
    dt = time.perf_counter() - t0
    acks = jnp.stack([a for _, _, a in outs])  # fetched by the caller
    return nbytes * ICI_REPS / dt / 1e9, acks


async def _run() -> dict:
    import tempfile

    tmp = tempfile.TemporaryDirectory(prefix="tpudfs-bench-")
    root = tmp.name
    maddr, cs_addrs, procs = _spawn_cluster(root)
    try:
        return await _run_against(maddr, cs_addrs)
    finally:
        from tpudfs.testing.procs import terminate_all

        terminate_all(procs)
        tmp.cleanup()


async def _run_against(maddr: str, cs_addrs: list[str]) -> dict:
    import jax

    from tpudfs.client.client import Client
    from tpudfs.common.rpc import RpcClient
    from tpudfs.tpu.hbm_reader import HbmReader

    rpc = RpcClient()
    client = Client([maddr], rpc_client=rpc, block_size=BLOCK_MB << 20)

    # Wait until the master has left safe mode and all 3 chunkservers are
    # registered (first placement needs a full replication set).
    deadline = asyncio.get_event_loop().time() + 60
    while True:
        try:
            await client.create_file("/bench/probe", b"x")
            await client.delete_file("/bench/probe")
            break
        except Exception:
            if asyncio.get_event_loop().time() > deadline:
                raise
            await asyncio.sleep(0.3)
    data = np.random.default_rng(0).integers(
        0, 256, BLOCK_MB << 20, dtype=np.uint8
    ).tobytes()
    wsem = asyncio.Semaphore(WRITE_CONCURRENCY)

    async def put(i):
        async with wsem:
            await client.create_file(f"/bench/f{i:04d}", data)

    # ---- metadata plane: creates/s at the reference harness config
    # (100 files, concurrency 10, dfs_cli.rs:131-146) — empty files, so
    # the number isolates the create -> allocate -> complete proposal
    # path (WAL group commit + fused first-block allocation).
    async def put_empty(i):
        async with wsem:
            await client.create_file(f"/bench/meta/m{i:03d}", b"")

    t0 = time.perf_counter()
    await asyncio.gather(*(put_empty(i) for i in range(100)))
    meta_creates_per_s = 100 / (time.perf_counter() - t0)

    # ---- write side: 3x pipeline-replicated DFS writes (logical GB/s).
    t0 = time.perf_counter()
    await asyncio.gather(*(put(i) for i in range(FILES)))
    write_wall = time.perf_counter() - t0
    write_gbps = FILES * len(data) / write_wall / 1e9

    device = jax.devices()[0]
    reader = HbmReader(client, [device], batch_reads=BATCH_READS)

    # See the module docstring's "Timing protocol": NO device->host
    # transfer happens before or inside any timed window below — the first
    # D2H of the process permanently degrades the tunneled transport in
    # both directions, so every window synchronizes with block_until_ready
    # (completion wait, no readback) and all verdicts are fetched once at
    # the very end.
    raw_before = _bench_raw_infeed(device, len(data), 16)

    # Warm up kernels + compile caches without any D2H (not the CS block
    # cache: it holds CS_CACHE_BLOCKS blocks; the sweeps touch FILES).
    # warm_batches pre-compiles every fused-round CRC bucket (device-verify
    # platforms only; the host-verify CPU fallback dispatches none).
    reader.warm_batches((BLOCK_MB << 20) // 512)
    # Warm the PER-BLOCK path (block_crc_device compile + gRPC read) with
    # short-circuit off — the fused path no longer exercises it, and
    # without this the gRPC sweep pays the XLA compile in its window.
    client.local_reads = False
    warm = await reader.read_file_to_device_blocks("/bench/f0000", verify="lazy")
    client.local_reads = True
    grpc_files = min(48, FILES)

    async def timed_sweep(items, read_fn, concurrency=READ_CONCURRENCY):
        """Shared sweep harness: sem-gated concurrent per-item reads, one
        block_until_ready over every block's sync set — per-block arrays
        and 0-d CRCs on the unfused path, whole-round batch arrays and CRC
        vectors on the fused one (transfer + on-device fold complete — no
        readback; see Timing protocol)."""
        sem = asyncio.Semaphore(concurrency)
        blocks: list = []

        async def one(item):
            async with sem:
                bs = await read_fn(item)
                blocks.extend(bs)
                return sum(b.size for b in bs)

        t0 = time.perf_counter()
        sizes = await asyncio.gather(*(one(it) for it in items))
        jax.block_until_ready([x for b in blocks for x in b.sync_arrays])
        return blocks, sum(sizes) / (time.perf_counter() - t0) / 1e9

    # ---- remote read path: short-circuit disabled — what a non-colocated
    # client gets over gRPC. Verification is dispatched in-window (the CRC
    # folds are part of the measured work), resolved by the final confirm.
    client.local_reads = False
    grpc_blocks, grpc_gbps = await timed_sweep(
        range(grpc_files),
        lambda i: reader.read_file_to_device_blocks(
            f"/bench/f{i:04d}", verify="lazy"),
    )
    client.local_reads = True
    # Pre-compile the confirm stack for the final batched verdict fetch
    # (built and executed, NOT fetched): only unfused blocks carry per-block
    # 0-d CRCs now — fused rounds resolve through their batch vectors.
    sample = next((b for b in grpc_blocks if b.pending_crc is not None), None)
    if sample is not None:
        reader.warm_confirm(sample, len(grpc_blocks) + len(warm))

    # ---- primary read path: short-circuit (client colocated with the
    # chunkservers — the north-star topology): verified pread off the
    # replica's disk, no gRPC byte shuffle.
    local_before = client.local_read_blocks
    comb_before = sum(c.blocks for c in reader._combiners.values())
    all_blocks, achieved = await timed_sweep(
        range(FILES),
        lambda i: reader.read_file_to_device_blocks(
            f"/bench/f{i:04d}", verify="lazy"),
        concurrency=FUSED_READ_CONCURRENCY,
    )
    # Fused rounds bypass client._read_local, so count combiner-served
    # blocks alongside the classic short-circuit counter.
    local_blocks = (client.local_read_blocks - local_before
                    + sum(c.blocks for c in reader._combiners.values())
                    - comb_before)

    # ---- warm infeed sweep: the steady-state training-infeed pattern. The
    # immutable block layout is cached ONCE outside the window (exactly how
    # the grain infeed reads, via read_meta_range) and colocated replicas
    # go through the one-thread-hop fast path; on-device CRC still runs.
    metas = await asyncio.gather(
        *(client.get_file_info(f"/bench/f{i:04d}") for i in range(FILES))
    )
    warm_blocks, warm_gbps = await timed_sweep(
        metas, lambda m: reader.read_meta_blocks_fast(m, device),
        concurrency=FUSED_READ_CONCURRENCY,
    )

    # ---- on-chip benches: pure device compute (H2D warm-up only), still
    # ahead of the first D2H so their inputs upload at full speed.
    ici_write, ici_oks = _bench_ici_write_step(device)
    ec_scatter, ec_acks = _bench_ec_scatter_step(device)

    # ---- end of timed windows: ONE batched verdict fetch resolves every
    # lazy verification (the process's first D2H), then assert.
    t0 = time.perf_counter()
    await reader.confirm(all_blocks + grpc_blocks + warm_blocks + warm)
    confirm_s = time.perf_counter() - t0
    assert all(b.verified for b in all_blocks)
    assert all(b.verified for b in grpc_blocks)
    assert all(b.verified for b in warm_blocks)
    assert np.asarray(ici_oks).all(), "ICI write step verification failed"
    assert (np.asarray(ec_acks) == 1).all(), "EC scatter verification failed"

    cache_hits = cache_misses = 0
    for addr in cs_addrs:
        stats = await rpc.call(addr, "ChunkServerService", "Stats", {})
        cache_hits += stats["cache_hits"]
        cache_misses += stats["cache_misses"]

    raw_after = _bench_raw_infeed(device, len(data), 16)
    raw = raw_before  # the honest (unpoisoned) denominator

    await rpc.close()

    target = 0.9 * raw
    return {
        "metric": (
            "1MiB-chunk read GB/s/host into TPU HBM (3x-replicated DFS, "
            "on-device CRC32C verify) + 3x-replication write GB/s over ICI"
        ),
        "value": round(achieved, 3),
        "unit": "GB/s",
        "vs_baseline": round(achieved / target, 3) if target else 0.0,
        "grpc_read_GBps": round(grpc_gbps, 3),
        "warm_infeed_read_GBps": round(warm_gbps, 3),
        "local_read_blocks": local_blocks,
        "confirm_s": round(confirm_s, 3),
        "write_pipeline_GBps": round(write_gbps, 3),
        "meta_creates_per_s": round(meta_creates_per_s, 1),
        "ici_write_GBps": round(ici_write, 3),
        "ici_ec_scatter_GBps": round(ec_scatter, 3),
        "raw_infeed_GBps": round(raw, 3),
        "raw_infeed_before_GBps": round(raw_before, 3),
        "raw_infeed_after_GBps": round(raw_after, 3),
        "files": FILES,
        "cs_cache_hit_rate": round(
            cache_hits / max(1, cache_hits + cache_misses), 3
        ),
        "platform": jax.devices()[0].platform,
    }


def _probe_tpu(timeout_s: float = 90.0, attempts: int = 2,
               retry_wait_s: float = 45.0) -> bool:
    """The tunneled TPU sometimes wedges so hard that jax.devices() never
    returns — probe it in a DISPOSABLE subprocess so the bench itself can't
    hang, and fall back to CPU (honestly labeled) when the device is gone:
    a degraded JSON line beats a driver timeout with no data. Wedges are
    sometimes transient, so one short retry is worth the wait before
    conceding the whole run to the CPU."""
    import os
    import subprocess
    import sys
    import time as _time

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return False
    for attempt in range(attempts):
        if attempt:
            _time.sleep(retry_wait_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, numpy as np\n"
                 "d = jax.devices()[0]\n"
                 "jax.block_until_ready(jax.device_put(np.zeros(1024), d))\n"
                 "print(d.platform)"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode == 0 and "cpu" not in proc.stdout:
                return True
        except subprocess.TimeoutExpired:
            pass
    return False


def main() -> None:
    import os

    requested_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
    fell_back = False
    if not requested_cpu and not _probe_tpu():
        fell_back = True
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["JAX_PLATFORM_NAME"] = "cpu"
    if requested_cpu or fell_back:
        # The env var alone is NOT enough: the preloaded axon TPU plugin
        # still wins the backend race (and hangs when the tunnel is
        # wedged) unless the platform is forced before first backend use.
        import jax

        jax.config.update("jax_platforms", "cpu")
    result = asyncio.run(_run())
    if fell_back:
        result["platform"] = "cpu-fallback(tpu unreachable)"
    print(json.dumps(result))


if __name__ == "__main__":
    main()
