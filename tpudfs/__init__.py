"""tpudfs — a TPU-native distributed file system framework.

A ground-up re-architecture of a GFS/HDFS-style DFS (reference:
getumen/rust-hadoop-generated-by-llm) for TPU pods:

- Control/metadata plane: Raft-replicated range-sharded masters (asyncio + gRPC
  over DCN), cross-shard 2PC transactions, dynamic split/merge.
- Data plane: ChunkServers colocated on TPU-host VMs; pipeline replication that
  can ride XLA collectives over ICI (``tpudfs.tpu.ici_replication``); CRC32C and
  Reed-Solomon hot paths as native C++ (``native/``) with bit-exact Pallas
  device twins (``tpudfs.tpu``).
- Client: shard-map caching, leader-hint retry, hedged reads, EC, plus a JAX
  reader that lands chunks directly in TPU HBM as sharded ``jax.Array``s.
- S3-compatible gateway with SigV4/OIDC/STS/IAM/SSE/audit.

See SURVEY.md for the reference structural analysis this build follows.
"""

__version__ = "0.1.0"
