"""Durable Raft state on the local filesystem.

The reference persists term/vote/log under RocksDB keys with batched writes
(simple_raft.rs:683,908-952) and snapshots as serialized state
(simple_raft.rs:1033-1097). RocksDB isn't available in this image, so this
module uses the equivalent primitives directly:

- ``hard_state`` file — atomic replace, fsync'd (term + voted_for);
- ``wal.bin`` — append-only length-prefixed msgpack records (append / truncate
  markers), one fsync per batch (the save_log_entries_batch analogue);
- ``snapshot.bin`` — atomic replace; saving a snapshot rewrites the WAL with
  only the entries past the snapshot (compaction, simple_raft.rs:1210-1213).
"""

from __future__ import annotations

import os
import struct
import threading
from pathlib import Path

import msgpack

from tpudfs.raft.core import LogEntry, Snapshot

_LEN = struct.Struct("<I")


def _write_all(fd: int, data: bytes) -> None:
    """os.write may be partial (signals, ENOSPC-adjacent paths); loop."""
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        view = view[n:]


def _atomic_write(path: Path, data: bytes) -> None:
    tmp = path.with_name(path.name + ".tmp")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        _write_all(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)


class RaftStorage:
    def __init__(self, data_dir: str | Path):
        self.dir = Path(data_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._hard = self.dir / "hard_state"
        self._wal = self.dir / "wal.bin"
        self._snap = self.dir / "snapshot.bin"
        self._wal_fd: int | None = None
        # WAL writes run on to_thread workers while close() can come from
        # the node's stop path — a threading.Lock serializes the fd's
        # open/append/compact/close lifecycle across those threads.
        self._io_lock = threading.Lock()

    # ------------------------------------------------------------------ load

    def load(self) -> tuple[int, str | None, list[LogEntry], Snapshot | None]:
        term, voted_for = 0, None
        if self._hard.exists():
            d = msgpack.unpackb(self._hard.read_bytes(), raw=False)
            term, voted_for = int(d["term"]), d["voted_for"]
        snapshot = None
        if self._snap.exists():
            snapshot = Snapshot.from_dict(
                msgpack.unpackb(self._snap.read_bytes(), raw=False)
            )
        log: list[LogEntry] = []
        if self._wal.exists():
            log = self._replay_wal()
        if snapshot is not None:
            log = [e for e in log if e.index > snapshot.last_index]
        return term, voted_for, log, snapshot

    def _replay_wal(self) -> list[LogEntry]:
        log: list[LogEntry] = []
        raw = self._wal.read_bytes()
        pos = 0
        while pos + _LEN.size <= len(raw):
            (n,) = _LEN.unpack_from(raw, pos)
            pos += _LEN.size
            if pos + n > len(raw):
                break  # torn tail record from a crash — ignore
            rec = msgpack.unpackb(raw[pos : pos + n], raw=False)
            pos += n
            if rec["t"] == "a":
                entries = [LogEntry.from_dict(e) for e in rec["e"]]
                if entries:
                    log = [x for x in log if x.index < entries[0].index]
                    log.extend(entries)
            elif rec["t"] == "t":
                log = [x for x in log if x.index < rec["i"]]
        return log

    # ----------------------------------------------------------------- write

    def save_hard_state(self, term: int, voted_for: str | None) -> None:
        _atomic_write(
            self._hard, msgpack.packb({"term": term, "voted_for": voted_for})
        )

    def _wal_handle(self) -> int:
        """Callers hold ``_io_lock``."""
        if self._wal_fd is None:
            self._wal_fd = os.open(
                self._wal, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
            )
        return self._wal_fd

    def _wal_append(self, rec: dict) -> None:
        payload = msgpack.packb(rec)
        with self._io_lock:
            fd = self._wal_handle()
            _write_all(fd, _LEN.pack(len(payload)) + payload)
            os.fsync(fd)

    def append_entries(self, entries: list[LogEntry]) -> None:
        if entries:
            self._wal_append({"t": "a", "e": [e.to_dict() for e in entries]})

    def truncate_from(self, index: int) -> None:
        self._wal_append({"t": "t", "i": index})

    def save_snapshot(self, snapshot: Snapshot, remaining: list[LogEntry]) -> None:
        """Persist snapshot and compact the WAL down to ``remaining``."""
        _atomic_write(self._snap, msgpack.packb(snapshot.to_dict()))
        with self._io_lock:
            if self._wal_fd is not None:
                os.close(self._wal_fd)
                self._wal_fd = None
            buf = b""
            if remaining:
                payload = msgpack.packb(
                    {"t": "a", "e": [e.to_dict() for e in remaining]})
                buf = _LEN.pack(len(payload)) + payload
            _atomic_write(self._wal, buf)

    def close(self) -> None:
        with self._io_lock:
            if self._wal_fd is not None:
                os.close(self._wal_fd)
                self._wal_fd = None
