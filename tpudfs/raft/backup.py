"""Off-site Raft snapshot backup.

Model: the reference leader uploads every compaction snapshot to S3 under
``master-snapshots/node-{id}/...`` (simple_raft.rs:1214-1271, flags
bin/master.rs:72-79). Two sinks:

- ``DirSnapshotBackup`` — a local/NFS directory (operationally the common
  case for on-prem TPU pods).
- ``S3SnapshotBackup`` — HTTP PUT against any S3-compatible endpoint using
  this project's own SigV4 presigner (tpudfs.auth.presign), so a cluster
  can back its metadata up into its own S3 gateway or any external store.

Uploads are fire-and-forget from the Raft apply loop (a slow or down sink
must never block consensus); restore is a manual operator action via
``fetch_latest`` (the reference's restore path is manual too).
"""

from __future__ import annotations

import logging
import os
import pathlib
import re

import msgpack

from tpudfs.common.fsutil import write_durable

logger = logging.getLogger(__name__)

KEEP_SNAPSHOTS = 5  # pruned oldest-first beyond this


def _node_slug(node_id: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", node_id)


def encode_snapshot(snapshot) -> bytes:
    """Self-describing envelope: meta + state-machine bytes."""
    return msgpack.packb({
        "last_index": snapshot.last_index,
        "last_term": snapshot.last_term,
        "config": snapshot.config.to_dict() if snapshot.config else None,
        "data": snapshot.data,
    })


def decode_snapshot(raw: bytes) -> dict:
    return msgpack.unpackb(raw, raw=False)


class DirSnapshotBackup:
    """Snapshot sink on a filesystem path (atomic tmp+rename publish)."""

    def __init__(self, root: str, keep: int = KEEP_SNAPSHOTS):
        self.root = pathlib.Path(root)
        self.keep = keep

    def _dir(self, node_id: str) -> pathlib.Path:
        return self.root / _node_slug(node_id)

    def upload(self, node_id: str, snapshot) -> None:
        d = self._dir(node_id)
        d.mkdir(parents=True, exist_ok=True)
        name = f"snap-{snapshot.last_index:012d}.bin"
        # fsync-then-rename via the shared helper: a backup that can be
        # torn by power loss (or a short write) is not a backup.
        write_durable(d / name, encode_snapshot(snapshot))
        snaps = sorted(p for p in d.iterdir()
                       if p.name.startswith("snap-")
                       and p.name.endswith(".bin"))
        for old in snaps[: -self.keep]:
            old.unlink(missing_ok=True)

    def fetch_latest(self, node_id: str) -> dict | None:
        """Newest restorable snapshot — falls back past torn/corrupt files
        (disaster recovery must not crash on the one bad file when intact
        older snapshots sit right next to it)."""
        d = self._dir(node_id)
        if not d.is_dir():
            return None
        snaps = sorted(p for p in d.iterdir()
                       if p.name.startswith("snap-")
                       and p.name.endswith(".bin"))
        for p in reversed(snaps):
            try:
                return decode_snapshot(p.read_bytes())
            except Exception:
                logger.warning("skipping unreadable backup snapshot %s", p)
        return None


class S3SnapshotBackup:
    """Snapshot sink on an S3-compatible endpoint via presigned PUT/GET
    (reference backup_snapshot_to_s3 simple_raft.rs:1214-1271; key layout
    ``master-snapshots/node-{id}/snap-{index}``)."""

    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, *, region: str = "us-east-1"):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def _key(self, node_id: str, last_index: int) -> str:
        return (f"master-snapshots/node-{_node_slug(node_id)}/"
                f"snap-{last_index:012d}")

    def _url(self, method: str, key: str) -> str:
        from tpudfs.auth import presign

        return presign.presign_url(
            method,
            self.endpoint,
            f"/{self.bucket}/{key}",
            self.access_key,
            self.secret_key,
            region=self.region,
            expires_seconds=300,
        )

    async def aupload(self, node_id: str, snapshot) -> None:
        import aiohttp

        url = self._url("PUT", self._key(node_id, snapshot.last_index))
        async with aiohttp.ClientSession() as s:
            async with s.put(url, data=encode_snapshot(snapshot)) as resp:
                if resp.status != 200:
                    raise RuntimeError(
                        f"snapshot upload HTTP {resp.status}: "
                        f"{(await resp.text())[:200]}"
                    )

    async def afetch(self, node_id: str, last_index: int) -> dict | None:
        import aiohttp

        url = self._url("GET", self._key(node_id, last_index))
        async with aiohttp.ClientSession() as s:
            async with s.get(url) as resp:
                if resp.status == 404:
                    return None
                if resp.status != 200:
                    raise RuntimeError(f"snapshot fetch HTTP {resp.status}")
                return decode_snapshot(await resp.read())
