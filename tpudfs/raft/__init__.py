"""Raft consensus: sans-io core, file-backed storage, gRPC transport shell."""
