"""Sans-io Raft core.

Feature parity with the reference's dfs/metaserver/src/simple_raft.rs:
- leader election with randomized 1.5-3 s timeouts (simple_raft.rs:758,1288),
- log replication with conflict back-off,
- snapshot compaction beyond a log-length threshold (simple_raft.rs:1210-1213)
  and InstallSnapshot catch-up for lagging followers (simple_raft.rs:1455-1533),
- ReadIndex linearizable reads confirmed by heartbeat quorum acks
  (simple_raft.rs:1863-1887,993-1011),
- joint-consensus membership change with a non-voting catch-up stage
  (10 rounds, simple_raft.rs:72-106,241-243,2458-2512) and joint-majority
  commit advancement (simple_raft.rs:2246-2277),
- leader transfer via TimeoutNow (simple_raft.rs:2740-2813).

Architecturally this is NOT a port: the reference interleaves consensus with
tokio channels, reqwest HTTP and RocksDB in one 3.8k-line loop. Here the core
is a pure deterministic state machine — time comes in via ``tick(now)``,
messages via ``handle_message``, randomness via an injected ``random.Random``
— and all I/O is returned as effect objects for a shell (tpudfs/raft/node.py)
to execute. That makes the whole consensus layer simulable in-process, which
is how the model-level test tiers (tests/test_raft_core.py,
test_raft_partitions.py, test_raft_jepsen.py) drive it.

On a TPU pod this control plane runs host-side over DCN (SURVEY.md §2.6 P4);
consensus never touches the accelerator.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field, replace
from enum import Enum
from typing import Any

# ---------------------------------------------------------------------------
# Data model
# ---------------------------------------------------------------------------


class Role(str, Enum):
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"


@dataclass(frozen=True)
class LogEntry:
    index: int
    term: int
    command: Any  # opaque msgpack-able value; dicts with "_config" are internal

    def to_dict(self) -> dict:
        return {"index": self.index, "term": self.term, "command": self.command}

    @classmethod
    def from_dict(cls, d: dict) -> "LogEntry":
        return cls(int(d["index"]), int(d["term"]), d["command"])


@dataclass(frozen=True)
class Config:
    """Cluster membership. ``voters_old`` is set only during joint consensus:
    decisions then require a majority of BOTH voter sets."""

    voters: frozenset[str]
    voters_old: frozenset[str] | None = None
    learners: frozenset[str] = frozenset()

    @property
    def joint(self) -> bool:
        return self.voters_old is not None

    def all_nodes(self) -> frozenset[str]:
        nodes = self.voters | self.learners
        if self.voters_old:
            nodes = nodes | self.voters_old
        return nodes

    def has_quorum(self, acks: set[str]) -> bool:
        def maj(group: frozenset[str]) -> bool:
            return len(acks & group) * 2 > len(group)

        if not self.voters:
            return False
        ok = maj(self.voters)
        if self.voters_old is not None:
            ok = ok and maj(self.voters_old)
        return ok

    def to_dict(self) -> dict:
        return {
            "voters": sorted(self.voters),
            "voters_old": sorted(self.voters_old) if self.voters_old is not None else None,
            "learners": sorted(self.learners),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        old = d.get("voters_old")
        return cls(
            voters=frozenset(d.get("voters") or []),
            voters_old=frozenset(old) if old is not None else None,
            learners=frozenset(d.get("learners") or []),
        )


@dataclass(frozen=True)
class Snapshot:
    last_index: int
    last_term: int
    config: Config
    data: bytes

    def to_dict(self) -> dict:
        return {
            "last_index": self.last_index,
            "last_term": self.last_term,
            "config": self.config.to_dict(),
            "data": self.data,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Snapshot":
        return cls(
            int(d["last_index"]), int(d["last_term"]),
            Config.from_dict(d["config"]), d["data"],
        )


# ---------------------------------------------------------------------------
# Effects (what the shell must do after each core call)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Send:
    to: str
    msg: dict


@dataclass(frozen=True)
class PersistHardState:
    term: int
    voted_for: str | None


@dataclass(frozen=True)
class AppendLog:
    entries: tuple[LogEntry, ...]


@dataclass(frozen=True)
class TruncateLog:
    """Drop every entry with index >= from_index."""

    from_index: int


@dataclass(frozen=True)
class Apply:
    entries: tuple[LogEntry, ...]


@dataclass(frozen=True)
class SaveSnapshot:
    snapshot: Snapshot


@dataclass(frozen=True)
class RestoreFromSnapshot:
    """State machine must reset itself from snapshot.data."""

    snapshot: Snapshot


@dataclass(frozen=True)
class ReadReady:
    request_id: Any
    read_index: int


@dataclass(frozen=True)
class SteppedDown:
    """Leadership lost — shell fails pending proposals with Not Leader."""

    term: int


@dataclass(frozen=True)
class BecameLeader:
    term: int


@dataclass(frozen=True)
class SnapshotNeeded:
    """Log exceeded the compaction threshold; shell should serialize the state
    machine and call ``compact(snapshot_data)``."""


class NotLeaderError(Exception):
    def __init__(self, leader_hint: str | None):
        super().__init__(f"Not Leader|{leader_hint or ''}")
        self.leader_hint = leader_hint


# ---------------------------------------------------------------------------
# Timings
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Timings:
    """Reference values: 1.5-3 s election (simple_raft.rs:758), 100 ms tick
    loop (simple_raft.rs:1190), snapshot at >100 entries
    (simple_raft.rs:1211), 10 catch-up rounds (simple_raft.rs:241-243)."""

    election_min: float = 1.5
    election_max: float = 3.0
    heartbeat: float = 0.5
    snapshot_threshold: int = 100
    catchup_rounds: int = 10
    #: Pre-vote (Raft §9.6, the etcd extension; the reference lacks it): a
    #: timed-out follower first polls a non-binding quorum before
    #: incrementing its term, so a partitioned node cannot inflate terms
    #: and depose a healthy leader when its partition heals.
    prevote: bool = True
    #: Leader-lease reads (Raft §6.4.1 / etcd lease read; the reference has
    #: only quorum ReadIndex): a heartbeat-quorum ack for a round sent at
    #: time t proves no new leader can be elected before
    #: t + election_min (followers refuse votes within election_min of
    #: leader contact — see vote stickiness in _on_request_vote), so reads
    #: until t + election_min*(1 - clock_drift_bound) skip the quorum
    #: round-trip entirely. Only honored when ``prevote`` is also on.
    lease_reads: bool = True
    #: Upper bound assumed on relative clock RATE drift between nodes over
    #: one election timeout (monotonic clocks; absolute offsets cancel out).
    clock_drift_bound: float = 0.1


# ---------------------------------------------------------------------------
# Core
# ---------------------------------------------------------------------------


class RaftCore:
    def __init__(
        self,
        node_id: str,
        config: Config,
        *,
        term: int = 0,
        voted_for: str | None = None,
        log: list[LogEntry] | None = None,
        snapshot: Snapshot | None = None,
        timings: Timings | None = None,
        rng: random.Random | None = None,
        now: float = 0.0,
    ):
        self.node_id = node_id
        self.timings = timings or Timings()
        self.rng = rng or random.Random()

        # Persistent state (the shell re-creates the core from storage).
        self.term = term
        self.voted_for = voted_for
        self.snapshot = snapshot
        self.log: list[LogEntry] = list(log or [])

        # Config: latest config entry in the log wins; else snapshot's; else boot.
        self._boot_config = config
        self.config = config
        if snapshot is not None:
            self.config = snapshot.config
        for e in self.log:
            cfg = self._config_of(e)
            if cfg is not None:
                self.config = cfg

        # Volatile state.
        self.role = Role.FOLLOWER
        self.leader_id: str | None = None
        self.commit_index = snapshot.last_index if snapshot else 0
        self.last_applied = self.commit_index
        self.votes: set[str] = set()
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        # ReadIndex machinery: monotonically increasing heartbeat probe seq,
        # per-peer highest acked seq, pending reads.
        self._probe_seq = 0
        self._peer_ack_seq: dict[str, int] = {}
        self._pending_reads: list[dict] = []  # {id, index, seq, lease?}
        # Leader-lease machinery: send time per probe round, the lease
        # expiry, the last instant a quorum was provably reachable (for
        # check-quorum step-down), and whether a TimeoutNow was fired this
        # leadership (transfer elections bypass vote stickiness, so the
        # lease argument is void once one is in flight).
        self._probe_sent_at: dict[int, float] = {}
        self._lease_until = float("-inf")
        self._quorum_contact = now
        self._transfer_fired = False
        # Membership-change machinery.
        self._catchup: dict | None = None  # {node, rounds_left, last_match}
        self._transfer_target: str | None = None
        self._transfer_deadline = 0.0
        # Pre-vote machinery: target term of the open round (None = no
        # round) and its grants. Nothing here persists — pre-votes are
        # non-binding and never touch term/voted_for.
        self._prevote_term: int | None = None
        self._prevotes: set[str] = set()
        # Initialized to NOW, not -inf: a restarted node must conservatively
        # assume it heard from a leader just before the crash, else its
        # reset stickiness window lets a new leader be elected inside an
        # old leader's still-valid lease (stale read). Costs at most one
        # election_min of vote refusal after boot — elections start no
        # earlier than that anyway (_election_deadline below).
        self._last_leader_contact = now

        self._election_deadline = now + self._election_timeout()
        self._heartbeat_due = now

    # ------------------------------------------------------------ log helpers

    @property
    def log_start(self) -> int:
        """Index of the first entry held in memory (1 if no snapshot)."""
        return (self.snapshot.last_index + 1) if self.snapshot else 1

    @property
    def last_index(self) -> int:
        if self.log:
            return self.log[-1].index
        return self.snapshot.last_index if self.snapshot else 0

    @property
    def last_term(self) -> int:
        if self.log:
            return self.log[-1].term
        return self.snapshot.last_term if self.snapshot else 0

    def entry(self, index: int) -> LogEntry | None:
        pos = index - self.log_start
        if 0 <= pos < len(self.log):
            return self.log[pos]
        return None

    def term_at(self, index: int) -> int | None:
        if index == 0:
            return 0
        if self.snapshot and index == self.snapshot.last_index:
            return self.snapshot.last_term
        e = self.entry(index)
        return e.term if e else None

    def entries_from(self, index: int, limit: int = 512) -> list[LogEntry]:
        pos = max(index - self.log_start, 0)
        return self.log[pos : pos + limit]

    @staticmethod
    def _config_of(entry: LogEntry) -> Config | None:
        cmd = entry.command
        if isinstance(cmd, dict) and "_config" in cmd:
            return Config.from_dict(cmd["_config"])
        return None

    def _recompute_config(self) -> None:
        """Re-derive membership from snapshot + surviving log entries (needed
        after truncation drops an uncommitted config entry)."""
        cfg = self.snapshot.config if self.snapshot else self._boot_config
        for e in self.log:
            c = self._config_of(e)
            if c is not None:
                cfg = c
        self.config = cfg

    def _election_timeout(self) -> float:
        return self.rng.uniform(self.timings.election_min, self.timings.election_max)

    @property
    def is_voter(self) -> bool:
        cfg = self.config
        return self.node_id in cfg.voters or (
            cfg.voters_old is not None and self.node_id in cfg.voters_old
        )

    # ------------------------------------------------------------------- tick

    def tick(self, now: float) -> list:
        effects: list = []
        if self.role == Role.LEADER:
            if self._transfer_target and now >= self._transfer_deadline:
                self._transfer_target = None  # transfer timed out; resume
            if self.config.has_quorum({self.node_id}):
                # Single-voter config: the leader alone is the quorum.
                self._quorum_contact = now
                self._lease_until = max(
                    self._lease_until, now + self._lease_duration()
                )
            elif now - self._quorum_contact > 2 * self.timings.election_max:
                # Check-quorum (etcd): a leader that cannot reach a quorum
                # steps down instead of heartbeat-pinning followers forever
                # — with vote stickiness, a send-only-partitioned leader
                # would otherwise block elections indefinitely.
                return effects + self._step_down(self.term, now)
            if now >= self._heartbeat_due:
                self._heartbeat_due = now + self.timings.heartbeat
                self._new_probe_round(now)
                effects += self._broadcast_append()
            if len(self.log) > self.timings.snapshot_threshold and \
                    self.last_applied >= self.log_start:
                effects.append(SnapshotNeeded())
        elif self.is_voter and now >= self._election_deadline:
            if self.timings.prevote:
                # Timed-out CANDIDATES step back through pre-vote too
                # (etcd's pre-candidate): a candidate partitioned
                # mid-election would otherwise bump its term every timeout
                # — the exact disruption pre-vote exists to prevent.
                if self.role == Role.CANDIDATE:
                    self.role = Role.FOLLOWER
                    self.votes = set()
                effects += self._start_prevote(now)
            else:
                effects += self._start_election(now)
        return effects

    # -------------------------------------------------------------- elections

    def _start_prevote(self, now: float) -> list:
        """Open a pre-vote round for term+1 — no state is changed beyond the
        round bookkeeping; a quorum of non-binding grants gates the real
        election (so an isolated node never inflates its term)."""
        self._prevote_term = self.term + 1
        self._prevotes = {self.node_id}
        self._election_deadline = now + self._election_timeout()
        effects: list = []
        voters = self.config.voters | (self.config.voters_old or frozenset())
        for peer in voters - {self.node_id}:
            effects.append(
                Send(peer, {
                    "type": "pre_vote",
                    "term": self._prevote_term,
                    "candidate_id": self.node_id,
                    "last_log_index": self.last_index,
                    "last_log_term": self.last_term,
                })
            )
        if self.config.has_quorum(self._prevotes):  # single-node cluster
            self._prevote_term = None
            effects += self._start_election(now)
        return effects

    def _start_election(self, now: float, transfer: bool = False) -> list:
        self.role = Role.CANDIDATE
        self._prevote_term = None
        self._prevotes = set()
        self.term += 1
        self.voted_for = self.node_id
        self.leader_id = None
        self.votes = {self.node_id}
        self._election_deadline = now + self._election_timeout()
        effects: list = [PersistHardState(self.term, self.voted_for)]
        voters = self.config.voters | (self.config.voters_old or frozenset())
        for peer in voters - {self.node_id}:
            effects.append(
                Send(peer, {
                    "type": "request_vote",
                    "term": self.term,
                    "candidate_id": self.node_id,
                    "last_log_index": self.last_index,
                    "last_log_term": self.last_term,
                    # Transfer elections bypass vote stickiness: the old
                    # leader asked for this election itself.
                    "transfer": transfer,
                })
            )
        if self.config.has_quorum(self.votes):  # single-node cluster
            effects += self._become_leader(now)
        return effects

    def _become_leader(self, now: float) -> list:
        self.role = Role.LEADER
        self.leader_id = self.node_id
        self.votes = set()
        self._transfer_target = None
        self.next_index = {p: self.last_index + 1 for p in self.config.all_nodes()}
        self.match_index = {p: 0 for p in self.config.all_nodes()}
        self._peer_ack_seq = {p: 0 for p in self.config.all_nodes()}
        self._pending_reads = []
        self._lease_until = float("-inf")  # no lease until own-term quorum
        self._quorum_contact = now
        self._transfer_fired = False
        self._heartbeat_due = now + self.timings.heartbeat
        effects: list = [BecameLeader(self.term)]
        # Commit-barrier no-op so this term can commit prior-term entries
        # and ReadIndex is immediately safe once it commits.
        effects += self._append_local({"_noop": True})
        self._new_probe_round(now)
        effects += self._broadcast_append()
        return effects

    def _step_down(self, term: int, now: float) -> list:
        effects: list = []
        was_leader = self.role == Role.LEADER
        if term > self.term:
            self.term = term
            self.voted_for = None
            effects.append(PersistHardState(self.term, self.voted_for))
        self.role = Role.FOLLOWER
        self.votes = set()
        self._prevote_term = None
        self._prevotes = set()
        self._pending_reads = []
        self._catchup = None
        self._transfer_target = None
        self._transfer_fired = False
        self._lease_until = float("-inf")
        self._election_deadline = now + self._election_timeout()
        if was_leader:
            effects.append(SteppedDown(self.term))
        return effects

    # ------------------------------------------------------------ proposals

    def propose(self, command: Any, now: float) -> tuple[int, list]:
        """Append a command; returns (log index, effects). Raises NotLeaderError
        with the last-known leader hint when not leader (the client-visible
        ``Not Leader|<hint>`` convention, reference mod.rs:1442-1467)."""
        indices, effects = self.propose_batch([command], now)
        return indices[0], effects

    def propose_batch(self, commands: list, now: float) -> tuple[list[int], list]:
        """Append a batch of commands as one log-append + one replication
        round (the reference drains up to 256 queued events per loop and
        batch-appends them, simple_raft.rs:1174-1185,1689-1778). Returns
        (log indices, effects) — a single AppendLog effect covers the whole
        batch, so the WAL takes one fsync for N proposals."""
        if self.role != Role.LEADER or self._transfer_target:
            raise NotLeaderError(self._transfer_target or self.leader_id)
        effects = self._append_local_batch(commands)
        effects += self._broadcast_append()
        self._heartbeat_due = now + self.timings.heartbeat
        first = self.last_index - len(commands) + 1
        return list(range(first, self.last_index + 1)), effects

    def _append_local(self, command: Any) -> list:
        return self._append_local_batch([command])

    def _append_local_batch(self, commands: list) -> list:
        entries = []
        for command in commands:
            entry = LogEntry(self.last_index + 1, self.term, command)
            self.log.append(entry)
            cfg = self._config_of(entry)
            if cfg is not None:
                self.config = cfg
                # Quorum membership changed: a lease earned under the old
                # config must not survive into the new one (joint consensus
                # makes this redundant in theory; keep it belt-and-braces).
                self._lease_until = float("-inf")
            entries.append(entry)
        effects: list = [AppendLog(tuple(entries))]
        # Single-node: may commit immediately.
        effects += self._advance_commit()
        return effects

    # ------------------------------------------------------------- ReadIndex

    def _new_probe_round(self, now: float) -> None:
        """Open a heartbeat round: bump the probe seq and record its send
        time. An ack for seq >= s proves the follower received a message
        sent no earlier than ``_probe_sent_at[s]`` — the foundation both of
        the leader lease and of check-quorum."""
        self._probe_seq += 1
        self._probe_sent_at[self._probe_seq] = now

    def _lease_duration(self) -> float:
        return self.timings.election_min * \
            (1.0 - self.timings.clock_drift_bound)

    def _update_lease(self) -> None:
        """Extend the lease from the newest probe round a quorum has acked:
        every acked follower reset its election timer no earlier than that
        round's send time, and (vote stickiness) refuses non-transfer votes
        for election_min after — so no new leader can exist before
        sent + election_min, drift margin deducted."""
        if self.role != Role.LEADER:
            return
        for s in sorted(set(self._peer_ack_seq.values()), reverse=True):
            if s <= 0:
                continue
            supporters = {self.node_id} | {
                p for p, q in self._peer_ack_seq.items() if q >= s
            }
            if not self.config.has_quorum(supporters):
                continue
            sent = self._probe_sent_at.get(s)
            if sent is not None:
                self._quorum_contact = max(self._quorum_contact, sent)
                self._lease_until = max(
                    self._lease_until, sent + self._lease_duration()
                )
                for old in [x for x in self._probe_sent_at if x < s]:
                    del self._probe_sent_at[old]
            return

    def lease_valid(self, now: float) -> bool:
        """True iff a lease read may skip the heartbeat-quorum round-trip."""
        return (
            self.role == Role.LEADER
            and self.timings.lease_reads
            and self.timings.prevote  # stickiness alone doesn't gate
            and self._transfer_target is None
            and not self._transfer_fired
            and now < self._lease_until
        )

    def read_index(self, request_id: Any, now: float) -> list:
        """Linearizable read barrier (reference simple_raft.rs:1863-1887):
        capture commit_index, then confirm leadership with a heartbeat quorum;
        ReadReady fires once confirmed AND last_applied has caught up. When
        the leader lease is valid the quorum round-trip is skipped entirely
        (Raft §6.4.1) — same linearizability, one network round cheaper.

        A fresh leader must first commit an entry of its own term (Raft §8 /
        §6.4): until then its commit_index may lag the true cluster commit
        point, so the read index is left unassigned (None) and filled in by
        ``_check_reads`` once the current-term no-op commits."""
        if self.role != Role.LEADER:
            raise NotLeaderError(self.leader_id)
        own_term_committed = self.term_at(self.commit_index) == self.term
        if own_term_committed and self.lease_valid(now):
            index = self.commit_index
            if self.last_applied >= index:
                return [ReadReady(request_id, index)]
            read = {"id": request_id, "index": index, "seq": 0, "lease": True}
            self._pending_reads.append(read)
            return []
        index = self.commit_index if own_term_committed else None
        self._new_probe_round(now)
        read = {"id": request_id, "index": index, "seq": self._probe_seq}
        self._pending_reads.append(read)
        effects = self._broadcast_append()
        self._heartbeat_due = now + self.timings.heartbeat
        # Single-node quorum satisfies immediately.
        effects += self._check_reads()
        return effects

    def _check_reads(self) -> list:
        if self.role != Role.LEADER or not self._pending_reads:
            return []
        own_term_committed = self.term_at(self.commit_index) == self.term
        effects: list = []
        remaining: list[dict] = []
        for read in self._pending_reads:
            if read.get("lease"):
                # Lease read: index was fixed under a valid lease; it only
                # waits for the state machine to catch up, never for acks.
                if self.last_applied >= read["index"]:
                    effects.append(ReadReady(read["id"], read["index"]))
                else:
                    remaining.append(read)
                continue
            if read["index"] is None:
                if not own_term_committed:
                    remaining.append(read)
                    continue
                # commit_index now covers everything committed before this
                # leader's term, so it is a safe (conservative) read index.
                read["index"] = self.commit_index
            acks = {self.node_id} | {
                p for p, s in self._peer_ack_seq.items() if s >= read["seq"]
            }
            if self.config.has_quorum(acks) and self.last_applied >= read["index"]:
                effects.append(ReadReady(read["id"], read["index"]))
            else:
                remaining.append(read)
        self._pending_reads = remaining
        return effects

    # ----------------------------------------------------------- replication

    def _broadcast_append(self) -> list:
        effects: list = []
        for peer in self.config.all_nodes() - {self.node_id}:
            effects += self._send_append(peer)
        return effects

    def _send_append(self, peer: str) -> list:
        next_idx = self.next_index.get(peer, self.last_index + 1)
        if next_idx < self.log_start:
            assert self.snapshot is not None
            return [Send(peer, {
                "type": "install_snapshot",
                "term": self.term,
                "leader_id": self.node_id,
                "snapshot": self.snapshot.to_dict(),
                "seq": self._probe_seq,
            })]
        prev_index = next_idx - 1
        prev_term = self.term_at(prev_index)
        if prev_term is None:  # compacted concurrently; retry via snapshot
            return self._send_append_snapshot_fallback(peer)
        entries = self.entries_from(next_idx)
        return [Send(peer, {
            "type": "append_entries",
            "term": self.term,
            "leader_id": self.node_id,
            "prev_log_index": prev_index,
            "prev_log_term": prev_term,
            "entries": [e.to_dict() for e in entries],
            "leader_commit": self.commit_index,
            "seq": self._probe_seq,
        })]

    def _send_append_snapshot_fallback(self, peer: str) -> list:
        if self.snapshot is None:
            return []
        return [Send(peer, {
            "type": "install_snapshot",
            "term": self.term,
            "leader_id": self.node_id,
            "snapshot": self.snapshot.to_dict(),
            "seq": self._probe_seq,
        })]

    def _advance_commit(self) -> list:
        """Joint-majority commit rule (reference simple_raft.rs:2246-2277) with
        the current-term restriction (Raft §5.4.2)."""
        if self.role != Role.LEADER:
            return []
        for n in range(self.last_index, self.commit_index, -1):
            if self.term_at(n) != self.term:
                break
            acks = {self.node_id} | {
                p for p, m in self.match_index.items() if m >= n
            }
            if self.config.has_quorum(acks):
                return self._commit_to(n)
        return []

    def _commit_to(self, n: int) -> list:
        self.commit_index = n
        effects = self._apply_committed()
        effects += self._check_reads()
        effects += self._maybe_advance_membership()
        # A leader removed by a committed final config steps down
        # (joint-consensus exit, Raft §6).
        if (
            self.role == Role.LEADER
            and not self.config.joint
            and self.node_id not in self.config.voters
        ):
            effects += self._step_down(self.term, 0.0)
        return effects

    def _apply_committed(self) -> list:
        if self.last_applied >= self.commit_index:
            return []
        entries = [
            e for e in self.entries_from(self.last_applied + 1,
                                         self.commit_index - self.last_applied)
            if e.index <= self.commit_index
        ]
        if not entries:
            return []
        self.last_applied = entries[-1].index
        return [Apply(tuple(entries))]

    # -------------------------------------------------------- message intake

    #: Fields each message type must carry, with the types the handlers
    #: index without further checks. Malformed peer input must be rejected
    #: BEFORE any state mutation: an exception mid-handler would leave the
    #: core half-updated (e.g. a truncated log whose TruncateLog effect
    #: never reached storage). The reference gets this for free from
    #: protobuf; msgpack-over-gRPC needs an explicit envelope check.
    _REQUIRED: dict = {
        "pre_vote": ("term", "candidate_id", "last_log_index",
                     "last_log_term"),
        "pre_vote_response": ("term", "from", "vote_granted"),
        "request_vote": ("term", "candidate_id", "last_log_index",
                         "last_log_term"),
        "request_vote_response": ("term", "from", "vote_granted"),
        "append_entries": ("term", "leader_id", "prev_log_index",
                           "prev_log_term", "leader_commit"),
        "append_entries_response": ("term", "from", "success",
                                    "match_index"),
        "install_snapshot": ("term", "leader_id", "snapshot"),
        "install_snapshot_response": ("term", "from", "last_index"),
        "timeout_now": (),
    }
    _INT_FIELDS = ("term", "prev_log_index", "prev_log_term",
                   "leader_commit", "last_log_index", "last_log_term",
                   "match_index", "seq", "conflict_index", "last_index")

    def _valid_message(self, msg: Any) -> bool:
        if not isinstance(msg, dict):
            return False
        required = self._REQUIRED.get(msg.get("type"))
        if required is None:
            return False
        if any(f not in msg for f in required):
            return False
        for f in self._INT_FIELDS:
            if f in msg and not isinstance(msg[f], int):
                return False
        for f in ("from", "leader_id", "candidate_id"):
            # Handlers use these as dict/set keys and Send targets: they
            # must be strings (an unhashable value would raise mid-handler).
            if f in msg and not isinstance(msg[f], str):
                return False
        if msg["type"] == "append_entries":
            entries = msg.get("entries") or []
            if not isinstance(entries, list):
                return False
            for e in entries:
                if not isinstance(e, dict) \
                        or not isinstance(e.get("index"), int) \
                        or not isinstance(e.get("term"), int) \
                        or "command" not in e:
                    return False
        if msg["type"] == "install_snapshot":
            snap = msg["snapshot"]
            if not isinstance(snap, dict) \
                    or not isinstance(snap.get("last_index"), int) \
                    or not isinstance(snap.get("last_term"), int) \
                    or not isinstance(snap.get("config"), dict) \
                    or "data" not in snap:
                return False
            cfg = snap["config"]
            groups = [cfg.get("voters"), cfg.get("voters_old"),
                      cfg.get("learners")]
            for g in groups:
                if g is None:
                    continue
                if not isinstance(g, list) \
                        or any(not isinstance(x, str) for x in g):
                    return False
        return True

    def handle_message(self, msg: dict, now: float) -> list:
        if not self._valid_message(msg):
            return []
        mtype = msg["type"]
        term = int(msg.get("term", 0))
        effects: list = []
        # Pre-vote traffic carries the PROSPECTIVE term and must never bump
        # anyone's real term — that is the whole point of pre-vote.
        if term > self.term and mtype not in ("pre_vote",
                                              "pre_vote_response"):
            effects += self._step_down(term, now)
        handler = {
            "pre_vote": self._on_pre_vote,
            "pre_vote_response": self._on_pre_vote_response,
            "request_vote": self._on_request_vote,
            "request_vote_response": self._on_vote_response,
            "append_entries": self._on_append_entries,
            "append_entries_response": self._on_append_response,
            "install_snapshot": self._on_install_snapshot,
            "install_snapshot_response": self._on_install_snapshot_response,
            "timeout_now": self._on_timeout_now,
        }[mtype]
        return effects + handler(msg, now)

    def _on_pre_vote(self, msg: dict, now: float) -> list:
        """Grant iff we'd plausibly vote for this candidate in a real
        election AND we have not heard from a live leader within the minimum
        election timeout — a node still in contact with its leader refuses,
        which is what stops a healed stragglers' election from deposing a
        healthy leader. Grants are non-binding: no term bump, no voted_for,
        nothing persisted, any number of grants per term."""
        up_to_date = (
            int(msg["last_log_term"]) > self.last_term
            or (
                int(msg["last_log_term"]) == self.last_term
                and int(msg["last_log_index"]) >= self.last_index
            )
        )
        granted = (
            int(msg["term"]) > self.term
            and up_to_date
            and self.role != Role.LEADER
            and now - self._last_leader_contact >= self.timings.election_min
        )
        return [Send(msg["candidate_id"], {
            "type": "pre_vote_response",
            "term": int(msg["term"]),
            "from": self.node_id,
            "vote_granted": granted,
        })]

    def _on_pre_vote_response(self, msg: dict, now: float) -> list:
        if self._prevote_term is None or \
                int(msg["term"]) != self._prevote_term or \
                self._prevote_term != self.term + 1 or \
                self.role == Role.LEADER:
            return []
        if msg["vote_granted"]:
            self._prevotes.add(msg["from"])
            if self.config.has_quorum(self._prevotes):
                self._prevote_term = None
                self._prevotes = set()
                return self._start_election(now)
        return []

    def _on_request_vote(self, msg: dict, now: float) -> list:
        granted = False
        # Vote stickiness (etcd check-quorum companion; load-bearing for
        # leader leases): a node that heard from a live leader within the
        # minimum election timeout refuses to elect a new one — except for
        # leadership-transfer elections, which the old leader itself
        # initiated (and which permanently void its lease, _transfer_fired).
        sticky = (
            not msg.get("transfer")
            and now - self._last_leader_contact < self.timings.election_min
        )
        if int(msg["term"]) >= self.term and not sticky:
            up_to_date = (
                int(msg["last_log_term"]) > self.last_term
                or (
                    int(msg["last_log_term"]) == self.last_term
                    and int(msg["last_log_index"]) >= self.last_index
                )
            )
            if up_to_date and self.voted_for in (None, msg["candidate_id"]) \
                    and self.role != Role.LEADER:
                granted = True
                self.voted_for = msg["candidate_id"]
                self._election_deadline = now + self._election_timeout()
        effects: list = []
        if granted:
            effects.append(PersistHardState(self.term, self.voted_for))
        effects.append(Send(msg["candidate_id"], {
            "type": "request_vote_response",
            "term": self.term,
            "from": self.node_id,
            "vote_granted": granted,
        }))
        return effects

    def _on_vote_response(self, msg: dict, now: float) -> list:
        if self.role != Role.CANDIDATE or int(msg["term"]) != self.term:
            return []
        if msg["vote_granted"]:
            self.votes.add(msg["from"])
            if self.config.has_quorum(self.votes):
                return self._become_leader(now)
        return []

    def _on_append_entries(self, msg: dict, now: float) -> list:
        effects: list = []
        leader = msg["leader_id"]
        if int(msg["term"]) < self.term:
            return [Send(leader, self._append_response(False, msg))]
        # Valid leader for this term.
        if self.role != Role.FOLLOWER:
            effects += self._step_down(int(msg["term"]), now)
        self.leader_id = leader
        self._election_deadline = now + self._election_timeout()
        self._last_leader_contact = now
        # A live leader aborts any open pre-vote round: late-arriving
        # grants must not spring a term-bumping election on it.
        self._prevote_term = None
        self._prevotes = set()

        prev_index = int(msg["prev_log_index"])
        prev_term = int(msg["prev_log_term"])
        local_prev_term = self.term_at(prev_index)
        if prev_index > 0 and local_prev_term != prev_term:
            if local_prev_term is None and self.snapshot \
                    and prev_index < self.snapshot.last_index:
                # Already covered by our snapshot; ask from snapshot end.
                conflict = self.snapshot.last_index + 1
            elif local_prev_term is None:
                conflict = self.last_index + 1
            else:
                # First index of the conflicting term (accelerated back-off).
                conflict = prev_index
                while conflict > self.log_start and \
                        self.term_at(conflict - 1) == local_prev_term:
                    conflict -= 1
            resp = self._append_response(False, msg)
            resp["conflict_index"] = conflict
            return effects + [Send(leader, resp)]

        entries = [LogEntry.from_dict(e) for e in msg.get("entries") or []]
        new_entries: list[LogEntry] = []
        truncated_from: int | None = None
        for e in entries:
            local = self.entry(e.index)
            if local is not None and local.term != e.term:
                # Conflict: drop this and everything after (and forget any
                # config that lived only in the truncated suffix).
                pos = e.index - self.log_start
                del self.log[pos:]
                truncated_from = e.index
                local = None
            if local is None and e.index == self.last_index + 1:
                self.log.append(e)
                new_entries.append(e)
                cfg = self._config_of(e)
                if cfg is not None:
                    self.config = cfg
        if truncated_from is not None:
            effects.append(TruncateLog(truncated_from))
            self._recompute_config()
        if new_entries:
            effects.append(AppendLog(tuple(new_entries)))

        # The follower may hold a divergent tail past the leader's entries, so
        # only prev_log_index + len(entries) is CONFIRMED matched — reporting
        # last_index here would let the leader count unheld entries toward
        # quorum and commit without a real majority.
        confirmed = prev_index + len(entries)
        leader_commit = int(msg["leader_commit"])
        if leader_commit > self.commit_index:
            self.commit_index = min(leader_commit, confirmed, self.last_index)
            effects += self._apply_committed()

        effects.append(Send(leader, self._append_response(True, msg, confirmed)))
        return effects

    def _append_response(self, success: bool, msg: dict, match: int = 0) -> dict:
        return {
            "type": "append_entries_response",
            "term": self.term,
            "from": self.node_id,
            "success": success,
            "match_index": match if success else 0,
            "seq": int(msg.get("seq", 0)),
        }

    def _on_append_response(self, msg: dict, now: float) -> list:
        if self.role != Role.LEADER or int(msg["term"]) != self.term:
            return []
        peer = msg["from"]
        seq = int(msg.get("seq", 0))
        if seq > self._peer_ack_seq.get(peer, 0):
            self._peer_ack_seq[peer] = seq
            self._update_lease()
        effects: list = []
        if msg["success"]:
            match = int(msg["match_index"])
            if match > self.match_index.get(peer, 0):
                self.match_index[peer] = match
            self.next_index[peer] = max(self.next_index.get(peer, 1), match + 1)
            effects += self._advance_commit()
            effects += self._check_reads()
            effects += self._tick_catchup(peer)
            # Leader transfer: fire TimeoutNow once the target caught up
            # (reference initiate_leader_transfer, simple_raft.rs:2740-2813).
            if self._transfer_target == peer and match >= self.last_index:
                self._transfer_fired = True  # lease void until next term
                self._lease_until = float("-inf")
                effects.append(Send(peer, {"type": "timeout_now", "term": self.term}))
            # Keep streaming if the follower is still behind.
            if self.next_index[peer] <= self.last_index:
                effects += self._send_append(peer)
        else:
            conflict = int(msg.get("conflict_index", 0))
            self.next_index[peer] = max(
                1, conflict if conflict else self.next_index.get(peer, 2) - 1
            )
            effects += self._send_append(peer)
        return effects

    def _on_install_snapshot(self, msg: dict, now: float) -> list:
        effects: list = []
        if int(msg["term"]) < self.term:
            return []
        if self.role != Role.FOLLOWER:
            effects += self._step_down(int(msg["term"]), now)
        self.leader_id = msg["leader_id"]
        self._election_deadline = now + self._election_timeout()
        self._last_leader_contact = now
        # A live leader aborts any open pre-vote round: late-arriving
        # grants must not spring a term-bumping election on it.
        self._prevote_term = None
        self._prevotes = set()
        snap = Snapshot.from_dict(msg["snapshot"])
        if self.snapshot is None or snap.last_index > self.snapshot.last_index:
            # Keep any log suffix that extends past the snapshot and matches.
            if self.term_at(snap.last_index) == snap.last_term:
                self.log = [e for e in self.log if e.index > snap.last_index]
            else:
                self.log = []
            self.snapshot = snap
            self.config = snap.config
            for e in self.log:
                cfg = self._config_of(e)
                if cfg is not None:
                    self.config = cfg
            self.commit_index = max(self.commit_index, snap.last_index)
            self.last_applied = max(self.last_applied, snap.last_index)
            effects.append(SaveSnapshot(snap))
            effects.append(RestoreFromSnapshot(snap))
        effects.append(Send(msg["leader_id"], {
            "type": "install_snapshot_response",
            "term": self.term,
            "from": self.node_id,
            "last_index": self.snapshot.last_index if self.snapshot else 0,
            "seq": int(msg.get("seq", 0)),
        }))
        return effects

    def _on_install_snapshot_response(self, msg: dict, now: float) -> list:
        if self.role != Role.LEADER or int(msg["term"]) != self.term:
            return []
        peer = msg["from"]
        last = int(msg["last_index"])
        seq = int(msg.get("seq", 0))
        if seq > self._peer_ack_seq.get(peer, 0):
            self._peer_ack_seq[peer] = seq
            self._update_lease()
        self.match_index[peer] = max(self.match_index.get(peer, 0), last)
        self.next_index[peer] = last + 1
        effects = self._advance_commit()
        effects += self._check_reads()
        if self.next_index[peer] <= self.last_index:
            effects += self._send_append(peer)
        return effects

    def _on_timeout_now(self, msg: dict, now: float) -> list:
        """Immediate election for leader transfer (reference TimeoutNow route,
        bin/master.rs:163-171). Stale-term transfers are ignored so a delayed
        TimeoutNow can't depose a healthy later-term leader."""
        if int(msg.get("term", 0)) < self.term:
            return []
        if not self.is_voter or self.role == Role.LEADER:
            return []
        return self._start_election(now, transfer=True)

    # ------------------------------------------------------------ membership

    def add_server(self, node: str, now: float) -> list:
        """Begin adding a voter: the node first replicates as a non-voting
        learner; once caught up (or after N catch-up rounds) the joint config
        is proposed (reference BeginJointConsensus + CatchUpProgress,
        simple_raft.rs:72-106,241-243)."""
        if self.role != Role.LEADER:
            raise NotLeaderError(self.leader_id)
        if self.config.joint or self._catchup is not None:
            raise ValueError("membership change already in progress")
        if node in self.config.voters:
            raise ValueError(f"{node} is already a voter")
        self._catchup = {
            "node": node,
            "rounds_left": self.timings.catchup_rounds,
            "target": self.last_index,
        }
        new_cfg = replace(self.config, learners=self.config.learners | {node})
        _, effects = self.propose({"_config": new_cfg.to_dict()}, now)
        self.next_index.setdefault(node, 1)
        self.match_index.setdefault(node, 0)
        self._peer_ack_seq.setdefault(node, 0)
        return effects

    def remove_server(self, node: str, now: float) -> list:
        if self.role != Role.LEADER:
            raise NotLeaderError(self.leader_id)
        if self.config.joint or self._catchup is not None:
            raise ValueError("membership change already in progress")
        if node not in self.config.voters:
            raise ValueError(f"{node} is not a voter")
        if len(self.config.voters) == 1:
            raise ValueError("cannot remove the last voter")
        joint = Config(
            voters=self.config.voters - {node},
            voters_old=self.config.voters,
            learners=self.config.learners,
        )
        _, effects = self.propose({"_config": joint.to_dict()}, now)
        return effects

    def _tick_catchup(self, peer: str) -> list:
        """Promote a caught-up learner into joint consensus."""
        cu = self._catchup
        if cu is None or cu["node"] != peer or self.config.joint:
            return []
        if self.match_index.get(peer, 0) >= cu["target"]:
            self._catchup = None
            joint = Config(
                voters=self.config.voters | {peer},
                voters_old=self.config.voters,
                learners=self.config.learners - {peer},
            )
            _, effects = self.propose({"_config": joint.to_dict()}, 0.0)
            return effects
        cu["rounds_left"] -= 1
        cu["target"] = self.last_index
        if cu["rounds_left"] <= 0:
            self._catchup = None  # abandon: learner too slow
        return []

    def _maybe_advance_membership(self) -> list:
        """Once the joint config commits, propose the final config
        (reference FinalizeConfiguration, simple_raft.rs:2458-2512)."""
        if self.role != Role.LEADER or not self.config.joint:
            return []
        # Find the latest config entry still in the log.
        for e in reversed(self.log):
            cfg = self._config_of(e)
            if cfg is None:
                continue
            if not cfg.joint:
                return []  # final already proposed
            if e.index <= self.commit_index:
                final = Config(voters=cfg.voters, learners=cfg.learners)
                _, effects = self.propose({"_config": final.to_dict()}, 0.0)
                return effects
            return []
        # No config entry in the log: the joint config came from the snapshot,
        # hence is committed — propose the final config so the cluster doesn't
        # stay in joint consensus forever after compaction.
        cfg = self.config
        final = Config(voters=cfg.voters, learners=cfg.learners)
        _, effects = self.propose({"_config": final.to_dict()}, 0.0)
        return effects

    def transfer_leadership(self, target: str, now: float,
                            timeout: float = 5.0) -> list:
        """Stop accepting proposals, catch the target up, then TimeoutNow
        (reference simple_raft.rs:2740-2813)."""
        if self.role != Role.LEADER:
            raise NotLeaderError(self.leader_id)
        if target not in self.config.voters:
            raise ValueError(f"{target} is not a voter")
        if target == self.node_id:
            return []
        self._transfer_target = target
        self._transfer_deadline = now + timeout
        if self.match_index.get(target, 0) >= self.last_index:
            self._transfer_fired = True  # lease void until next term
            self._lease_until = float("-inf")
            return [Send(target, {"type": "timeout_now", "term": self.term})]
        return self._send_append(target)

    # -------------------------------------------------------------- snapshot

    def compact(self, state_machine_data: bytes) -> list:
        """Install a local snapshot at last_applied and drop covered entries
        (reference create_snapshot, simple_raft.rs:1033-1097)."""
        if self.last_applied < self.log_start:
            return []
        last_term = self.term_at(self.last_applied)
        assert last_term is not None
        snap = Snapshot(
            last_index=self.last_applied,
            last_term=last_term,
            config=self._config_at(self.last_applied),
            data=state_machine_data,
        )
        self.log = [e for e in self.log if e.index > self.last_applied]
        self.snapshot = snap
        return [SaveSnapshot(snap)]

    def _config_at(self, index: int) -> Config:
        cfg = self.snapshot.config if self.snapshot else self.config
        latest = None
        for e in self.log:
            if e.index > index:
                break
            c = self._config_of(e)
            if c is not None:
                latest = c
        if latest is not None:
            return latest
        # No config entry at/below index in the in-memory log.
        if self.snapshot:
            return self.snapshot.config
        return cfg

    # ------------------------------------------------------------- inspection

    def status(self, now: float | None = None) -> dict:
        d = {
            "node_id": self.node_id,
            "role": self.role.value,
            "term": self.term,
            "leader_id": self.leader_id,
            "commit_index": self.commit_index,
            "last_applied": self.last_applied,
            "last_index": self.last_index,
            "log_len": len(self.log),
            "config": self.config.to_dict(),
            "snapshot_index": self.snapshot.last_index if self.snapshot else 0,
        }
        if now is not None and self.role == Role.LEADER:
            d["lease_valid"] = self.lease_valid(now)
            d["lease_remaining_s"] = round(max(0.0, self._lease_until - now), 4)
            d["quorum_contact_age_s"] = round(max(0.0, now - self._quorum_contact), 4)
        return d
