"""Asyncio shell driving a RaftCore over the gRPC substrate.

This is the runtime half of the reference's RaftNode (simple_raft.rs:568-653):
the event loop ticking at 100 ms (simple_raft.rs:1160,1190), commit-wait
replies keyed by log index (pending_replies, simple_raft.rs:627,2452-2454),
peer RPC with a 1.5 s timeout (simple_raft.rs:690), and snapshot compaction
via the state machine's serializer. Where the reference interleaves all of
this with consensus logic in one task, here every decision lives in the pure
core and this shell only executes effects.

State machine contract: ``apply(command) -> result`` (synchronous, fast),
``snapshot() -> bytes``, ``restore(bytes)``. Commands are opaque msgpack-able
values.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Callable

from tpudfs.common.rpc import RpcClient, RpcError, RpcServer, ServerTls
from tpudfs.raft.core import (
    Apply,
    AppendLog,
    BecameLeader,
    Config,
    NotLeaderError,
    PersistHardState,
    RaftCore,
    ReadReady,
    RestoreFromSnapshot,
    SaveSnapshot,
    Send,
    SnapshotNeeded,
    SteppedDown,
    Timings,
    TruncateLog,
)
from tpudfs.raft.storage import RaftStorage

logger = logging.getLogger(__name__)

SERVICE = "RaftService"
PEER_RPC_TIMEOUT = 1.5  # reference simple_raft.rs:690
TICK_INTERVAL = 0.1  # reference simple_raft.rs:1190
PROPOSE_BATCH = 256  # reference event-batch drain, simple_raft.rs:1174-1185


class RaftNode:
    def __init__(
        self,
        node_id: str,
        peers: list[str],
        data_dir: str,
        *,
        apply: Callable[[Any], Any],
        snapshot: Callable[[], bytes],
        restore: Callable[[bytes], None],
        timings: Timings | None = None,
        rpc_client: RpcClient | None = None,
        snapshot_backup=None,
    ):
        self.node_id = node_id
        self.storage = RaftStorage(data_dir)
        term, voted_for, log, snap = self.storage.load()
        if timings is None and \
                os.environ.get("TPUDFS_LEASE_READS", "1") == "0":
            # Ops escape hatch: force every linearizable read through the
            # heartbeat-quorum ReadIndex path (e.g. on hosts with suspect
            # monotonic clocks, where the lease drift bound may not hold).
            timings = Timings(lease_reads=False)
        self.core = RaftCore(
            node_id,
            Config(voters=frozenset(peers) | {node_id}),
            term=term,
            voted_for=voted_for,
            log=log,
            snapshot=snap,
            timings=timings,
            now=time.monotonic(),
        )
        self._apply_fn = apply
        self._snapshot_fn = snapshot
        self._restore_fn = restore
        if snap is not None:
            self._restore_fn(snap.data)
        # Replay committed-but-unsnapshotted state: the core re-applies from
        # snapshot.last_index as commits re-advance after election.
        self._owns_client = rpc_client is None
        self.client = rpc_client or RpcClient()
        self._pending: dict[int, tuple[int, asyncio.Future]] = {}
        self._propose_queue: list[list] = []
        self._drain_task: asyncio.Task | None = None
        self._pending_reads: dict[int, asyncio.Future] = {}
        self._read_seq = 0
        self._lock = asyncio.Lock()
        self._tick_task: asyncio.Task | None = None
        self._send_tasks: set[asyncio.Task] = set()
        self._snapshotting = False
        # Off-site snapshot sink (tpudfs.raft.backup); leader-only uploads,
        # fire-and-forget (reference simple_raft.rs:1214-1271).
        self._backup = snapshot_backup

    # ---------------------------------------------------------------- server

    def handlers(self) -> dict:
        return {"Message": self.rpc_message, "Status": self.rpc_status}

    def attach(self, server: RpcServer) -> None:
        server.add_service(SERVICE, self.handlers())

    async def start(self) -> None:
        self._tick_task = asyncio.create_task(self._tick_loop())

    async def stop(self) -> None:
        if self._tick_task:
            self._tick_task.cancel()
            self._tick_task = None
        if self._drain_task is not None:
            self._drain_task.cancel()
            self._drain_task = None
        # Fail queued-but-undrained proposals so callers don't sit out
        # their full timeout against a stopped node.
        queued, self._propose_queue = self._propose_queue, []
        for item in queued:
            if not item[1].done():
                item[1].set_exception(NotLeaderError(self.core.leader_id))
        for t in list(self._send_tasks):
            t.cancel()
        # close() takes the storage I/O lock, which WAL fsyncs hold on
        # worker threads — never block the loop on it.
        await asyncio.to_thread(self.storage.close)
        if self._owns_client:
            await self.client.close()

    # ----------------------------------------------------------------- RPCs

    async def rpc_message(self, req: dict) -> dict:
        async with self._lock:
            effects = self.core.handle_message(req["msg"], self._now())
            await self._execute(effects)
        return {}

    async def rpc_status(self, _req: dict) -> dict:
        return self.status()

    def status(self) -> dict:
        """Introspection (the reference's /raft/state, bin/master.rs:261-278)
        plus lease/check-quorum health on leaders."""
        return self.core.status(self._now())

    # ------------------------------------------------------------ public API

    @property
    def is_leader(self) -> bool:
        return self.core.role.value == "leader"

    @property
    def leader_hint(self) -> str | None:
        return self.core.leader_id

    async def propose(self, command: Any, timeout: float = 10.0) -> Any:
        """Replicate ``command``; resolves with the state machine's apply
        result once committed (commit-wait, reference simple_raft.rs:2452).

        Concurrent proposals are group-committed: they queue here and a
        single drainer appends up to PROPOSE_BATCH of them as one log-append
        (one WAL fsync) and one replication round, matching the reference's
        256-event batch drain (simple_raft.rs:1174-1185,1689-1778)."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        item = [command, fut, None]  # slot 2 = log index once drained
        self._propose_queue.append(item)
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.create_task(self._drain_proposals())
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            if item[2] is not None:
                self._pending.pop(item[2], None)
            else:
                try:
                    self._propose_queue.remove(item)
                except ValueError:
                    pass
            raise NotLeaderError(self.core.leader_id) from None

    async def _drain_proposals(self) -> None:
        while self._propose_queue:
            batch = self._propose_queue[:PROPOSE_BATCH]
            del self._propose_queue[: len(batch)]
            async with self._lock:
                try:
                    indices, effects = self.core.propose_batch(
                        [item[0] for item in batch], self._now()
                    )
                except NotLeaderError as e:
                    for item in batch:
                        if not item[1].done():
                            item[1].set_exception(
                                NotLeaderError(e.leader_hint)
                            )
                    continue
                for item, index in zip(batch, indices):
                    item[2] = index
                    self._pending[index] = (self.core.term, item[1])
                try:
                    await self._execute(effects)
                except Exception as e:
                    # Persistence/effect failure (e.g. WAL append ENOSPC):
                    # surface the real error to this batch — the entries are
                    # appended in-memory so they MAY still commit ("maybe
                    # applied", same contract as a propose timeout) — and
                    # keep draining so later proposals aren't stranded.
                    logger.exception("proposal batch effects failed")
                    for item in batch:
                        self._pending.pop(item[2], None)
                        if not item[1].done():
                            item[1].set_exception(e)

    async def read_index(self, timeout: float = 10.0) -> int:
        """Linearizable read barrier; resolves once this node has confirmed
        leadership and applied up to the read index."""
        async with self._lock:
            self._read_seq += 1
            rid = self._read_seq
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending_reads[rid] = fut
            await self._execute(self.core.read_index(rid, self._now()))
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending_reads.pop(rid, None)
            raise NotLeaderError(self.core.leader_id) from None

    async def add_server(self, node: str) -> None:
        async with self._lock:
            await self._execute(self.core.add_server(node, self._now()))

    async def remove_server(self, node: str) -> None:
        async with self._lock:
            await self._execute(self.core.remove_server(node, self._now()))

    async def transfer_leadership(self, target: str) -> None:
        async with self._lock:
            await self._execute(self.core.transfer_leadership(target, self._now()))

    # -------------------------------------------------------------- internals

    @staticmethod
    def _now() -> float:
        return time.monotonic()

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(TICK_INTERVAL)
            async with self._lock:
                try:
                    await self._execute(self.core.tick(self._now()))
                except Exception:
                    logger.exception("tick failed")

    async def _execute(self, effects: list) -> None:
        sends: list[Send] = []
        for eff in effects:
            if isinstance(eff, Send):
                sends.append(eff)
            elif isinstance(eff, PersistHardState):
                await asyncio.to_thread(
                    self.storage.save_hard_state, eff.term, eff.voted_for
                )
            elif isinstance(eff, AppendLog):
                await asyncio.to_thread(
                    self.storage.append_entries, list(eff.entries)
                )
            elif isinstance(eff, TruncateLog):
                await asyncio.to_thread(self.storage.truncate_from, eff.from_index)
                self._fail_pending_from(eff.from_index)
            elif isinstance(eff, Apply):
                for entry in eff.entries:
                    result = None
                    if not (isinstance(entry.command, dict)
                            and ("_noop" in entry.command or "_config" in entry.command)):
                        try:
                            result = self._apply_fn(entry.command)
                        except Exception as e:
                            logger.exception("state machine apply failed")
                            result = e
                    pending = self._pending.pop(entry.index, None)
                    if pending is not None:
                        term, fut = pending
                        if not fut.done():
                            if term != entry.term:
                                fut.set_exception(
                                    NotLeaderError(self.core.leader_id)
                                )
                            elif isinstance(result, Exception):
                                fut.set_exception(result)
                            else:
                                fut.set_result(result)
            elif isinstance(eff, SaveSnapshot):
                await asyncio.to_thread(
                    self.storage.save_snapshot, eff.snapshot, list(self.core.log)
                )
                if self._backup is not None and self.is_leader:
                    task = asyncio.create_task(
                        self._backup_snapshot(eff.snapshot)
                    )
                    self._send_tasks.add(task)
                    task.add_done_callback(self._send_tasks.discard)
            elif isinstance(eff, RestoreFromSnapshot):
                self._restore_fn(eff.snapshot.data)
            elif isinstance(eff, ReadReady):
                fut = self._pending_reads.pop(eff.request_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(eff.read_index)
            elif isinstance(eff, SteppedDown):
                self._fail_all_pending()
            elif isinstance(eff, BecameLeader):
                logger.info("%s became leader for term %d", self.node_id, eff.term)
            elif isinstance(eff, SnapshotNeeded):
                if not self._snapshotting:
                    self._snapshotting = True
                    try:
                        data = self._snapshot_fn()
                        await self._execute(self.core.compact(data))
                    finally:
                        self._snapshotting = False
        for s in sends:
            task = asyncio.create_task(self._send(s.to, s.msg))
            self._send_tasks.add(task)
            task.add_done_callback(self._send_tasks.discard)

    async def _backup_snapshot(self, snapshot) -> None:
        """Upload to the off-site sink without ever blocking consensus."""
        try:
            aupload = getattr(self._backup, "aupload", None)
            if aupload is not None:
                await aupload(self.node_id, snapshot)
            else:
                await asyncio.to_thread(
                    self._backup.upload, self.node_id, snapshot
                )
            logger.info("snapshot @%d backed up off-site", snapshot.last_index)
        except Exception:
            logger.exception("off-site snapshot backup failed")

    def _fail_pending_from(self, index: int) -> None:
        for idx in [i for i in self._pending if i >= index]:
            _, fut = self._pending.pop(idx)
            if not fut.done():
                fut.set_exception(NotLeaderError(self.core.leader_id))

    def _fail_all_pending(self) -> None:
        for idx in list(self._pending):
            _, fut = self._pending.pop(idx)
            if not fut.done():
                fut.set_exception(NotLeaderError(self.core.leader_id))
        for rid in list(self._pending_reads):
            fut = self._pending_reads.pop(rid)
            if not fut.done():
                fut.set_exception(NotLeaderError(self.core.leader_id))

    async def _send(self, peer: str, msg: dict) -> None:
        try:
            await self.client.call(
                peer, SERVICE, "Message", {"msg": msg}, timeout=PEER_RPC_TIMEOUT
            )
        except RpcError as e:
            logger.debug("raft send to %s failed: %s", peer, e.message)
