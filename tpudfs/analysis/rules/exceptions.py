"""TPL003 — silent broad exception handler.

A bare ``except:`` / ``except Exception:`` that neither logs, re-raises,
propagates, nor counts the failure can swallow data-plane corruption: a
checksum mismatch or a failed replication ack disappears without a trace and
the system keeps serving. Every broad handler must leave evidence.

Accepted evidence inside the handler body:

- ``raise`` (bare or new exception);
- a logging call — any ``logger.*`` / ``logging.*`` / ``self.log.*`` method
  (``debug`` through ``critical``/``exception``), or ``print`` (CLI surface);
- error propagation — ``fut.set_exception(...)``/``.set_result`` on a
  future, or handing the caught exception object itself to any callable
  (``out.put(e)``, ``callback(e)``, ``errors.append(e)``) — the error
  travels on for someone else to observe;
- a telemetry update — calling ``.inc``/``.observe``/``.increment``, touching
  a dotted path containing ``metrics``/``stats``/``counter``, or an
  augmented assignment to such a path (``self.stats.failures += 1``).

Narrow handlers (``except RpcError:`` etc.) are out of scope: catching a
specific type is itself a statement of intent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "fatal",
}
_PROPAGATE_METHODS = {"set_exception", "set_result"}
_COUNTER_METHODS = {"inc", "observe", "increment", "add", "update"}
_COUNTER_HINTS = ("metrics", "stats", "counter", "telemetry")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name)
                   and e.id in ("Exception", "BaseException")
                   for e in t.elts)
    return False


def _counterish(name: str | None) -> bool:
    if not name:
        return False
    low = name.lower()
    return any(h in low for h in _COUNTER_HINTS)


def _has_evidence(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and handler.name:
            # `except Exception as e: out.put(e)` — the exception object is
            # handed to another party; that IS the propagation.
            values = list(node.args) + [k.value for k in node.keywords]
            if any(isinstance(v, ast.Name) and v.id == handler.name
                   for v in values):
                return True
        if isinstance(node, ast.AugAssign):
            if _counterish(dotted_name(node.target)):
                return True
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name) and func.id == "print":
            return True
        name = dotted_name(func)
        if isinstance(func, ast.Attribute):
            attr = func.attr
            receiver = dotted_name(func.value) or ""
            rlow = receiver.lower()
            if attr in _LOG_METHODS and (
                "log" in rlow or rlow in ("logging",)
            ):
                return True
            if attr in _PROPAGATE_METHODS:
                return True
            if attr in _COUNTER_METHODS and _counterish(receiver):
                return True
        if _counterish(name):
            return True
    return False


@register
class SilentBroadExcept(Rule):
    id = "TPL003"
    name = "silent-broad-except"
    summary = ("bare/broad `except` that neither logs, re-raises, propagates "
               "nor counts — can silently swallow data-plane corruption")
    doc = (
        "A distributed file system's worst failure mode is silent: a "
        "swallowed BlockCorruptionError is a read that returned garbage "
        "and told no one. `except Exception: pass` (or bare `except`) is "
        "acceptable only when the handler leaves a trace — a log line, a "
        "metrics counter, a re-raise — so operators can see the failure "
        "rate. Narrow excepts ((OSError, ValueError)) are always fine: "
        "naming the exception is itself the evidence of intent."
    )
    example = """\
def read_meta(path):
    try:
        return load(path)
    except Exception:
        pass           # corruption, ENOSPC, bugs: all invisible
"""
    fix = ("Narrow the exception types, or keep the breadth but log "
           "(`logger.exception`), count (`self.metrics.x += 1`), or "
           "re-raise a wrapped error.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _has_evidence(node):
                continue
            caught = "bare except" if node.type is None else \
                f"except {ast.unparse(node.type)}"
            yield self.finding(
                module, node,
                f"{caught} swallows errors silently — log it, re-raise, "
                "or bump a telemetry counter (or narrow the except type)",
            )
