"""tpulint rule registry.

Importing this package registers every rule with the framework (the
``@register`` decorator in tpudfs.analysis.linter). Adding a rule = adding a
module here and importing it below.
"""

from tpudfs.analysis.rules import (  # noqa: F401
    blocking,
    locks,
    exceptions,
    raft_state,
    checksum,
    determinism,
    tasks,
    # Interprocedural rules (call-graph backed, see tpudfs/analysis/callgraph.py)
    transitive,
    lock_order,
    rpc_contract,
    checksum_taint,
    task_escape,
    deadline,
    # CFG/dataflow rules (see tpudfs/analysis/cfg.py + dataflow.py)
    races,
    lock_hygiene,
    resources,
    raft_durability,
    ckpt_publish,
    stream_discipline,
    # tpuperf performance rules (hotpath.py + bufferflow.py backed)
    perf,
    # tpunative cross-language rules (nativesrc.py C++ extraction backed)
    native_abi,
    native_wire,
    native_threads,
    # tpusched protocol-ordering rules (explorer targets, see
    # tpudfs/testing/vclock.py + tpudfs/analysis/linearize.py)
    interleave,
    # tpuflow zero-copy rules (byteflow.py byte-cost ledger backed)
    zerocopy,
)
