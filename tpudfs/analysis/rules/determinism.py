"""TPL006 — nondeterminism inside the pure Raft core.

tpudfs/raft/core.py is a deterministic state machine by contract: time
enters via ``tick(now)``, randomness via an injected ``random.Random``, and
every run of the simulation tiers (test_raft_core / test_raft_partitions /
test_raft_jepsen) must replay bit-identically from a seed. A stray
``time.time()`` or module-level ``random.uniform()`` silently breaks replay
— bugs found by the Jepsen-style fuzzer stop being reproducible.

``random.Random(...)`` (constructing the injectable RNG) is allowed; calling
the module-level convenience functions, wall clocks, uuid or os.urandom is
not. The rule applies only to the modules listed in ``PURE_MODULES``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

PURE_MODULES = ("tpudfs/raft/core.py",)

_FORBIDDEN_EXACT = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "os.urandom", "os.getrandom",
    "uuid.uuid1", "uuid.uuid4",
}
_FORBIDDEN_PREFIXES = ("random.", "secrets.")
_ALLOWED = {"random.Random", "random.SystemRandom"}  # SystemRandom flagged below

_MESSAGE = ("nondeterministic call `{name}` in the pure Raft core — inject "
            "time via `tick(now)` and randomness via the `rng` parameter so "
            "simulation replays stay bit-identical")


@register
class NondeterminismInPureCore(Rule):
    id = "TPL006"
    name = "nondeterminism-in-pure-core"
    summary = ("wall-clock / module-level random / uuid inside raft/core.py "
               "breaks deterministic simulation replay")
    doc = (
        "The Raft core is tested by deterministic simulation (seeded "
        "schedules, replayable histories — tests/raft_sim.py). That only "
        "works if the core's behavior is a pure function of its inputs: "
        "time comes in as a parameter, randomness from an injected "
        "seeded rng. A stray time.monotonic() or random.uniform() makes "
        "a failing schedule unreproducible — the one property that makes "
        "consensus bugs debuggable."
    )
    example = """\
def election_timeout(self):    # tpudfs/raft/core.py
    return time.monotonic() + random.uniform(1, 2)
"""
    fix = ("Take `now` as an argument (the node passes it in) and draw "
           "jitter from the injected `random.Random(seed)`.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if module.rel_path not in PURE_MODULES:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            if name == "random.Random":
                continue  # the injectable RNG type itself
            bad = name in _FORBIDDEN_EXACT or name == "random.SystemRandom" \
                or any(name.startswith(p) for p in _FORBIDDEN_PREFIXES)
            if bad:
                yield self.finding(module, node, _MESSAGE.format(name=name))
