"""TPL010 — transitive blocking call reachable from ``async def``.

TPL001 catches ``time.sleep`` written directly inside an async function.
The production incidents look different: the sleep (or requests call, or
subprocess) sits three helpers deep in a sync utility that an async RPC
handler calls — each function locally innocent, the composition a stalled
event loop. This rule walks the project call graph: starting from every
``async def``, it follows ``"call"`` edges into synchronous functions and
flags the first edge of any chain that reaches a blocking leaf.

Propagation deliberately stops at:

- ``"thread"`` edges (``asyncio.to_thread`` / ``run_in_executor`` /
  ``threading.Thread``) — blocking work behind those runs off-loop, which
  is exactly the recommended fix;
- async callees — an awaited async function's own blocking calls are its
  own TPL001/TPL010 findings (one report at the responsible function, not
  one per transitive caller);
- unresolved calls — dynamic dispatch produces silence, not guesses.

Direct blocking calls inside the async function itself stay TPL001's;
TPL010 only fires on chains of length >= 2, so the two rules partition the
failure mode instead of double-reporting it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.rules.blocking import blocking_call


def _direct_blocking(fn: FunctionInfo) -> tuple[str, str] | None:
    """First blocking leaf whose innermost enclosing function is ``fn``
    (nested defs analyze as their own functions), suppression-aware."""
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        if fn.module.enclosing_function(node) is not fn.node:
            continue
        hit = blocking_call(node)
        if hit is None:
            continue
        line = getattr(node, "lineno", 0)
        if fn.module.suppressed("TPL001", line) \
                or fn.module.suppressed("TPL010", line):
            continue
        return hit
    return None


@register
class TransitiveBlockingInAsync(ProjectRule):
    id = "TPL010"
    name = "transitive-blocking-in-async"
    summary = ("a sync call chain reachable from `async def` ends in a "
               "blocking leaf (time.sleep, requests, subprocess, sync file "
               "I/O) — stalls the event loop just like a direct call")
    doc = (
        "TPL001 sees the blocking call only when it is written inside "
        "the `async def`. The ones that survive review hide two hops "
        "away: the coroutine calls a helper, the helper calls a leaf "
        "that sleeps. This rule walks the resolved call graph from every "
        "coroutine through same-thread sync calls and reports the full "
        "chain down to the blocking leaf. to_thread/executor bridges "
        "end the chain — that is the sanctioned way to run such code."
    )
    example = """\
# util.py
def fetch_meta(req):
    return slow_probe(req)     # -> time.sleep(0.2)
# handler.py
async def handle(req):
    return fetch_meta(req)     # blocks the loop, two files away
"""
    fix = ("Offload the sync entry point: `await asyncio.to_thread("
           "fetch_meta, req)` — or make the chain truly async.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        #: fn -> (chain of FunctionInfo down to the leaf, leaf what/hint)
        memo: dict[FunctionInfo, tuple[list[FunctionInfo],
                                       tuple[str, str]] | None] = {}

        def reach(fn: FunctionInfo, stack: set[FunctionInfo]):
            if fn in memo:
                return memo[fn]
            if fn in stack:
                return None  # recursion: break the cycle, assume clean
            stack.add(fn)
            result = None
            hit = _direct_blocking(fn)
            if hit is not None:
                result = ([fn], hit)
            else:
                for edge in project.sync_call_edges(fn):
                    sub = reach(edge.callee, stack)
                    if sub is not None:
                        result = ([fn] + sub[0], sub[1])
                        break
            stack.discard(fn)
            memo[fn] = result
            return result

        for fn in project.functions.values():
            if not fn.is_async:
                continue
            for edge in project.sync_call_edges(fn):
                sub = reach(edge.callee, set())
                if sub is None:
                    continue
                chain, (what, hint) = sub
                path = " -> ".join(f.short() for f in [fn] + chain)
                yield self.finding(
                    fn.module, edge.site,
                    f"async `{fn.short()}` transitively blocks the event "
                    f"loop: {path} -> `{what}`; {hint}, or move the chain "
                    "behind `asyncio.to_thread`",
                )
