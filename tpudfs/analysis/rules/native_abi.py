"""TPL040 — C ABI conformance between native exports and ctypes bindings.

The native engine exports a hand-written C ABI (``extern "C"`` functions
in ``native/*.cc``) that ``tpudfs/common/native.py`` binds with equally
hand-written ctypes declarations. Nothing checks the two against each
other: an extra parameter added on the C side, a ``uint32_t`` narrowed
to ``uint16_t``, or a forgotten ``TPUDFS_DATAPLANE_ABI`` bump all load
and link fine — and then corrupt arguments at call time, on whatever
machine rebuilds the ``.so`` first. This rule parses both sides
(:mod:`tpudfs.analysis.nativesrc`) and proves them in lockstep:

- every ``lib.tpudfs_*`` ctypes declaration must name a real export,
  with matching arity and ABI-compatible parameter/return types
  (``c_void_p`` accepts any pointer; ``c_char_p`` means ``char*``;
  scalars must match width and signedness, with ``size_t``/``uint64_t``
  and ``ssize_t``/``int64_t`` treated as the LP64 aliases they are);
- when one ``.cc`` file re-declares another's export (dataplane.cc
  declares the blockio.cc staging functions it calls), the duplicate
  declarations must agree;
- the dataplane ABI version must be the same number in
  ``tpudfs_dataplane_abi()``'s return and native.py's version guard; and
- the checked-in ABI manifest (``tpudfs/analysis/native_abi.json``,
  regenerated via ``tpulint --write-native-abi``) pins every
  ``tpudfs_dataplane_*`` signature at the current version — changing a
  signature without bumping the version is a finding even though both
  sides changed in lockstep, because old ``.so`` files stay loadable.

This module also hosts the helpers the other TPL04x rules share
(:func:`native_context`, :func:`native_finding`).
"""

from __future__ import annotations

import json
import pathlib
from typing import Iterator

from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.nativesrc import (
    CFunc,
    NativeSource,
    ctype_compatible,
    format_ctype_for_human,
    load_native_sources,
    parse_ctypes_decls,
    project_root,
)

#: Repo-relative path of the ABI manifest.
ABI_MANIFEST_REL = "tpudfs/analysis/native_abi.json"

#: Exports pinned by the manifest: the dataplane family, whose loadable
#: lifetime is governed by the ``tpudfs_dataplane_abi()`` version gate.
ABI_FAMILY_PREFIX = "tpudfs_dataplane_"


def native_context(project) -> tuple[pathlib.Path | None,
                                     list[NativeSource]]:
    """(repo root, parsed native sources) for a project — the shared
    entry point of every TPL04x rule. Empty sources = rules inert."""
    root = project_root(project)
    if root is None:
        return None, []
    return root, load_native_sources(root)


def native_finding(rule_id: str, src: NativeSource, line: int,
                   scope: str, message: str) -> Finding | None:
    """A finding anchored in a C++ file, honoring its ``// tpulint:``
    suppressions (the driver only applies Python-module suppressions)."""
    if src.suppressed(rule_id, line):
        return None
    return Finding(rule=rule_id, path=src.rel, line=line, col=0,
                   message=message, scope=scope,
                   snippet=src.snippet(line))


def py_finding(rule_id: str, module, line: int, scope: str,
               message: str) -> Finding:
    """A finding anchored in a Python module at a known line (the driver
    applies the module's suppressions)."""
    return Finding(rule=rule_id, path=module.rel_path, line=line, col=0,
                   message=message, scope=scope,
                   snippet=module.snippet(line))


def collect_exports(
    sources: list[NativeSource],
) -> dict[str, list[tuple[CFunc, NativeSource]]]:
    """Every ``extern "C"`` declaration/definition by symbol name."""
    out: dict[str, list[tuple[CFunc, NativeSource]]] = {}
    for src in sources:
        for fn in src.exports:
            out.setdefault(fn.name, []).append((fn, src))
    return out


def best_export(entries: list[tuple[CFunc, NativeSource]]
                ) -> tuple[CFunc, NativeSource]:
    """Prefer the definition over redeclarations."""
    for fn, src in entries:
        if fn.defined:
            return fn, src
    return entries[0]


def load_abi_manifest(root: pathlib.Path) -> dict | None:
    path = root / ABI_MANIFEST_REL
    if not path.exists():
        return None
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "exports" not in data:
        return None
    return data


def current_abi_surface(
    sources: list[NativeSource],
) -> tuple[int | None, dict[str, str]]:
    """(dataplane ABI version, {export name: canonical signature}) as
    the tree defines them right now — the manifest's ground truth."""
    version = None
    for src in sources:
        if src.abi_version is not None:
            version = src.abi_version
    sigs: dict[str, str] = {}
    for name, entries in collect_exports(sources).items():
        if not name.startswith(ABI_FAMILY_PREFIX):
            continue
        fn, _src = best_export(entries)
        if fn.defined:
            sigs[name] = fn.signature
    return version, sigs


def _human_sig(fn: CFunc) -> str:
    params = ", ".join(format_ctype_for_human(p.canon) for p in fn.params)
    return f"{format_ctype_for_human(fn.ret)}({params})"


@register
class NativeAbiConformance(ProjectRule):
    id = "TPL040"
    name = "native-abi-conformance"
    summary = ("ctypes declaration in native.py out of lockstep with the "
               "`extern \"C\"` export it binds (missing symbol, arity or "
               "type mismatch, ABI version drift, or a dataplane "
               "signature changed without a TPUDFS_DATAPLANE_ABI bump)")
    doc = (
        "native.py's ctypes declarations and the `extern \"C\"` exports "
        "in native/*.cc are two hand-written copies of one C ABI; "
        "ctypes trusts the Python copy blindly, so a drifted parameter "
        "list or return type loads fine and silently corrupts arguments "
        "at call time. This rule parses both sides and flags: a bound "
        "symbol no native file exports; argtypes whose arity differs "
        "from the C parameter list; a parameter or return whose ctypes "
        "type is not ABI-compatible with the C type (c_void_p matches "
        "any pointer, c_char_p means char*, scalars must match width "
        "and signedness — size_t/uint64_t and ssize_t/int64_t are LP64 "
        "aliases); two .cc files declaring the same export with "
        "different signatures; the version returned by "
        "tpudfs_dataplane_abi() differing from the guard in native.py; "
        "and any tpudfs_dataplane_* signature differing from the "
        "checked-in manifest (tpudfs/analysis/native_abi.json) while "
        "the ABI version stayed the same — lockstep edits still break "
        "previously-built .so files, which the version gate exists to "
        "reject."
    )
    example = """\
// dataplane.cc
int64_t tpudfs_dataplane_start(const char* host, uint32_t port,
                               uint16_t shards);  // 3 params
# native.py
lib.tpudfs_dataplane_start.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
"""
    fix = ("Make the ctypes declaration mirror the C signature "
           "parameter-for-parameter; when a tpudfs_dataplane_* "
           "signature legitimately changes, bump the version returned "
           "by tpudfs_dataplane_abi(), update the guard in native.py, "
           "and regenerate the manifest with `python -m tpudfs.analysis "
           "--write-native-abi`.")

    def check_project(self, project) -> Iterator[Finding]:
        root, sources = native_context(project)
        if not sources:
            return
        exports = collect_exports(sources)
        yield from self._redeclaration_findings(exports)
        yield from self._python_side_findings(project, exports)
        yield from self._abi_version_findings(project, sources)
        yield from self._manifest_findings(root, sources)

    # ------------------------------------------- cross-TU redeclarations

    def _redeclaration_findings(self, exports) -> Iterator[Finding]:
        for name, entries in exports.items():
            if len(entries) < 2:
                continue
            ref, ref_src = best_export(entries)
            for fn, src in entries:
                if fn is ref or fn.signature == ref.signature:
                    continue
                f = native_finding(
                    self.id, src, fn.line, name,
                    f"`{name}` is declared here as `{_human_sig(fn)}` "
                    f"but {'defined' if ref.defined else 'declared'} in "
                    f"{ref_src.rel}:{ref.line} as `{_human_sig(ref)}` — "
                    "the redeclaration lies about the real ABI")
                if f is not None:
                    yield f

    # ------------------------------------------------- ctypes vs exports

    def _python_side_findings(self, project, exports) -> Iterator[Finding]:
        for module in project.modules.values():
            decls = parse_ctypes_decls(module.tree)
            for name in sorted(decls.decls):
                d = decls.decls[name]
                if not name.startswith("tpudfs_"):
                    continue
                entries = exports.get(name)
                line = d.argtypes_line or d.restype_line
                if not entries:
                    yield py_finding(
                        self.id, module, line, name,
                        f"ctypes binds `lib.{name}` but no native/*.cc "
                        "file exports that symbol — the call will raise "
                        "AttributeError (or bind a stale .so) at "
                        "runtime")
                    continue
                fn, src = best_export(entries)
                yield from self._signature_findings(module, d, fn, src)

    def _signature_findings(self, module, d, fn: CFunc,
                            src: NativeSource) -> Iterator[Finding]:
        name = fn.name
        if d.argtypes is not None and len(d.argtypes) != len(fn.params):
            f = native_finding(
                self.id, src, fn.line, name,
                f"`{name}` takes {len(fn.params)} parameter(s) here "
                f"(`{_human_sig(fn)}`) but native.py declares "
                f"{len(d.argtypes)} argtype(s) "
                f"({module.rel_path}:{d.argtypes_line}) — arity "
                "mismatch corrupts the call frame")
            if f is not None:
                yield f
            return
        if d.argtypes is not None:
            for i, (py_t, param) in enumerate(zip(d.argtypes, fn.params)):
                if ctype_compatible(py_t, param.canon):
                    continue
                pname = f" `{param.name}`" if param.name else ""
                f = native_finding(
                    self.id, src, fn.line, name,
                    f"`{name}` parameter {i + 1}{pname} is "
                    f"`{format_ctype_for_human(param.canon)}` here but "
                    f"native.py declares "
                    f"`{format_ctype_for_human(py_t)}` "
                    f"({module.rel_path}:{d.argtypes_line}) — not "
                    "ABI-compatible")
                if f is not None:
                    yield f
        if d.restype is not None \
                and not ctype_compatible(d.restype, fn.ret):
            f = native_finding(
                self.id, src, fn.line, name,
                f"`{name}` returns "
                f"`{format_ctype_for_human(fn.ret)}` here but native.py "
                f"declares restype "
                f"`{format_ctype_for_human(d.restype)}` "
                f"({module.rel_path}:{d.restype_line}) — not "
                "ABI-compatible")
            if f is not None:
                yield f

    # -------------------------------------------------- ABI version gate

    def _abi_version_findings(self, project, sources) -> Iterator[Finding]:
        cc_version = None
        cc_src = None
        for src in sources:
            if src.abi_version is not None:
                cc_version, cc_src = src.abi_version, src
        if cc_version is None:
            return
        for module in project.modules.values():
            for expected, line in parse_ctypes_decls(module.tree).abi_checks:
                if expected == cc_version:
                    continue
                yield py_finding(
                    self.id, module, line, "tpudfs_dataplane_abi",
                    f"native.py gates the dataplane bindings on ABI "
                    f"version {expected} but tpudfs_dataplane_abi() in "
                    f"{cc_src.rel}:{cc_src.abi_line} returns "
                    f"{cc_version} — the two sides will refuse (or "
                    "worse, mis-accept) each other")

    # --------------------------------------------- manifest / bump gate

    def _manifest_findings(self, root, sources) -> Iterator[Finding]:
        manifest = load_abi_manifest(root)
        if manifest is None:
            return
        version, sigs = current_abi_surface(sources)
        if version is None or not sigs:
            return
        abi_src = next(s for s in sources if s.abi_version is not None)
        man_version = manifest.get("abi_version")
        man_exports = manifest.get("exports", {})
        if man_version != version:
            f = native_finding(
                self.id, abi_src, abi_src.abi_line, "tpudfs_dataplane_abi",
                f"tpudfs_dataplane_abi() returns {version} but the ABI "
                f"manifest ({ABI_MANIFEST_REL}) records "
                f"{man_version} — regenerate it with `python -m "
                "tpudfs.analysis --write-native-abi`")
            if f is not None:
                yield f
            return  # signature diffs against a stale manifest are noise
        for name in sorted(set(sigs) | set(man_exports)):
            cur, pinned = sigs.get(name), man_exports.get(name)
            if cur == pinned:
                continue
            if cur is None:
                f = native_finding(
                    self.id, abi_src, abi_src.abi_line, name,
                    f"dataplane export `{name}` was removed (or "
                    "un-exported) without bumping "
                    f"tpudfs_dataplane_abi() — still pinned at version "
                    f"{version} in {ABI_MANIFEST_REL}; bump the version "
                    "and regenerate with --write-native-abi")
                if f is not None:
                    yield f
                continue
            fn, src = best_export(collect_exports(sources)[name])
            what = ("is new" if pinned is None else
                    f"changed signature (manifest pins `{pinned}`, now "
                    f"`{cur}`)")
            f = native_finding(
                self.id, src, fn.line, name,
                f"dataplane export `{name}` {what} but "
                f"tpudfs_dataplane_abi() still returns {version} — "
                "previously built .so files would pass the version gate "
                "with a different ABI; bump the version, update the "
                "native.py guard, and regenerate the manifest with "
                "`python -m tpudfs.analysis --write-native-abi`")
            if f is not None:
                yield f
