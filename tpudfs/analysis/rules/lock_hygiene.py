"""TPL021 — path-sensitive lock hygiene over the function CFG.

TPL002 is lexical: it sees an ``await`` written inside a ``with lock:``
body, or a bare ``.acquire()`` in async code, and fires on the shape. This
rule runs a may-analysis over :mod:`tpudfs.analysis.cfg` and reasons about
*paths*, which catches what shapes cannot:

- a ``threading`` lock acquired with a bare ``.acquire()`` and provably
  still held when control reaches an ``await`` — the event-loop thread
  parks with the mutex locked, and every other thread (and any coroutine
  reaching the same lock) blocks behind a suspended coroutine;
- **any** lock (``threading`` or ``asyncio``) acquired without ``with``
  on a path that can raise before the matching ``.release()`` — the
  exception unwinds, nothing releases, and the lock is dead forever; also
  the plain multi-path variant where an early ``return`` skips the
  release.

``with``-based acquisitions are exempt everywhere here: the context
manager releases on all paths by construction (their await-crossing case
is TPL002's). A function that never calls ``.release()`` on the lock is
also exempt from the leak checks — that is the cross-function hand-off
protocol, someone else's release, and flow analysis inside one function
cannot judge it.

Lock identity is module-local (names and ``self.attr`` targets assigned
from ``threading.*``/``asyncio.*`` lock constructors), like TPL002 —
which keeps this rule per-module and content-cacheable.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.cfg import Node, cfg_for
from tpudfs.analysis.dataflow import MayAnalysis, solve
from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)
from tpudfs.analysis.lockinfo import ASYNC_CTORS, THREAD_CTORS

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _lock_kinds(module: ModuleInfo) -> dict[str, str]:
    """Module-local lock symbols: dotted name -> "thread" | "async"."""
    kinds: dict[str, str] = {}
    for node in ast.walk(module.tree):
        value = None
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        if not isinstance(value, ast.Call):
            continue
        ctor = dotted_name(value.func)
        if ctor in THREAD_CTORS:
            kind = "thread"
        elif ctor in ASYNC_CTORS:
            kind = "async"
        else:
            continue
        for t in targets:
            name = dotted_name(t)
            if name:
                kinds[name] = kind
    return kinds


def _receiver_name(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute):
        return dotted_name(call.func.value)
    return None


class _HeldMay(MayAnalysis):
    """May-held lock entries: (name, kind, origin, acquire_lineno)."""

    def __init__(self, kinds: dict[str, str]):
        self._kinds = kinds

    def _with_entries(self, node: Node) -> frozenset:
        out = set()
        for item in node.stmt.items:  # type: ignore[union-attr]
            expr = item.context_expr
            target = expr.func if isinstance(expr, ast.Call) else expr
            if isinstance(target, ast.Attribute) \
                    and target.attr in ("acquire", "locked"):
                target = target.value
            name = dotted_name(target)
            kind = self._kinds.get(name or "")
            if kind is not None:
                out.add((name, kind, "with", node.stmt.lineno))
        return frozenset(out)

    def transfer(self, node: Node, value):
        if node.kind == "with_enter":
            return value | self._with_entries(node)
        if node.kind == "with_exit":
            return value - self._with_entries(node)
        for sub in node.walk():
            if not isinstance(sub, ast.Call) \
                    or not isinstance(sub.func, ast.Attribute):
                continue
            name = _receiver_name(sub)
            kind = self._kinds.get(name or "")
            if kind is None:
                continue
            if sub.func.attr == "acquire":
                value = value | {(name, kind, "bare", sub.lineno)}
            elif sub.func.attr == "release":
                value = frozenset(e for e in value if e[0] != name)
        return value

    def edge_value(self, src: Node, dst: Node, kind: str, value):
        if kind != "exc":
            return value
        # If the acquire statement itself raised, the lock was not taken.
        return frozenset(e for e in value
                         if not (e[2] == "bare" and e[3] == src.lineno))


@register
class PathSensitiveLockHygiene(Rule):
    id = "TPL021"
    name = "lock-leak-on-path"
    summary = ("bare .acquire() held across an await, or a lock acquired "
               "on a path that can raise (or return) before release — "
               "use `with` so every path releases")
    doc = (
        "Path-sensitive companion to TPL002: a may-analysis over the "
        "function CFG tracks which bare `.acquire()` calls are still "
        "unreleased at each node, including the exception edges the "
        "lexical check cannot see. A threading lock provably held when "
        "control reaches an `await` parks the loop thread with the "
        "mutex locked; any lock still held at the raise-exit leaks "
        "permanently when an exception unwinds before the `.release()`; "
        "one still held at a `return` means some branch skips the "
        "release. `with`-based acquisitions are exempt (the context "
        "manager releases on all paths), as are functions that never "
        "release the lock at all (the cross-function hand-off protocol)."
    )
    example = """\
def charge(self, n):
    self._mu.acquire()
    self._balance -= n        # raises on bad n -> _mu locked forever
    self._mu.release()
"""
    fix = ("`with self._mu:` — or release in a `finally`; never hold a "
           "threading lock across an `await`.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        kinds = _lock_kinds(module)
        if not kinds:
            return
        for fn in ast.walk(module.tree):
            if isinstance(fn, _FUNC_NODES):
                yield from self._check_fn(module, kinds, fn)

    def _check_fn(self, module: ModuleInfo, kinds: dict[str, str],
                  fn: ast.FunctionDef | ast.AsyncFunctionDef) -> \
            Iterator[Finding]:
        # Pre-scan this function only: acquire sites and released names.
        acquire_sites: dict[tuple[str, int], ast.Call] = {}
        released: set[str] = set()
        for sub in ast.walk(fn):
            if module.enclosing_function(sub) is not fn:
                continue
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute):
                name = _receiver_name(sub)
                if name in kinds:
                    if sub.func.attr == "acquire":
                        acquire_sites[(name, sub.lineno)] = sub
                    elif sub.func.attr == "release":
                        released.add(name)
        if not acquire_sites and not released:
            return

        cfg = cfg_for(module, fn)
        res = solve(cfg, _HeldMay(kinds))

        def in_value(node: Node) -> frozenset:
            pair = res.get(node.index)
            return pair[0] if pair and pair[0] is not None else frozenset()

        # -- bare thread-lock holds across a suspension point
        reported_awaits: set[tuple[str, int]] = set()
        for node in cfg.await_nodes():
            for name, kind, origin, line in sorted(in_value(node)):
                if origin != "bare" or kind != "thread":
                    continue
                site = (name, line)
                if site in reported_awaits:
                    continue
                reported_awaits.add(site)
                yield self.finding(
                    module, node.stmt if node.stmt is not None else fn,
                    f"threading lock `{name}` (bare .acquire() at line "
                    f"{line}) is still held when this path reaches the "
                    f"`await` at line {node.lineno} — the loop thread "
                    "parks with the mutex locked; release before "
                    "awaiting, or use `with` + asyncio.to_thread",
                )

        # -- bare acquisitions that leak on some path
        leak_exc = {(e[0], e[3]) for e in in_value(cfg.raise_exit)
                    if e[2] == "bare"}
        leak_ret = {(e[0], e[3]) for e in in_value(cfg.exit)
                    if e[2] == "bare"}
        for (name, line) in sorted(leak_exc | leak_ret):
            if name not in released:
                continue  # hand-off protocol: released elsewhere
            site = acquire_sites.get((name, line))
            if site is None:
                continue
            if (name, line) in leak_exc and (name, line) in leak_ret:
                how = ("on some paths — including an exception unwinding "
                       "before the release")
            elif (name, line) in leak_exc:
                how = ("when an exception is raised between the acquire "
                       "and the release")
            else:
                how = "on an early-return path that skips the release"
            yield self.finding(
                module, site,
                f"lock `{name}` acquired here is left locked {how} — "
                "every later acquirer deadlocks; use `with {0}:` or "
                "release in a `finally`".format(name),
            )
