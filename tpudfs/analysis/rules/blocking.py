"""TPL001 — blocking call inside ``async def``.

A single ``time.sleep`` or synchronous HTTP/subprocess call on the event
loop stalls every in-flight RPC on that process: heartbeats miss, Raft
elections fire, replication pipelines wedge. Blocking work belongs behind
``await asyncio.to_thread(...)`` / ``loop.run_in_executor`` or an async
equivalent (``await asyncio.sleep``, aiohttp).

Sync ``def``s nested inside an ``async def`` are exempt — that is exactly
the ``to_thread`` closure pattern.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

#: Exact dotted names that block the calling thread.
BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "os.system": "use `asyncio.create_subprocess_shell`",
    "os.wait": "use `asyncio.create_subprocess_exec` + `await proc.wait()`",
    "subprocess.run": "use `asyncio.create_subprocess_exec`",
    "subprocess.call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec`",
    "subprocess.getoutput": "use `asyncio.create_subprocess_shell`",
    "subprocess.getstatusoutput": "use `asyncio.create_subprocess_shell`",
    "socket.create_connection": "use `asyncio.open_connection`",
    "urllib.request.urlopen": "use aiohttp",
}

#: Any call into these modules is synchronous network I/O.
BLOCKING_PREFIXES = {
    "requests.": "use aiohttp (requests is fully synchronous)",
}

#: Methods that do synchronous file I/O when invoked on pathlib.Path-like
#: receivers. Attribute calls are receiver-typed only by convention, so this
#: list is deliberately short and unambiguous.
BLOCKING_METHODS = {
    "read_bytes", "read_text", "write_bytes", "write_text",
}


def blocking_call(node: ast.Call) -> tuple[str, str] | None:
    """(what, hint) when ``node`` is a call that blocks the calling thread,
    per the tables above. Shared with TPL010's transitive analysis."""
    name = dotted_name(node.func)
    if name in BLOCKING_CALLS:
        return name, BLOCKING_CALLS[name]
    if name:
        for prefix, hint in BLOCKING_PREFIXES.items():
            if name.startswith(prefix):
                return name, hint
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in BLOCKING_METHODS:
        return f".{node.func.attr}(...)", "wrap in `await asyncio.to_thread(...)`"
    return None


@register
class BlockingCallInAsync(Rule):
    id = "TPL001"
    name = "blocking-call-in-async"
    summary = ("time.sleep / sync I/O / subprocess inside `async def` stalls "
               "the event loop (heartbeats, elections, replication)")
    doc = (
        "Everything in tpudfs shares one event loop per process: Raft "
        "ticks, heartbeats, RPC dispatch, replication pipelines. One "
        "blocking call in any coroutine freezes all of them — a 200ms "
        "disk read in a handler delays every election timer on the node. "
        "The rule flags known-blocking leaves (time.sleep, requests, "
        "subprocess, sync file I/O methods) lexically inside `async def`. "
        "Sync `def`s nested in a coroutine are exempt: that is the "
        "to_thread worker idiom."
    )
    example = """\
async def pump(path):
    time.sleep(0.5)            # stalls every coroutine on the loop
    return path.read_bytes()   # sync disk I/O on the loop
"""
    fix = ("`await asyncio.sleep(...)` for delays; wrap blocking work in "
           "`await asyncio.to_thread(fn, ...)` (or an executor).")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if not module.in_async_context(node):
                continue
            hit = blocking_call(node)
            if hit is None:
                continue
            what, hint = hit
            yield self.finding(
                module, node,
                f"blocking call `{what}` in async function; {hint}",
            )
