"""TPL030-TPL034 — tpuperf: hot-path copy and chattiness rules.

BENCH r01-r05 ended with the read path at ~1 GB/s and the write pipeline
at 0.025 GB/s. The difference is not architecture — both paths move the
same frames through the same transports — it is a layer of Python-level
de-optimisations no correctness rule sees: a slice that memcpys every
block, a ``b"".join`` over a batch the socket could scatter, one awaited
round-trip per frame, the same buffer CRC'd twice by adjacent layers.
These five rules put the analyzer on that money path:

- **TPL030** — O(n) buffer copy (slice / concat / ``bytes()`` /
  ``join``) inside a hot-path loop where a ``memoryview`` (or a scatter
  list handed to ``writelines``) provably suffices for every consumer.
- **TPL031** — quadratic ``buf += chunk`` accumulation of immutable
  ``bytes`` in a loop (each += re-copies the prefix; ``bytearray`` or a
  list + single ``join`` is linear).
- **TPL032** — an awaited RPC/IO call per iteration of a hot loop with
  no batching, gather, or pipelining between iterations — the
  sequential-await chain that serializes N round-trips.
- **TPL033** — redundant checksum: a CRC computed over a buffer whose
  current value already has a CRC on some path in (directly, or because
  a callee checksums the same argument). Reuses the TPL013 idea of
  walking resolved call edges instead of trusting names.
- **TPL034** — synchronous serialization / compression / slow digest on
  the event loop in a hot path, size-aware: only flagged when an
  argument has byte-buffer provenance (headers and tiny control dicts
  pack in microseconds; payloads do not).

All five key off :mod:`tpudfs.analysis.hotpath` (reachability from the
bench/data-plane roots + effective loop depth) and
:mod:`tpudfs.analysis.bufferflow` (per-node buffer kinds and CRC facts
on the fixed-point solver), so a copy in a config loader stays silent
while the same copy per frame of a chain write is a finding.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.bufferflow import (
    CRC_CALLS,
    PAYLOAD_NAME_RE,
    buffer_flow,
    env_from,
    crc_names,
    is_copy_expr,
    kind_of,
)
from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.cfg import cfg_for
from tpudfs.analysis.hotpath import hot_paths, loop_depth_at
from tpudfs.analysis.linter import (Finding, ProjectRule, profile_units,
                                    register)

#: Callees for which passing a memoryview instead of a fresh bytes copy
#: is known-safe: checksums, length, socket/file writes, struct/msgpack
#: packing (msgpack bin-packs any buffer), list collection for
#: writelines/join, numpy ingestion.
_MV_SAFE_CALLEES = {
    "crc32c", "crc32c_chunks", "crc64nvme", "len", "min", "max",
    "write", "writelines", "sendall", "send", "update", "pack", "packb",
    "memoryview", "bytearray", "bytes", "frombuffer", "append", "extend",
    "isinstance", "enumerate", "range",
}

#: Slices with constant bounds at or under this are header peeks /
#: fixed-size prefixes — O(1)-ish, not the per-frame memcpy this rule
#: hunts.
_SMALL_SLICE = 4096

#: Await-call names that initiate a round-trip / offload per iteration.
_RPC_IO_NAMES = {"call", "to_thread", "run_in_executor", "request",
                 "fetch", "execute", "submit"}
_RPC_IO_PREFIXES = ("rpc_", "read_", "write_", "_read_", "_write_",
                    "send_", "recv_", "_execute", "replicate",
                    "publish", "_call", "_data_call")

#: Names whose presence in a loop body is batching/pipelining evidence.
_BATCH_NAMES = {"gather", "wait", "as_completed", "create_task",
                "ensure_future", "TaskGroup", "start_soon"}

#: Receivers that are ordered streams: per-iteration awaits on them are
#: sequential by nature (a TCP stream cannot be gathered).
_STREAM_RECEIVERS = {"r", "w", "reader", "writer", "stream", "sock",
                     "conn", "resp", "response"}

#: Serialization / compression / slow-digest callees for TPL034. crc32c
#: is deliberately absent (native-accelerated, sub-ms per MiB);
#: crc64nvme's Python fallback is the documented slow path.
_SERIALIZE_CALLEES = {"packb", "unpackb", "dumps", "loads", "compress",
                      "decompress", "crc64nvme", "md5", "sha1", "sha256",
                      "blake2b", "b64encode", "b64decode"}


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _hot_functions(
    project: Project, rule_id: str | None = None
) -> Iterator[tuple[FunctionInfo, int]]:
    """Hot functions with their entry loop depth. With ``rule_id`` set
    and ``tpulint --profile`` active, each function's analysis time (the
    caller's loop body) is billed to it in ``linter.UNIT_TIMINGS``."""
    hp = hot_paths(project)
    fns = ((fn, hp.entry_depth(fn))
           for fn in project.functions.values() if hp.is_hot(fn))
    yield from profile_units(rule_id, fns, lambda pair: pair[0].qualname)


def _own_nodes(fn: FunctionInfo):
    """CFG nodes of ``fn`` (its own statements; nested defs are their
    own functions and analyze separately)."""
    return cfg_for(fn.module, fn.node).nodes


def _in_env(fn: FunctionInfo, node):
    flow = buffer_flow(fn.module, fn.node)
    in_facts, _ = flow.get(node.index, (None, None))
    return env_from(in_facts), in_facts


def _const_small_slice(sl: ast.Slice) -> bool:
    lower = 0
    if sl.lower is not None:
        if not (isinstance(sl.lower, ast.Constant)
                and isinstance(sl.lower.value, int)):
            return False
        lower = sl.lower.value
    if sl.upper is None:
        return False
    if not (isinstance(sl.upper, ast.Constant)
            and isinstance(sl.upper.value, int)):
        return False
    return 0 <= sl.upper.value - lower <= _SMALL_SLICE


class _MvSafety:
    """Answers "would a memoryview work everywhere this value flows?" —
    AST-level consumer check, with one-hop-per-call recursion into
    resolved project-internal callees' parameter uses."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self._param_memo: dict[tuple[int, str], bool] = {}

    def expr_safe(self, module, expr: ast.AST, depth: int = 0) -> bool:
        """True when the immediate consumer of ``expr`` accepts any
        buffer-protocol object."""
        parent = module.parent(expr)
        if isinstance(parent, ast.Call) and expr in parent.args:
            return self._call_arg_safe(module, parent, expr, depth)
        if isinstance(parent, ast.Subscript):
            return True  # further slicing/indexing works on memoryview
        if isinstance(parent, (ast.Compare, ast.UnaryOp)):
            return True  # truthiness / equality work via the buffer len
        if isinstance(parent, (ast.IfExp, ast.If, ast.While)) \
                and expr is parent.test:
            return True  # bare truthiness test: memoryview has __len__
        if isinstance(parent, ast.Assign):
            targets = [t for t in parent.targets if isinstance(t, ast.Name)]
            if len(targets) == len(parent.targets) and targets:
                fn = module.enclosing_function(expr)
                return fn is not None and all(
                    self._name_uses_safe(module, fn, t.id, parent, depth)
                    for t in targets)
            return False
        if isinstance(parent, ast.Dict):
            # Stored as a dict value: our serializers (msgpack bin),
            # transports (writelines), and caches all take buffer-
            # protocol objects; the store itself copies nothing.
            return expr in parent.values
        return False

    def _name_uses_safe(self, module, fn: ast.AST, name: str,
                        defining: ast.AST, depth: int) -> bool:
        """Every Load of ``name`` inside ``fn`` (outside the defining
        assignment) must itself be a memoryview-safe consumer."""
        if depth >= 3:
            return False
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)):
                continue
            if module.enclosing_function(n) is not fn:
                continue
            if any(anc is defining for anc in module.ancestors(n)):
                continue
            if not self.expr_safe(module, n, depth + 1):
                return False
        return True

    def _call_arg_safe(self, module, call: ast.Call, arg: ast.AST,
                       depth: int) -> bool:
        name = _call_name(call)
        if name in _MV_SAFE_CALLEES or name in CRC_CALLS:
            return True
        if depth >= 3:
            return False
        # Resolved internal callee: safe iff the receiving parameter is
        # itself only used in memoryview-safe ways.
        fn = self._enclosing_info(module, call)
        if fn is None:
            return False
        for edge in fn.calls:
            if edge.site is not call:
                continue
            callee = edge.callee
            param = self._param_for_arg(callee, call, arg, edge.kind)
            if param is None:
                return False
            return self._param_safe(callee, param, depth + 1)
        return False

    def _enclosing_info(self, module, node) -> FunctionInfo | None:
        fn_node = module.enclosing_function(node)
        if fn_node is None:
            return None
        return self.project.enclosing_function_info(module, node)

    @staticmethod
    def _param_for_arg(callee: FunctionInfo, call: ast.Call, arg: ast.AST,
                       kind: str) -> str | None:
        args = list(call.args)
        if kind == "thread" and args:
            args = args[1:]  # to_thread(fn, *args)
        try:
            pos = args.index(arg)
        except ValueError:
            return None
        params = [a.arg for a in callee.node.args.args]
        if params and params[0] in ("self", "cls"):
            pos += 1
        if pos < len(params):
            return params[pos]
        return None

    def _param_safe(self, callee: FunctionInfo, param: str,
                    depth: int) -> bool:
        key = (id(callee.node), param)
        memo = self._param_memo.get(key)
        if memo is not None:
            return memo
        self._param_memo[key] = True  # cycle guard: optimistic
        module = callee.module
        safe = True
        for node in ast.walk(callee.node):
            if isinstance(node, ast.Name) and node.id == param \
                    and isinstance(node.ctx, ast.Load) \
                    and module.enclosing_function(node) is callee.node:
                if not self.expr_safe(module, node, depth):
                    safe = False
                    break
        self._param_memo[key] = safe
        return safe


@register
class HotLoopCopy(ProjectRule):
    id = "TPL030"
    name = "hot-loop-buffer-copy"
    summary = ("O(n) buffer copy (slice/concat/`bytes()`/`join`) inside "
               "a hot-path loop where a `memoryview` or scatter list "
               "suffices — memcpy per frame is the write-pipeline gap")
    doc = (
        "`data[off:off+n]` on `bytes` memcpys n bytes; per block of a "
        "chain write that is the whole payload copied again before it "
        "even reaches the socket. On the hot paths (bench/data-plane "
        "reachability with loop depth from the CFG) this rule flags "
        "slice, concat, `bytes()` and `b''.join` copies whose consumers "
        "all accept buffer-protocol objects — checksums, socket "
        "writes/writelines, msgpack bin packing, further slicing — so "
        "`memoryview(data)[off:off+n]` (or handing the parts list to "
        "`writelines`) is a drop-in. Small constant-bound slices "
        "(header peeks) and copies whose value escapes to unknown "
        "consumers stay silent."
    )
    example = """\
while offset < len(data):                  # hot write loop
    piece = data[offset:offset + block]    # memcpys every block
    await write_block(piece, crc32c(piece))
    offset += block
"""
    fix = ("Slice a `memoryview(data)` once outside the loop: "
           "`view = memoryview(data); piece = view[off:off+n]` — "
           "checksums, msgpack and socket writes all take it unchanged.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hp = hot_paths(project)
        safety = _MvSafety(project)
        for fn, _entry in _hot_functions(project, self.id):
            module = fn.module
            seen: set[tuple[int, int]] = set()
            for node in _own_nodes(fn):
                eff = hp.effective_depth(fn, node.loop_depth)
                env, _ = _in_env(fn, node)
                for top in node.exprs():
                    for expr in ast.walk(top):
                        label = self._copy_label(module, expr, env)
                        if label is None:
                            continue
                        batch_join = (
                            label == "join"
                            and self._loop_accumulated(module, fn, expr))
                        if eff < 1 and not batch_join:
                            continue
                        key = (getattr(expr, "lineno", 0),
                               getattr(expr, "col_offset", 0))
                        if key in seen:
                            continue
                        if not safety.expr_safe(module, expr):
                            continue
                        seen.add(key)
                        if eff >= 1:
                            msg = (
                                f"O(n) {label} copy in a hot loop "
                                f"(effective depth {eff}) in "
                                f"`{fn.short()}`; every consumer accepts "
                                "a buffer view — use `memoryview` "
                                "slicing (or pass the parts list to "
                                "`writelines`) instead of copying per "
                                "iteration")
                        else:
                            msg = (
                                "`join` flattens a batch accumulated in "
                                f"a loop in `{fn.short()}` — the whole "
                                "batch is re-copied once more; hand the "
                                "parts list to the transport "
                                "(`writelines`/scatter framing) instead")
                        yield self.finding(module, expr, msg)

    @staticmethod
    def _loop_accumulated(module, fn: FunctionInfo, expr: ast.AST) -> bool:
        """``b"".join(parts)`` where ``parts`` is ``.append``ed inside a
        loop of the same function: the join re-copies the entire batch
        the loop just assembled, even when the join itself sits after
        the loop at depth 0."""
        if not (isinstance(expr, ast.Call) and expr.args
                and isinstance(expr.args[0], ast.Name)):
            return False
        name = expr.args[0].id
        for n in ast.walk(fn.node):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("append", "extend") \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == name:
                cur = module.parent(n)
                while cur is not None and cur is not fn.node:
                    if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                        return True
                    cur = module.parent(cur)
        return False

    @staticmethod
    def _copy_label(module, expr: ast.AST, env) -> str | None:
        label = is_copy_expr(expr, env)
        if label is None:
            return None
        if label == "slice":
            if not isinstance(expr.ctx, ast.Load):
                return None
            if _const_small_slice(expr.slice):
                return None
        if label == "concat":
            # `buf = buf + chunk` is TPL031's quadratic accumulation;
            # don't double-report the same expression.
            parent = module.parent(expr)
            if isinstance(parent, ast.Assign) \
                    and isinstance(expr.left, ast.Name) \
                    and any(isinstance(t, ast.Name)
                            and t.id == expr.left.id
                            for t in parent.targets):
                return None
        return label


@register
class QuadraticAccumulation(ProjectRule):
    id = "TPL031"
    name = "quadratic-bytes-accumulation"
    summary = ("`buf += chunk` on immutable `bytes` in a loop re-copies "
               "the whole prefix every iteration — O(n^2) accumulation; "
               "use `bytearray` or collect parts and `join` once")
    doc = (
        "`bytes` is immutable: `buf += chunk` allocates a fresh object "
        "and memcpys len(buf) + len(chunk) bytes, so accumulating n "
        "chunks costs O(n^2) — 256 frames of 64 KiB copy two gigabytes. "
        "The rule uses buffer provenance to fire only when the target "
        "may hold `bytes` (bytearray += is amortized O(1) and stays "
        "silent) and only inside a loop in a hot function, where the "
        "accumulation actually multiplies."
    )
    example = """\
frame = b""
while len(frame) < total:       # hot reassembly loop
    frame += await read_chunk() # re-copies the prefix every time
"""
    fix = ("Accumulate into a `bytearray` (then `bytes(buf)` once if an "
           "immutable result is needed), or append chunks to a list and "
           "`b''.join(parts)` after the loop.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hp = hot_paths(project)
        for fn, _entry in _hot_functions(project, self.id):
            module = fn.module
            for node in _own_nodes(fn):
                if node.loop_depth < 1:
                    # The accumulator must live across iterations of a
                    # loop in THIS function to go quadratic.
                    continue
                env, _ = _in_env(fn, node)
                for top in node.exprs():
                    hit = self._accumulation(top, env)
                    if hit is None:
                        continue
                    target, form = hit
                    yield self.finding(
                        module, top,
                        f"quadratic accumulation `{target} {form}` on "
                        f"immutable bytes in a loop in `{fn.short()}` — "
                        "each iteration re-copies the whole prefix; use "
                        "a `bytearray` or collect parts and `join` once",
                    )

    @staticmethod
    def _accumulation(stmt: ast.AST, env) -> tuple[str, str] | None:
        if isinstance(stmt, ast.AugAssign) and isinstance(stmt.op, ast.Add) \
                and isinstance(stmt.target, ast.Name):
            name = stmt.target.id
            kinds = env.get(name, set())
            if "bytes" in kinds and "bytearray" not in kinds:
                return name, "+= ..."
            return None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.BinOp) \
                and isinstance(stmt.value.op, ast.Add) \
                and isinstance(stmt.value.left, ast.Name) \
                and stmt.value.left.id == stmt.targets[0].id:
            name = stmt.targets[0].id
            kinds = env.get(name, set())
            if "bytes" in kinds and "bytearray" not in kinds \
                    and kind_of(stmt.value.right, env):
                return name, "= " + name + " + ..."
        return None


@register
class SequentialAwaitPerFrame(ProjectRule):
    id = "TPL032"
    name = "sequential-await-in-hot-loop"
    summary = ("awaited RPC/IO per iteration of a hot loop with no "
               "batching/gather/pipelining — N serial round-trips where "
               "one gathered batch would do")
    doc = (
        "A loop that awaits a round-trip per item serializes N network "
        "(or thread-pool) latencies; the reads of a 256-block batch "
        "take 256x the latency of one. Detection is on the CFG: a loop "
        "in a hot async function whose body awaits an initiating RPC/IO "
        "call, with no batching evidence — no gather/create_task/"
        "TaskGroup in the body, no inner batch-building loop (the "
        "group-commit drain shape), no normal-path break/return (the "
        "retry/failover shape tries alternatives, it does not iterate "
        "work), and not a pure stream-consumer await (an ordered TCP "
        "stream cannot be gathered). An unconditional `await w.drain()` "
        "per frame counts — flushing every frame is the ack-chattiness "
        "this rule exists for; a watermark-guarded drain does not."
    )
    example = """\
for block_id in req["block_ids"]:          # hot batch-read handler
    data = await asyncio.to_thread(store.read, block_id)
    out.append(data)                       # N serial disk round-trips
"""
    fix = ("Issue the calls concurrently and gather: `await asyncio."
           "gather(*(asyncio.to_thread(store.read, b) for b in ids))` — "
           "or pipeline iterations with create_task/TaskGroup.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hp = hot_paths(project)
        for fn, _entry in _hot_functions(project, self.id):
            if not fn.is_async:
                continue
            module = fn.module
            for loop in ast.walk(fn.node):
                if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                    continue
                if module.enclosing_function(loop) is not fn.node:
                    continue
                hit = self._chatty_await(module, fn, loop)
                if hit is not None:
                    await_node, what = hit
                    yield self.finding(
                        module, await_node,
                        f"`{fn.short()}` awaits `{what}` on every "
                        "iteration of a hot loop with no batching or "
                        "pipelining between iterations — gather the "
                        "calls, pipeline with create_task, or batch "
                        "the flush behind a watermark",
                    )

    def _chatty_await(self, module, fn: FunctionInfo,
                      loop: ast.AST) -> tuple[ast.AST, str] | None:
        body_nodes = [n for stmt in loop.body for n in ast.walk(stmt)]
        # Batching / pipelining evidence exempts the whole loop.
        for n in body_nodes:
            if isinstance(n, ast.Call) and _call_name(n) in _BATCH_NAMES:
                return None
            if isinstance(n, ast.Attribute) and n.attr in _BATCH_NAMES:
                return None
        # An inner loop is the drain-batch shape: each awaited call
        # covers many gathered items.
        for stmt in loop.body:
            for n in ast.walk(stmt):
                if n is not loop and isinstance(
                        n, (ast.While, ast.For, ast.AsyncFor)):
                    return None
        # Normal-path break/return = retry/failover over alternatives.
        for n in body_nodes:
            if isinstance(n, (ast.Break, ast.Return)) \
                    and not self._under_except(module, n, loop) \
                    and module.enclosing_function(n) is fn.node:
                return None

        candidate: tuple[ast.AST, str] | None = None
        for n in body_nodes:
            if not isinstance(n, ast.Await):
                continue
            if module.enclosing_function(n) is not fn.node:
                continue
            call = n.value
            if isinstance(call, ast.Call) \
                    and _call_name(call) == "wait_for" and call.args:
                inner = call.args[0]
                call = inner if isinstance(inner, ast.Call) else call
            if not isinstance(call, ast.Call):
                continue
            name = _call_name(call)
            if name in ("sleep",):
                continue
            if name in ("drain", "flush"):
                if self._guarded(module, n, loop):
                    continue
                return n, name + "()"
            if self._stream_consumer(call, loop):
                continue
            if name in _RPC_IO_NAMES \
                    or name.startswith(_RPC_IO_PREFIXES):
                candidate = (n, name + "()")
        return candidate

    @staticmethod
    def _under_except(module, node: ast.AST, stop: ast.AST) -> bool:
        cur = module.parent(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, ast.ExceptHandler):
                return True
            cur = module.parent(cur)
        return False

    @staticmethod
    def _guarded(module, node: ast.AST, loop: ast.AST) -> bool:
        """True when an `if` between the await and the loop gates it —
        the flush-on-watermark idiom."""
        cur = module.parent(node)
        while cur is not None and cur is not loop:
            if isinstance(cur, ast.If):
                return True
            cur = module.parent(cur)
        return False

    @staticmethod
    def _stream_consumer(call: ast.Call, loop: ast.AST) -> bool:
        """Reads from an ordered stream object: sequential by nature."""
        name = _call_name(call)
        reads_input = name.startswith(("read", "_read", "recv", "_recv"))
        if not reads_input:
            return False
        if isinstance(loop, ast.While):
            return True  # serve/consumer loop: input arrival is the clock
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in _STREAM_RECEIVERS:
            return True
        return False


@register
class RedundantChecksum(ProjectRule):
    id = "TPL033"
    name = "redundant-checksum"
    summary = ("CRC computed over a buffer whose current value already "
               "has a CRC on this path (directly or via a callee) — two "
               "O(n) passes where a combine/fold gives one")
    doc = (
        "crc32c over a buffer is an O(n) pass; two layers each taking "
        "their own pass over the same unmodified bytes doubles the "
        "checksum cost of every write. The buffer-provenance dataflow "
        "tracks a `crc`-computed fact per name, killed on reassignment "
        "or mutation; a second CRC call over the same name — or passing "
        "it to a resolved callee that (transitively) checksums that "
        "parameter, the TPL013-style walk — fires on the path where "
        "both passes happen. `crc32c_combine_chunks` folds per-chunk "
        "CRCs into the whole-buffer CRC, so one pass can serve both "
        "verification and sidecar generation."
    )
    example = """\
actual = crc32c(data)              # pass 1: whole-buffer verify
if actual != expected:
    return reject()
await store.write(block_id, data)  # pass 2: write_staged re-CRCs data
"""
    fix = ("Compute per-chunk CRCs once and fold them: `crcs = "
           "crc32c_chunks(data); crc32c_combine_chunks(crcs, CHUNK) == "
           "expected` — then hand the chunk CRCs to the layer that "
           "needed them.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hp = hot_paths(project)
        memo: dict[FunctionInfo, frozenset[str]] = {}

        def checksummed_params(fn: FunctionInfo,
                               stack: set[FunctionInfo]) -> frozenset[str]:
            """Parameter names ``fn`` (transitively) computes a CRC over."""
            if fn in memo:
                return memo[fn]
            if fn in stack:
                return frozenset()
            stack.add(fn)
            params = {a.arg for a in fn.node.args.args}
            out: set[str] = set()
            for n in ast.walk(fn.node):
                if isinstance(n, ast.Call) and _call_name(n) in CRC_CALLS \
                        and n.args and isinstance(n.args[0], ast.Name) \
                        and n.args[0].id in params \
                        and not _compute_if_absent(fn.module, n):
                    out.add(n.args[0].id)
            for edge in fn.calls:
                if not isinstance(edge.site, ast.Call):
                    continue
                callee_sums = checksummed_params(edge.callee, stack)
                if not callee_sums:
                    continue
                for arg_name, param in _positional_map(edge):
                    if param in callee_sums and arg_name in params:
                        out.add(arg_name)
            stack.discard(fn)
            memo[fn] = frozenset(out)
            return memo[fn]

        for fn, _entry in _hot_functions(project, self.id):
            module = fn.module
            flow = buffer_flow(module, fn.node)
            edges_by_site = {id(e.site): e for e in fn.calls}
            reported: set[tuple[str, int]] = set()
            for node in _own_nodes(fn):
                in_facts, _ = flow.get(node.index, (None, None))
                already = crc_names(in_facts)
                if not already:
                    continue
                for top in node.exprs():
                    for n in ast.walk(top):
                        if not isinstance(n, ast.Call):
                            continue
                        hit = self._second_pass(
                            module, n, already, edges_by_site,
                            checksummed_params)
                        if hit is None:
                            continue
                        var, how = hit
                        key = (var, getattr(n, "lineno", 0))
                        if key in reported:
                            continue
                        reported.add(key)
                        yield self.finding(
                            module, n,
                            f"`{var}` already has a CRC computed on this "
                            f"path in `{fn.short()}`, and {how} takes "
                            "another O(n) pass over the same bytes — "
                            "compute chunk CRCs once and fold with "
                            "`crc32c_combine_chunks`",
                        )

    @staticmethod
    def _second_pass(module, call: ast.Call, already: set[str],
                     edges_by_site,
                     checksummed_params) -> tuple[str, str] | None:
        name = _call_name(call)
        if name in CRC_CALLS and call.args \
                and isinstance(call.args[0], ast.Name) \
                and call.args[0].id in already \
                and not _compute_if_absent(module, call):
            return call.args[0].id, f"`{name}(...)`"
        edge = edges_by_site.get(id(call))
        if edge is None:
            return None
        callee_sums = checksummed_params(edge.callee, set())
        if not callee_sums:
            return None
        for arg_name, param in _positional_map(edge):
            if param in callee_sums and arg_name in already:
                return arg_name, f"`{edge.callee.short()}(...)`"
        return None


def _compute_if_absent(module, call: ast.Call) -> bool:
    """`crc if crc is not None else crc32c(data)` — or the statement
    form, `if crcs is None: crcs = crc32c_chunks(data)` — computes the
    CRC only when the caller did not supply one; on the supplied path
    there is exactly one pass, so this is not redundancy."""
    cur = module.parent(call)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        test = cur.test if isinstance(cur, (ast.IfExp, ast.If)) else None
        if isinstance(test, ast.Compare) \
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in [test.left, *test.comparators]):
            return True
        cur = module.parent(cur)
    return False


def _positional_map(edge) -> list[tuple[str, str]]:
    """(caller arg name, callee param name) pairs for plain positional
    Name arguments of a resolved call edge, self-offset and
    to_thread-shift aware."""
    call = edge.site
    if not isinstance(call, ast.Call):
        return []
    args = list(call.args)
    if edge.kind == "thread" and args:
        fname = _call_name(call)
        args = args[2:] if fname == "run_in_executor" else args[1:]
    params = [a.arg for a in edge.callee.node.args.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    out = []
    for i, a in enumerate(args):
        if isinstance(a, ast.Name) and i < len(params):
            out.append((a.id, params[i]))
    return out


@register
class SyncSerializationOnLoop(ProjectRule):
    id = "TPL034"
    name = "sync-serialization-on-loop"
    summary = ("synchronous serialization/compression/slow digest of a "
               "byte buffer on the event loop in a hot path — O(n) CPU "
               "that stalls every other connection")
    doc = (
        "TPL010 catches blocking *calls* (sleep, sync I/O); this is its "
        "size-aware sibling for blocking *CPU*: msgpack/pickle/json "
        "serialization, zlib-family compression, md5/sha digests and "
        "the pure-Python crc64nvme fallback are all O(n) passes that "
        "hold the event loop for milliseconds per megabyte. The rule "
        "fires only in hot async functions and only when an argument "
        "has byte-buffer provenance from the dataflow — packing a "
        "20-byte header dict is free and stays silent; packing the "
        "payload is not."
    )
    example = """\
async def send_block(w, data: bytes):       # hot data-plane send
    w.write(zlib.compress(data))            # O(n) CPU on the loop
    await w.drain()
"""
    fix = ("Offload the O(n) pass: `await asyncio.to_thread(zlib."
           "compress, data)` — or move payload bytes outside the "
           "serialized envelope entirely (scatter framing).")

    def check_project(self, project: Project) -> Iterator[Finding]:
        hp = hot_paths(project)
        for fn, _entry in _hot_functions(project, self.id):
            if not fn.is_async:
                continue
            module = fn.module
            for node in _own_nodes(fn):
                env, _ = _in_env(fn, node)
                for top in node.exprs():
                    for n in ast.walk(top):
                        if not isinstance(n, ast.Call):
                            continue
                        name = _call_name(n)
                        if name not in _SERIALIZE_CALLEES:
                            continue
                        if module.enclosing_function(n) is not fn.node:
                            continue
                        if not self._buffer_arg(n, env):
                            continue
                        if self._offloaded(module, n):
                            continue
                        yield self.finding(
                            module, n,
                            f"`{name}(...)` serializes a byte buffer "
                            f"synchronously on the event loop in hot "
                            f"`{fn.short()}` — offload with "
                            "`asyncio.to_thread`, or keep payload bytes "
                            "out of the serialized envelope",
                        )

    @classmethod
    def _buffer_arg(cls, call: ast.Call, env) -> bool:
        return any(cls._payloadish(a, env) for a in call.args)

    @classmethod
    def _payloadish(cls, expr: ast.AST, env) -> bool:
        """Buffer provenance AND a payload-reading name somewhere in the
        expression. `unpackb(await r.readexactly(hlen))` has provenance
        but is a length-prefixed *header* read — without a payload name
        there is no evidence the buffer is O(payload)-sized."""
        if isinstance(expr, ast.Name):
            return bool(kind_of(expr, env)) \
                and PAYLOAD_NAME_RE.match(expr.id) is not None
        if isinstance(expr, ast.Await):
            return cls._payloadish(expr.value, env)
        if isinstance(expr, ast.Subscript):
            return cls._payloadish(expr.value, env)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return cls._payloadish(expr.left, env) \
                or cls._payloadish(expr.right, env)
        if isinstance(expr, ast.Dict):
            return any(v is not None and cls._payloadish(v, env)
                       for v in expr.values)
        return False

    @staticmethod
    def _offloaded(module, call: ast.Call) -> bool:
        """Already behind to_thread/run_in_executor at this site."""
        cur = module.parent(call)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(cur, ast.Call) \
                    and _call_name(cur) in ("to_thread", "run_in_executor"):
                return True
            cur = module.parent(cur)
        return False
