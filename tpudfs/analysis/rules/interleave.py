"""TPL050-TPL052 — protocol-ordering lints: the static half of tpusched.

The schedule explorer (``tpudfs/testing/vclock.py``) can only check the
interleavings a scenario drives; these rules enumerate, on the CFG, the
*shapes* that make an interleaving dangerous in the first place — the
await points where shared state can shear, the handler paths that can
double-respond or go silent, the retry loops that replay a
non-idempotent effect. Findings double as explorer targets: each one
names an await-crossing region worth a scenario.

- **TPL050 await-atomicity**: shared ``self``-state is read (a guard
  test, or a local bound from the attribute), an ``await`` suspends the
  task, and the same attribute is then mutated with no re-validation
  between the suspension and the write. Every other task ran in that
  window; the guard's truth and the local's value are stale.
- **TPL051 one-terminal-response**: a framed stream handler (the
  blockport ``(req, r, w)`` shape) must send exactly one terminal frame
  — an error frame or the final ack — per connection-preserving path.
  Zero leaves the peer waiting on a live socket; two desyncs framing for
  every later request on the pooled connection.
- **TPL052 retry-of-non-idempotent-op-without-fence**: a retry loop
  re-awaits a create/rename/complete-class mutation whose request
  carries no fence (etag / overwrite / token / txid / term). If attempt
  one applied and its ack was lost, the replay double-applies or
  misreports AlreadyExists/NotFound as failure.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.cfg import Node, cfg_for
from tpudfs.analysis.linter import Finding, ModuleInfo, Rule, register

def _walk_shallow(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested function bodies:
    a nested ``def`` statement DEFINES code, it doesn't run it."""
    work = [root]
    while work:
        node = work.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # yield the def itself, never its body
        work.extend(ast.iter_child_nodes(node))


#: Mutating method names on an attribute (``self.A.append(...)``).
_MUTATING_METHODS = {
    "append", "extend", "insert", "add", "update", "remove", "discard",
    "pop", "popitem", "clear", "setdefault", "appendleft", "popleft",
}


def _self_attr_reads(expr: ast.AST) -> set[str]:
    """Attributes of ``self`` loaded anywhere inside ``expr``."""
    out: set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load) \
                and isinstance(n.value, ast.Name) and n.value.id == "self":
            out.add(n.attr)
    return out


def _self_attr_of(node: ast.AST) -> str | None:
    """``self.A`` -> "A" for a bare attribute node."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _mutated_attrs(stmt: ast.AST) -> set[str]:
    """``self`` attributes a single statement's exprs mutate: assignment
    to ``self.A`` / ``self.A[...]``, augmented assignment, ``del``, or a
    mutating method call ``self.A.append(...)``."""
    out: set[str] = set()
    for n in _walk_shallow(stmt):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                out |= _target_attrs(t)
        elif isinstance(n, ast.Delete):
            for t in n.targets:
                out |= _target_attrs(t)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATING_METHODS:
            a = _self_attr_of(n.func.value)
            if a is not None:
                out.add(a)
    return out


def _target_attrs(t: ast.AST) -> set[str]:
    out: set[str] = set()
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            out |= _target_attrs(e)
        return out
    if isinstance(t, ast.Starred):
        return _target_attrs(t.value)
    if isinstance(t, ast.Subscript):
        a = _self_attr_of(t.value)
        if a is not None:
            out.add(a)
        return out
    a = _self_attr_of(t)
    if a is not None:
        out.add(a)
    return out


def _node_mutates(node: Node, attr: str) -> bool:
    return any(attr in _mutated_attrs(e) for e in node.exprs())


def _node_tests_attr(node: Node, attr: str) -> bool:
    """A test/compare over the attribute at this node — re-validation."""
    if node.kind in ("if_test", "while_test"):
        return any(attr in _self_attr_reads(e) for e in node.exprs())
    return False


def _shares_async_with(module: ModuleInfo, a: ast.AST, b: ast.AST) -> bool:
    """Both statements sit inside the SAME ``async with`` block: every
    other task that respects that lock is excluded from the window, which
    is the one re-validation-free shape that is actually safe."""
    anc_a = {id(n) for n in module.ancestors(a)
             if isinstance(n, ast.AsyncWith)}
    if not anc_a:
        return False
    return any(id(n) in anc_a for n in module.ancestors(b)
               if isinstance(n, ast.AsyncWith))


def _async_functions(module: ModuleInfo) -> Iterator[ast.AsyncFunctionDef]:
    for n in ast.walk(module.tree):
        if isinstance(n, ast.AsyncFunctionDef):
            yield n


@register
class AwaitAtomicity(Rule):
    id = "TPL050"
    name = "await-atomicity"
    summary = ("shared `self` state read before an await and mutated "
               "after it with no re-validation — every other task ran in "
               "that window, so the guard/local is stale at the write")
    doc = (
        "An `await` is a scheduling point: by the time the coroutine "
        "resumes, any other task may have mutated the object. A guard "
        "(`if not self.closed:`) or a local snapshot (`n = self.count`) "
        "taken before the await therefore proves nothing about the state "
        "the post-await write applies to — the classic check-then-act "
        "race, TOCTOU at event-loop granularity. The dataplane "
        "lost-wakeup commit-loop poll and the admission double-count "
        "both had this shape. Flagged on the CFG: a read of `self.A` "
        "(test or local-bind), an await-bearing node on the path, then a "
        "mutation of `self.A` (or a write of the stale local into it) "
        "with no re-test of `self.A` in between. Mutations inside the "
        "same `async with` lock block as the read stay silent — the "
        "lock excludes the interleaving."
    )
    example = """\
async def admit(self):
    if self.inflight < self.limit:        # guard read
        await self.backend.reserve()      # every task runs here
        self.inflight += 1                # stale guard: may overshoot
"""
    fix = ("Re-validate after the await (`if self.inflight >= self.limit: "
           "return` again), mutate BEFORE suspending and roll back on "
           "failure, or hold an `asyncio.Lock` across the whole "
           "check-then-act window.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for fn in _async_functions(module):
            cfg = cfg_for(module, fn)
            if not any(n.has_await for n in cfg.rpo()):
                continue
            yield from self._guarded_mutations(module, cfg)
            yield from self._stale_locals(module, cfg)

    # ------------------------------------------- guard ... await ... mutate

    def _guarded_mutations(self, module: ModuleInfo, cfg) -> Iterator[Finding]:
        for test in cfg.rpo():
            if test.kind not in ("if_test", "while_test"):
                continue
            attrs = set()
            for e in test.exprs():
                attrs |= _self_attr_reads(e)
            for attr in sorted(attrs):
                hit = self._first_unvalidated_mutation(test, attr)
                if hit is None:
                    continue
                if _shares_async_with(module, test.stmt, hit.stmt):
                    continue
                yield self.finding(
                    module, hit.stmt,
                    f"`self.{attr}` is mutated after an await on a path "
                    f"guarded by the `self.{attr}` test at line "
                    f"{test.lineno}, with no re-validation after the "
                    "suspension — the guard is stale by the time this "
                    "write runs")

    @staticmethod
    def _first_unvalidated_mutation(start: Node, attr: str) -> Node | None:
        """BFS from ``start``: does some path cross an await and then
        mutate ``attr`` before any re-test of ``attr``?"""
        seen: set[tuple[int, bool]] = set()
        work: list[tuple[Node, bool]] = [
            (s, start.has_await) for s, _k in start.succs]
        while work:
            node, crossed = work.pop()
            if crossed and _node_mutates(node, attr):
                return node
            if _node_tests_attr(node, attr):
                continue  # re-validated: this path is clean past here
            if not crossed and _node_mutates(node, attr):
                # Pre-await mutation re-establishes the state the
                # guard was about; stop to avoid flagging the idiom
                # "mutate first, then await".
                continue
            crossed = crossed or node.has_await
            key = (node.index, crossed)
            if key in seen:
                continue
            seen.add(key)
            for succ, _kind in node.succs:
                work.append((succ, crossed))
        return None

    # ------------------------------------------ local = self.A ... await ...

    def _stale_locals(self, module: ModuleInfo, cfg) -> Iterator[Finding]:
        for read in cfg.rpo():
            binds = self._local_binds(read)
            for local, attr, bind_stmt in binds:
                hit = self._stale_write(read, local, attr)
                if hit is None:
                    continue
                if _shares_async_with(module, bind_stmt, hit.stmt):
                    continue
                yield self.finding(
                    module, hit.stmt,
                    f"`self.{attr}` is overwritten from `{local}` — a "
                    f"snapshot taken at line {read.lineno} BEFORE an "
                    "await — losing every update that landed during the "
                    "suspension; re-read or re-validate "
                    f"`self.{attr}` after resuming")

    @staticmethod
    def _local_binds(node: Node) -> list[tuple[str, str, ast.AST]]:
        """``v = <expr reading self.A>`` bindings at this node."""
        out = []
        for e in node.exprs():
            if not (isinstance(e, ast.Assign) and len(e.targets) == 1
                    and isinstance(e.targets[0], ast.Name)):
                continue
            if isinstance(e.value, ast.Await):
                continue  # value produced after the suspension: fresh
            for attr in sorted(_self_attr_reads(e.value)):
                out.append((e.targets[0].id, attr, e))
        return out

    @staticmethod
    def _stale_write(start: Node, local: str, attr: str) -> Node | None:
        """BFS: an await, then ``self.A = f(local)`` (or ``self.A[k] =``)
        with no rebind of the local and no re-test of the attr between."""
        def writes_attr_from_local(node: Node) -> bool:
            for e in node.exprs():
                for n in ast.walk(e):
                    if not isinstance(n, (ast.Assign, ast.AugAssign)):
                        continue
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    if not any(attr in _target_attrs(t) for t in targets):
                        continue
                    if attr in _self_attr_reads(n.value):
                        # The new value incorporates the CURRENT state
                        # (e.g. `self.q = self.q[n:]`): that re-read is
                        # the re-validation this rule asks for.
                        continue
                    value_names = {
                        nm.id for nm in ast.walk(n.value)
                        if isinstance(nm, ast.Name)}
                    if local in value_names:
                        return True
            return False

        def rebinds_local(node: Node) -> bool:
            for e in node.exprs():
                for n in ast.walk(e):
                    if isinstance(n, ast.Assign):
                        for t in n.targets:
                            for nm in ast.walk(t):
                                if isinstance(nm, ast.Name) \
                                        and nm.id == local:
                                    return True
            return False

        seen: set[tuple[int, bool]] = set()
        work: list[tuple[Node, bool]] = [
            (s, start.has_await) for s, _k in start.succs]
        while work:
            node, crossed = work.pop()
            if crossed and writes_attr_from_local(node):
                return node
            if rebinds_local(node) or _node_tests_attr(node, attr):
                continue
            crossed = crossed or node.has_await
            key = (node.index, crossed)
            if key in seen:
                continue
            seen.add(key)
            for succ, _kind in node.succs:
                work.append((succ, crossed))
        return None


# --------------------------------------------------------------- TPL051


def _terminal_send_in(call: ast.Call, local_senders: set[str]) -> bool:
    """A call that puts a TERMINAL frame on the stream: an error helper,
    a locally-defined abort helper, or ``w.writelines(_pack_frame(h))``
    where ``h`` is a dict literal carrying ``final`` or ``ok: False``."""
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else \
        func.id if isinstance(func, ast.Name) else None
    if name in local_senders or name == "_stream_err":
        return True
    if not (isinstance(func, ast.Attribute) and func.attr == "writelines"
            and call.args):
        return False
    packed = call.args[0]
    if not (isinstance(packed, ast.Call) and isinstance(
            packed.func, (ast.Attribute, ast.Name))):
        return False
    pname = packed.func.attr if isinstance(packed.func, ast.Attribute) \
        else packed.func.id
    if pname != "_pack_frame" or not packed.args:
        return False
    header = packed.args[0]
    if not isinstance(header, ast.Dict):
        return False
    for k, v in zip(header.keys, header.values):
        if not isinstance(k, ast.Constant):
            continue
        if k.value == "final":
            return True
        if k.value == "ok" and isinstance(v, ast.Constant) \
                and v.value is False:
            return True
    return False


def _stream_handler_functions(module: ModuleInfo
                              ) -> Iterator[ast.AsyncFunctionDef]:
    """Blockport stream handlers: async, and the parameter list ends in
    the ``(..., r, w)`` connection pair (the framed-stream contract)."""
    for fn in _async_functions(module):
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        if len(params) >= 2 and params[-2:] == ["r", "w"]:
            yield fn


@register
class OneTerminalResponse(Rule):
    id = "TPL051"
    name = "one-terminal-response"
    summary = ("framed stream handler path can send two terminal frames "
               "(or report the connection as framed without sending one) "
               "— either desyncs the pooled blockport connection")
    doc = (
        "Blockport stream handlers own a pooled framed connection: the "
        "contract (tpudfs/common/blocknet.py) is exactly one terminal "
        "frame — an error frame or the final ack — per request, then "
        "`return True` iff the connection is still in frame-sync. A "
        "path that sends two terminal frames leaves the second one to "
        "be parsed as the NEXT request's response; a path that returns "
        "True without having sent any leaves the peer waiting forever "
        "on a connection the pool will happily reuse. Flagged on the "
        "CFG of every `(..., r, w)` handler: a terminal send reachable "
        "after another terminal send, and a `return True` reachable "
        "with no terminal send. `return False` paths (torn peer, "
        "connection discarded) are exempt — there is no reader left."
    )
    example = """\
async def rpc_thing(self, req, r, w):
    if bad(req):
        await self._stream_err(w, "INVALID_ARGUMENT", "bad")
        # missing return: falls through to the final ack below
    w.writelines(blocknet._pack_frame({"ok": True, "final": 1}, None))
    return True
"""
    fix = ("Return immediately after an error frame; funnel every exit "
           "through exactly one terminal send (the `_abort` helper "
           "pattern in chunkserver/service.py), and return False when "
           "the frame boundary is gone instead of responding.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for fn in _stream_handler_functions(module):
            local_senders = self._local_senders(fn)
            cfg = cfg_for(module, fn)
            send_nodes = [
                n for n in cfg.rpo()
                if any(self._node_sends(e, local_senders)
                       for e in n.exprs())
            ]
            if not send_nodes:
                continue
            yield from self._double_sends(module, cfg, send_nodes,
                                          local_senders)
            yield from self._silent_framed_returns(module, cfg,
                                                   local_senders)

    @staticmethod
    def _node_sends(expr: ast.AST, local_senders: set[str]) -> bool:
        return any(
            isinstance(n, ast.Call) and _terminal_send_in(n, local_senders)
            for n in _walk_shallow(expr))

    @staticmethod
    def _local_senders(fn: ast.AsyncFunctionDef) -> set[str]:
        """Nested helpers that themselves send a terminal frame (the
        `_abort` closure idiom): calling one counts as sending."""
        out: set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fn:
                if any(isinstance(c, ast.Call)
                       and _terminal_send_in(c, set())
                       for c in ast.walk(n)):
                    out.add(n.name)
        return out

    def _double_sends(self, module, cfg, send_nodes,
                      local_senders) -> Iterator[Finding]:
        # The discipline is per REQUEST: a handler loop that serves many
        # requests sends once per iteration, so retreating edges (loop
        # back-edges, rpo position not increasing) are not "after".
        rpo_pos = {n.index: i for i, n in enumerate(cfg.rpo())}

        def forward_succs(node):
            for s, kind in node.succs:
                if kind == "exc":
                    continue
                if rpo_pos.get(s.index, -1) <= rpo_pos.get(node.index, -1):
                    continue
                yield s

        send_ids = {n.index for n in send_nodes}
        for first in send_nodes:
            seen: set[int] = set()
            work = list(forward_succs(first))
            while work:
                node = work.pop()
                if node.index in seen:
                    continue
                seen.add(node.index)
                if node.index in send_ids:
                    yield self.finding(
                        module, node.stmt or first.stmt,
                        f"second terminal frame reachable at line "
                        f"{node.lineno} after the terminal send at line "
                        f"{first.lineno} — the peer will parse it as the "
                        "next request's response (one-terminal-response "
                        "discipline)")
                    break  # one finding per origin send is enough
                work.extend(forward_succs(node))

    def _silent_framed_returns(self, module, cfg,
                               local_senders) -> Iterator[Finding]:
        """`return True` (framed!) reachable from entry with zero
        terminal sends along the way."""
        targets = [
            n for n in cfg.rpo()
            if n.kind == "stmt" and isinstance(n.stmt, ast.Return)
            and isinstance(n.stmt.value, ast.Constant)
            and n.stmt.value.value is True
        ]
        if not targets:
            return
        reachable_clean: set[int] = set()
        work = [cfg.entry]
        seen: set[int] = set()
        while work:
            node = work.pop()
            if node.index in seen:
                continue
            seen.add(node.index)
            if any(self._node_sends(e, local_senders)
                   for e in node.exprs()):
                continue  # paths through a send are fine
            reachable_clean.add(node.index)
            work.extend(s for s, _k in node.succs)
        for t in targets:
            if t.index in reachable_clean:
                yield self.finding(
                    module, t.stmt,
                    "`return True` declares the connection framed, but "
                    "this path sent no terminal frame — the peer waits "
                    "forever on a connection the pool will reuse "
                    "(one-terminal-response discipline)")


# --------------------------------------------------------------- TPL052

#: Client-surface mutators that are NOT idempotent without a fence.
_NON_IDEMPOTENT_METHODS = {"create_file", "rename_file", "complete_file"}

#: RPC method strings with the same property.
_NON_IDEMPOTENT_RPCS = {"CreateFile", "Rename", "CompleteFile",
                        "RenamePrepare", "RenameCommit"}

#: Keyword/request-dict keys that fence a replay: content addressing,
#: last-writer-wins, epoch/term fencing, or an explicit idempotency key.
_FENCE_KEYS = {"etag", "overwrite", "token", "txid", "fence",
               "request_id", "idempotency_key", "if_match", "master_term"}


@register
class RetryWithoutFence(Rule):
    id = "TPL052"
    name = "retry-non-idempotent-without-fence"
    summary = ("retry loop replays a create/rename/complete-class "
               "mutation whose request carries no fence (etag/overwrite/"
               "token/term) — a lost ack makes the replay double-apply "
               "or misreport")
    doc = (
        "A retry after UNAVAILABLE/DEADLINE_EXCEEDED is indeterminate: "
        "attempt one may have applied and only the ack was lost. "
        "Replaying an op that is not idempotent then either "
        "double-applies (a second rename moves the already-moved key's "
        "new occupant) or turns success into a reported failure "
        "(create-once replay sees AlreadyExists). Every replayed "
        "mutation must carry a fence the server can use to recognize "
        "the replay: a content ETag, `overwrite=True` last-writer-wins, "
        "a transaction/idempotency token, or the master term for "
        "epoch-fenced block writes. Flagged: an awaited "
        "create/rename/complete-class call inside a loop that catches "
        "an exception and iterates again, with no fence key in the "
        "call's keywords or its request dict literal."
    )
    example = """\
while True:
    try:
        await client.rename_file(src, dst)   # no txid/fence
        break
    except DfsError:
        continue                              # replays the rename
"""
    fix = ("Carry a fence on the call (`etag=`, `overwrite=True`, a "
           "transaction token, `master_term`) so the server detects the "
           "replay, or hoist the op out of the retry loop and resolve "
           "indeterminacy by re-reading state (the `_put_if_absent` "
           "probe idiom in tpu/checkpoint.py).")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        reported: set[int] = set()  # call node ids; nested loops walk
        # the same Try twice and must not duplicate findings
        for fn in ast.walk(module.tree):
            if not isinstance(fn,
                              (ast.AsyncFunctionDef, ast.FunctionDef)):
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.While, ast.For)):
                    continue
                if module.enclosing_function(loop) is not fn:
                    continue
                loop_vars = self._loop_assigned_names(loop)
                retrying_tries = [
                    t for t in ast.walk(loop)
                    if isinstance(t, ast.Try) and self._retries(t)
                ]
                for t in retrying_tries:
                    yield from self._unfenced_calls(module, t, loop_vars,
                                                    reported)

    @staticmethod
    def _retries(t: ast.Try) -> bool:
        """An except handler that lets the loop take another iteration:
        its body neither raises, returns, nor breaks on its last
        statement."""
        for h in t.handlers:
            last = h.body[-1] if h.body else None
            if not isinstance(last, (ast.Raise, ast.Return, ast.Break)):
                return True
        return False

    @staticmethod
    def _loop_assigned_names(loop: ast.While | ast.For) -> set[str]:
        """Names (re)bound inside the loop body each iteration. A call
        whose arguments depend on one issues a DIFFERENT op every trip
        around — a workload driver, not a replay."""
        out: set[str] = set()
        if isinstance(loop, ast.For):
            for nm in ast.walk(loop.target):
                if isinstance(nm, ast.Name):
                    out.add(nm.id)
        for stmt in loop.body:
            for n in _walk_shallow(stmt):
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                  ast.For)):
                    targets = (
                        n.targets if isinstance(n, ast.Assign)
                        else [n.target])
                    for t in targets:
                        for nm in ast.walk(t):
                            if isinstance(nm, ast.Name):
                                out.add(nm.id)
        return out

    def _unfenced_calls(self, module: ModuleInfo, t: ast.Try,
                        loop_vars: set[str],
                        reported: set[int]) -> Iterator[Finding]:
        for n in _walk_shallow(ast.Module(body=t.body, type_ignores=[])):
            if not (isinstance(n, ast.Await)
                    and isinstance(n.value, ast.Call)):
                continue
            call = n.value
            if id(call) in reported:
                continue
            label = self._non_idempotent(module, call)
            if label is None:
                continue
            if self._fenced(module, call):
                continue
            arg_names = {
                nm.id
                for a in list(call.args) + [kw.value for kw in call.keywords]
                for nm in ast.walk(a) if isinstance(nm, ast.Name)}
            if arg_names & loop_vars:
                continue  # per-iteration op, not a replay of one op
            reported.add(id(call))
            yield self.finding(
                module, call,
                f"`{label}` is replayed by this retry loop without a "
                "fence (no etag/overwrite/token/term in the call or its "
                "request) — a lost ack makes the retry double-apply or "
                "misreport the outcome")

    @staticmethod
    def _non_idempotent(module: ModuleInfo, call: ast.Call) -> str | None:
        func = call.func
        if isinstance(func, ast.Attribute) \
                and func.attr in _NON_IDEMPOTENT_METHODS:
            return func.attr
        if isinstance(func, ast.Attribute) and func.attr == "call":
            # rpc.call(addr, SERVICE, "Method", req): find the method
            # string among the positional args.
            for a in call.args:
                if isinstance(a, ast.Constant) \
                        and a.value in _NON_IDEMPOTENT_RPCS:
                    return f"rpc {a.value}"
        return None

    @staticmethod
    def _fenced(module: ModuleInfo, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg in _FENCE_KEYS:
                return True
            if kw.arg is None and isinstance(kw.value, ast.Dict):
                for k in kw.value.keys:
                    if isinstance(k, ast.Constant) \
                            and k.value in _FENCE_KEYS:
                        return True
        for a in call.args:
            if isinstance(a, ast.Dict):
                for k in a.keys:
                    if isinstance(k, ast.Constant) \
                            and k.value in _FENCE_KEYS:
                        return True
        return False
