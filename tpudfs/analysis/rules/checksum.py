"""TPL005 — data-plane read path without checksum verification.

tpudfs promises END-TO-END CRC32C: every byte handed to a caller was either
verified against the sidecar checksums in this hop or is explicitly
delegated to a path that verifies. A read function that silently skips
verification turns a flipped bit on disk or on the wire into silent
corruption delivered to training jobs.

Scope: functions in the data-plane packages (``tpudfs/chunkserver/``,
``tpudfs/client/``, ``tpudfs/tpu/``) whose name starts with ``read``/
``pread`` or contains ``_read``, and that return a value.

A function passes if it shows any of:

- a verification call — dotted path mentioning ``verify``, ``crc32c``,
  ``checksum`` or ``validate``;
- a raise of a corruption error (``BlockCorruptionError``/``ChecksumError``)
  — it implements verification itself;
- delegation — it calls another read-style function (``self.store.
  read_verified(...)``, ``read_from(...)``) which is linted in its own
  right. Raw OS/stdlib reads (``os.pread``, ``f.read``) do NOT count as
  delegation.

Intentionally-unverified primitives (the raw ``BlockStore.read`` under the
verified wrappers) must carry an explicit
``# tpulint: disable=TPL005`` with a justification.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

DATA_PLANE_PREFIXES = (
    "tpudfs/chunkserver/",
    "tpudfs/client/",
    "tpudfs/tpu/",
)

_READ_NAME = re.compile(r"^p?read|_read")
_VERIFY_HINTS = ("verify", "crc32c", "checksum", "validate")
_CORRUPTION_ERRORS = {"BlockCorruptionError", "ChecksumError", "CorruptionError"}
#: Receivers whose ``read*`` methods are raw byte I/O, not linted delegates.
_RAW_RECEIVERS = {"os", "io", "socket", "struct", "mmap", "f", "fh", "fd",
                  "file", "fp", "buf", "reader"}

#: RPC methods whose server-side handler verifies the sidecar CRC32C before
#: the bytes leave the chunkserver (rpc_read_block raises
#: BlockCorruptionError on mismatch; TPL012 cross-checks the method name
#: exists). A client-side call passing one of these as a string argument to
#: a ``*call``-named helper is delegation to a verified read. ``ReadBlocks``
#: (the batch path) is deliberately absent — its payloads ship unverified
#: and every consumer re-verifies per-slot.
_VERIFIED_RPC_METHODS = {"ReadBlock"}


def _is_read_name(name: str) -> bool:
    return bool(_READ_NAME.search(name))


def _returns_value(fn: ast.FunctionDef | ast.AsyncFunctionDef,
                   module: ModuleInfo) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            if module.enclosing_function(node) is fn:
                if isinstance(node.value, ast.Constant) \
                        and node.value.value is None:
                    continue
                return True
    return False


_THREAD_BRIDGES = {"asyncio.to_thread"}
_EXECUTOR_ATTRS = {"run_in_executor"}


def _has_verification(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            target = exc.func if isinstance(exc, ast.Call) else exc
            name = dotted_name(target) or ""
            if name.split(".")[-1] in _CORRUPTION_ERRORS:
                return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and any(h in name.lower() for h in _VERIFY_HINTS):
                return True
        if isinstance(node, ast.Attribute):
            # Verified callables passed by reference, e.g.
            # `asyncio.to_thread(store.read_verified, ...)`.
            if any(h in node.attr.lower() for h in _VERIFY_HINTS):
                return True
    return False


def _read_callable_ref(node: ast.AST) -> bool:
    """``node`` references (not calls) a linted read-style callable."""
    if isinstance(node, ast.Attribute):
        if not _is_read_name(node.attr):
            return False
        receiver = dotted_name(node.value) or ""
        return receiver.split(".")[0] not in _RAW_RECEIVERS
    if isinstance(node, ast.Name):
        return _is_read_name(node.id)
    if isinstance(node, ast.IfExp):
        # `store.read_verified if verify else store.read`
        return _read_callable_ref(node.body) or _read_callable_ref(node.orelse)
    return False


def _delegates(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = dotted_name(func) or ""
        # Thread-bridge indirection: the effective callee is the first
        # function argument (`asyncio.to_thread(self.store.read, ...)`,
        # `loop.run_in_executor(None, store.read, ...)`).
        if name in _THREAD_BRIDGES and node.args:
            if _read_callable_ref(node.args[0]):
                return True
            continue
        if isinstance(func, ast.Attribute) \
                and func.attr in _EXECUTOR_ATTRS and len(node.args) >= 2:
            if _read_callable_ref(node.args[1]):
                return True
            continue
        if _read_callable_ref(func):
            return True
        # RPC delegation: `self._data_call(addr, "ReadBlock", req)` /
        # `rpc.call(addr, CS, "ReadBlock", req)` — the named server handler
        # verifies before responding.
        if isinstance(func, ast.Attribute) and func.attr.endswith("call"):
            for arg in node.args:
                if isinstance(arg, ast.Constant) \
                        and arg.value in _VERIFIED_RPC_METHODS:
                    return True
    return False


@register
class UnverifiedBlockRead(Rule):
    id = "TPL005"
    name = "unverified-block-read"
    summary = ("data-plane read path returns bytes without a CRC32C/verify "
               "call or a delegation to a verified read")
    doc = (
        "End-to-end CRC32C is the paper's integrity story: every byte "
        "leaving the data plane (chunkserver/client/tpu packages) must "
        "have been verified against its sidecar checksum somewhere on "
        "the read path. This per-function heuristic accepts a verify "
        "call, a corruption raise, or delegation to a read-named callee; "
        "intentionally-raw primitives carry `# tpulint: disable=TPL005` "
        "on their `def` line with justification, which TPL013 then "
        "treats as a taint source for whole-program tracking."
    )
    example = """\
def read_block(path):          # tpudfs/chunkserver/...
    with open(path, "rb") as f:
        return f.read()        # no verify, no corruption raise
"""
    fix = ("Verify before returning (compare crc32c, raise "
           "BlockCorruptionError on mismatch), or delegate to a "
           "*_verified read; mark a deliberate raw primitive on its "
           "`def` line.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not module.rel_path.startswith(DATA_PLANE_PREFIXES):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_read_name(node.name):
                continue
            if not _returns_value(node, module):
                continue
            if _has_verification(node) or _delegates(node):
                continue
            yield self.finding(
                module, node,
                f"read path `{node.name}` returns data without checksum "
                "verification or delegation to a verified read — end-to-end "
                "CRC32C requires every hop to verify or explicitly delegate "
                "(`# tpulint: disable=TPL005` with justification for raw "
                "primitives)",
            )
