"""TPL042/TPL043 — concurrency discipline in the native C++ engine.

The native data plane is the one place in the tree where real OS
threads share mutable state: the accept loop, per-connection handlers,
the group-commit thread, the stream disk thread, and ctypes callers
polling stats all touch the same ``Engine``. TSan catches what the
stress harness happens to execute; these rules check the whole file,
lexically, on every lint:

- **TPL042** maps each threaded class's shared state (non-atomic,
  non-const fields; file-scope globals in files that spawn threads) and
  flags accesses outside any lock — or guarded by no consistent mutex.
  Fields written only during single-threaded setup (constructor, or
  methods annotated ``// tpulint: pre-start``) are configuration and
  may be read anywhere; atomics/mutexes/threads are exempt by type; the
  destructor is exempt (join-then-teardown).
- **TPL043** flags blocking syscalls executed while a lexically tracked
  ``lock_guard``/``unique_lock`` is held — ``pread`` under the cache
  mutex serializes every reader behind one disk seek. The blocking set
  is transitive across ``native/*.cc``: a helper that calls ``fsync``
  makes its callers blocking too. ``cv.wait`` is exempt (it releases
  the lock); ``unique_lock.unlock()``/``.lock()`` toggles are honored,
  which is exactly the pattern the commit loop uses around ``syncfs``.

Both rules are pragmatic lexical passes tuned for the native sources'
idiom (members named ``foo_``, ``std::lock_guard<std::mutex> g(mu_)``),
biased to zero false positives on the real tree; genuinely clever code
can opt out per line with ``// tpulint: disable=TPL042``. Two idioms
are recognized structurally instead of suppressed: a private helper
annotated ``// tpulint: guarded-by(mu_)`` is analyzed as if ``mu_``
were held for its whole body (callers take the lock — the Qos admission
plane's `_locked` helpers), and a member whose type is a lock-owning
class defined in the same file (``Qos qos_``) is exempt from TPL042 in
the enclosing class because it synchronizes itself.
"""

from __future__ import annotations

from typing import Iterator

from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.nativesrc import (
    CClass,
    CMethod,
    NativeSource,
    Token,
    iter_with_locks,
)
from tpudfs.analysis.rules.native_abi import native_context, native_finding

#: Method calls that do not mutate the receiver — reads for the purpose
#: of the config-field classification.
_CONST_METHODS = frozenset({
    "size", "empty", "count", "find", "begin", "end", "cbegin", "cend",
    "c_str", "data", "length", "at", "front", "back", "load", "substr",
    "rfind", "compare", "capacity", "get", "lower_bound", "upper_bound",
    "contains", "native_handle",
})

#: Blocking primitives matched as non-member calls (``::read`` and
#: ``std::this_thread::sleep_for`` count; ``obj.read()`` does not).
#: Deliberately excludes ``wait`` (a condition variable releases its
#: lock), ``close``/``shutdown``/``rename``/``unlink`` (metadata ops
#: the engine treats as non-blocking fast paths).
_BLOCKING_CALLS = frozenset({
    "read", "write", "pread", "pwrite", "readv", "writev", "recv",
    "send", "recvmsg", "sendmsg", "recvfrom", "sendto", "accept",
    "accept4", "connect", "poll", "ppoll", "select", "getaddrinfo",
    "fsync", "fdatasync", "syncfs", "sync_file_range", "open", "openat",
    "fopen", "sleep", "usleep", "nanosleep", "sleep_for", "sleep_until",
    "flock", "fallocate", "posix_fallocate", "sendfile", "copy_file_range",
})

#: Blocking member calls (``thread.join()`` parks the caller).
_BLOCKING_MEMBER_CALLS = frozenset({"join", "sleep_for", "sleep_until"})


def _is_member_access(body: list[Token], i: int) -> bool:
    """``x.f`` / ``x->f`` / ``ns::f`` — but ``this->f`` counts as a bare
    member access and returns False."""
    if i == 0:
        return False
    prev = body[i - 1]
    if prev.kind != "punct" or prev.text not in (".", "->", "::"):
        return False
    if i >= 2 and body[i - 2].kind == "id" and body[i - 2].text == "this":
        return False
    return True


def _is_write_site(body: list[Token], i: int) -> tuple[bool, bool]:
    """(is_access_written, via_mutating_method) for identifier at i."""
    nxt = body[i + 1] if i + 1 < len(body) else None
    prv = body[i - 1] if i > 0 else None
    if prv is not None and prv.kind == "punct" and prv.text in ("++", "--"):
        return True, False
    if nxt is None or nxt.kind != "punct":
        return False, False
    if nxt.text in ("=", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=",
                    "<<=", ">>=", "++", "--"):
        return True, False
    if nxt.text in (".", "->") and i + 2 < len(body) \
            and body[i + 2].kind == "id":
        meth = body[i + 2].text
        if i + 3 < len(body) and body[i + 3].kind == "punct" \
                and body[i + 3].text == "(" \
                and meth not in _CONST_METHODS:
            return True, True
    if nxt.text == "[":
        # Indexed store? conservatively: `x[i] =` — scan to the matching
        # bracket and peek.
        depth = 0
        for j in range(i + 1, len(body)):
            t = body[j]
            if t.kind == "punct":
                if t.text == "[":
                    depth += 1
                elif t.text == "]":
                    depth -= 1
                    if depth == 0:
                        k = body[j + 1] if j + 1 < len(body) else None
                        return (k is not None and k.kind == "punct"
                                and k.text == "="), False
        return False, False
    return False, False


class _Access:
    __slots__ = ("line", "method", "write", "held")

    def __init__(self, line: int, method: str, write: bool,
                 held: tuple[str, ...]):
        self.line = line
        self.method = method
        self.write = write
        self.held = held


def _field_accesses(cls: CClass, field_name: str,
                    methods: list[CMethod]) -> list[_Access]:
    out: list[_Access] = []
    for m in methods:
        body = m.body
        for i, tok, held in iter_with_locks(body, base=m.guarded_by):
            if tok.kind != "id" or tok.text != field_name:
                continue
            if _is_member_access(body, i):
                continue
            write, _ = _is_write_site(body, i)
            out.append(_Access(tok.line, m.name, write, held))
    return out


@register
class NativeSharedStateGuard(ProjectRule):
    id = "TPL042"
    name = "native-shared-state-guard"
    summary = ("non-atomic shared state of a threaded native class (or "
               "a file-scope global in a thread-spawning file) accessed "
               "outside its mutex, or guarded by no single consistent "
               "mutex")
    doc = (
        "Classes in native/*.cc that own a std::thread or std::mutex "
        "are concurrent by construction: the accept loop, connection "
        "handlers, the commit thread, and ctypes stats callers all "
        "enter the same object. This rule classifies each non-atomic, "
        "non-const field: written only in the constructor or in "
        "methods annotated `// tpulint: pre-start` (setup that runs "
        "before any thread exists) means configuration — reads anywhere "
        "are fine; everything else is shared state, and every access "
        "in a normal method must happen while a lexically tracked "
        "lock_guard/unique_lock is held, with one mutex common to all "
        "of the field's guarded accesses (a field guarded by conns_mu_ "
        "here and cache_mu_ there is a race with extra steps). "
        "Destructors are exempt (threads are joined first). File-scope "
        "globals get the same treatment in any file that mentions "
        "threads, unless no function ever writes them (lookup tables)."
    )
    example = """\
struct Engine {
  std::mutex mu_;
  std::map<std::string, uint64_t> terms_;
  void set_term(const std::string& s, uint64_t t) {
    terms_[s] = t;                      // no lock held
  }
};
"""
    fix = ("Take the field's mutex (`std::lock_guard<std::mutex> "
           "g(mu_);`) around the access, make the field std::atomic if "
           "it is a scalar counter, annotate a helper whose callers "
           "all hold the lock with `// tpulint: guarded-by(mu_)` on "
           "the line above, or — if the method really runs before any "
           "thread is spawned — annotate it with `// tpulint: "
           "pre-start`.")

    def check_project(self, project) -> Iterator[Finding]:
        _root, sources = native_context(project)
        for src in sources:
            for cls in src.classes:
                if cls.has_sync:
                    yield from self._check_class(src, cls)
            if src.has_threads:
                yield from self._check_globals(src)

    # ------------------------------------------------------ class fields

    def _check_class(self, src: NativeSource, cls: CClass
                     ) -> Iterator[Finding]:
        normal = [m for m in cls.methods
                  if not (m.is_ctor or m.is_dtor or m.pre_start)]
        # A member whose type is itself a lock-owning class defined in
        # this file (e.g. `Qos qos_`) is a synchronization domain of its
        # own — its internals are checked when that class is analyzed,
        # and calls into it from any thread are the intended interface.
        sync_classes = {c.name for c in src.classes
                        if c.has_sync and c.name != cls.name}
        for name, fld in cls.fields.items():
            if fld.sync or fld.const:
                continue
            type_words = fld.type_text.split()
            if type_words and type_words[0] in sync_classes:
                continue
            accesses = _field_accesses(cls, name, normal)
            if not accesses:
                continue
            normal_writes = [a for a in accesses if a.write]
            if not normal_writes:
                # Config field: mutated only (if ever) during setup
                # (ctor / `// tpulint: pre-start`). A field nothing
                # ever writes is likewise inert.
                continue
            unguarded = [a for a in accesses if not a.held]
            guarded = [a for a in accesses if a.held]
            for a in unguarded:
                f = native_finding(
                    self.id, src, a.line, f"{cls.name}.{a.method}",
                    f"`{cls.name}::{name}` is shared state (written in "
                    f"`{next(w.method for w in normal_writes)}`) but "
                    f"this {'write' if a.write else 'read'} in "
                    f"`{a.method}` holds no lock"
                    + (f" — other accesses hold "
                       f"`{guarded[0].held[-1]}`" if guarded else ""))
                if f is not None:
                    yield f
            if not unguarded and guarded:
                common = set(guarded[0].held)
                for a in guarded[1:]:
                    common &= set(a.held)
                if not common:
                    a = guarded[-1]
                    f = native_finding(
                        self.id, src, a.line, f"{cls.name}.{a.method}",
                        f"`{cls.name}::{name}` is guarded by different "
                        "mutexes at different sites ("
                        + ", ".join(sorted({h for g in guarded
                                            for h in g.held}))
                        + ") — no single lock orders its accesses")
                    if f is not None:
                        yield f

    # ---------------------------------------------------------- globals

    def _check_globals(self, src: NativeSource) -> Iterator[Finding]:
        bodies: list[CMethod] = list(src.free_funcs)
        for cls in src.classes:
            bodies.extend(cls.methods)
        for name, g in src.globals.items():
            if g.sync or g.const:
                continue
            accesses: list[_Access] = []
            for m in bodies:
                body = m.body
                for i, tok, held in iter_with_locks(body,
                                                    base=m.guarded_by):
                    if tok.kind != "id" or tok.text != name:
                        continue
                    if _is_member_access(body, i):
                        continue
                    write, _ = _is_write_site(body, i)
                    accesses.append(_Access(tok.line, m.name, write, held))
            if not any(a.write for a in accesses):
                continue  # read-only table
            for a in accesses:
                if a.held:
                    continue
                f = native_finding(
                    self.id, src, a.line, a.method,
                    f"file-scope global `{name}` is mutated across "
                    f"threads but this "
                    f"{'write' if a.write else 'read'} in `{a.method}` "
                    "holds no lock")
                if f is not None:
                    yield f


@register
class NativeBlockingUnderMutex(ProjectRule):
    id = "TPL043"
    name = "native-blocking-under-mutex"
    summary = ("blocking syscall (disk/network/sleep/join, directly or "
               "via a native helper) executed while a mutex is held in "
               "native/*.cc — every thread contending that lock stalls "
               "behind one I/O")
    doc = (
        "A mutex in the native engine orders map updates measured in "
        "nanoseconds; a pread or fsync inside the critical section "
        "turns it into a disk-latency lock, and the accept loop, every "
        "connection handler, and the stats poller pile up behind it. "
        "This rule tracks lock_guard/unique_lock scopes lexically — "
        "including unique_lock's mid-scope .unlock()/.lock() toggles, "
        "the exact idiom the commit loop uses to drop the queue lock "
        "around syncfs+rename — and flags any call to a blocking "
        "primitive (read/write/pread/pwrite/send/recv/accept/connect/"
        "poll/open/fsync/syncfs/sleep_for/join/...) made while a lock "
        "is held. The blocking property is transitive across "
        "native/*.cc: calling a helper that calls fsync is as blocking "
        "as fsync. cv.wait is exempt (it releases the lock while "
        "parked)."
    )
    example = """\
int64_t persist(const std::string& id, const uint8_t* p, uint64_t n) {
  std::lock_guard<std::mutex> g(commit_mu_);
  int64_t rc = tpudfs_block_write_staged(hot_.c_str(), id.c_str(),
                                         p, n, chunk_, nullptr);  // disk I/O
  return rc;
}
"""
    fix = ("Move the I/O out of the critical section: copy what you "
           "need under the lock, drop it (scope exit or "
           "unique_lock.unlock()), do the blocking work, re-acquire to "
           "publish the result — the group-commit loop in dataplane.cc "
           "is the template.")

    def check_project(self, project) -> Iterator[Finding]:
        _root, sources = native_context(project)
        if not sources:
            return
        blocking = self._transitive_blocking(sources)
        for src in sources:
            bodies: list[tuple[str, CMethod]] = [
                (m.name, m) for m in src.free_funcs]
            for cls in src.classes:
                bodies.extend((f"{cls.name}.{m.name}", m)
                              for m in cls.methods)
            for scope, m in bodies:
                yield from self._check_body(src, scope, m, blocking)

    # ---------------------------------------------- transitive closure

    @staticmethod
    def _direct_calls(body: list[Token]) -> Iterator[tuple[int, str, bool]]:
        """(index, callee, is_member) for each call site in a body."""
        for i in range(len(body) - 1):
            t, nxt = body[i], body[i + 1]
            if t.kind != "id" or nxt.kind != "punct" or nxt.text != "(":
                continue
            member = _is_member_access(body, i) and \
                body[i - 1].text in (".", "->")
            yield i, t.text, member

    def _transitive_blocking(self, sources: list[NativeSource]
                             ) -> dict[str, str]:
        """``{function name: blocking witness}`` over every function/
        method defined in the native tree."""
        defined: dict[str, list[CMethod]] = {}
        for src in sources:
            for m in src.free_funcs:
                defined.setdefault(m.name, []).append(m)
            for cls in src.classes:
                for m in cls.methods:
                    defined.setdefault(m.name, []).append(m)
        blocking: dict[str, str] = {}
        changed = True
        while changed:
            changed = False
            for name, impls in defined.items():
                if name in blocking:
                    continue
                witness = None
                for m in impls:
                    for _i, callee, member in self._direct_calls(m.body):
                        if member:
                            if callee in _BLOCKING_MEMBER_CALLS:
                                witness = callee
                                break
                            continue
                        if callee in _BLOCKING_CALLS:
                            witness = callee
                            break
                        if callee in blocking and callee != name:
                            witness = f"{callee} -> {blocking[callee]}"
                            break
                    if witness:
                        break
                if witness:
                    blocking[name] = witness
                    changed = True
        return blocking

    # -------------------------------------------------- per-body check

    def _check_body(self, src: NativeSource, scope: str, m: CMethod,
                    blocking: dict[str, str]) -> Iterator[Finding]:
        body = m.body
        for i, tok, held in iter_with_locks(body, base=m.guarded_by):
            if not held or tok.kind != "id":
                continue
            nxt = body[i + 1] if i + 1 < len(body) else None
            if nxt is None or nxt.kind != "punct" or nxt.text != "(":
                continue
            member = _is_member_access(body, i) and \
                body[i - 1].text in (".", "->")
            name = tok.text
            if member:
                if name not in _BLOCKING_MEMBER_CALLS:
                    continue
                why = name
            elif name in _BLOCKING_CALLS:
                why = name
            elif name in blocking and name != m.name:
                why = f"{name} (-> {blocking[name]})"
            else:
                continue
            f = native_finding(
                self.id, src, tok.line, scope,
                f"blocking call `{why}` while holding "
                f"`{held[-1]}` — every thread contending this mutex "
                "stalls behind the I/O; drop the lock around the "
                "blocking work (unique_lock.unlock()/.lock(), as in "
                "the commit loop)")
            if f is not None:
                yield f
