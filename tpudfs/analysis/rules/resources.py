"""TPL022 — resource liveness over all CFG paths, exception edges included.

A chunkserver that leaks one file descriptor per failed read eventually
cannot open its own WAL; a forgotten ``create_task`` handle means the
coroutine's exception is never retrieved and the task can be garbage
collected mid-flight. The classic shape is *almost* right code::

    fd = os.open(path, os.O_RDONLY)
    data = os.read(fd, n)        # can raise — fd leaks on this edge
    os.close(fd)

This rule runs a may-analysis over the function CFG: an acquisition site
stays live until a release kills it, and the ``exc`` edges give exception
unwinding its own paths — so the example above is flagged even though the
happy path closes, while the ``try/finally`` version is clean because the
exception edges route through the ``finally`` close. An acquisition whose
own statement raises is not charged (the ``edge_value`` hook subtracts the
site on its ``exc`` edge: if ``os.open`` raised, there is nothing to
leak).

Tracked acquisitions (a simple ``name = <acquire>()`` binding): files and
sockets (``open``, ``os.open``, ``os.fdopen``, ``socket.socket``,
``socket.create_connection``), temp state (``tempfile.mkdtemp`` /
``TemporaryDirectory`` / ``NamedTemporaryFile``), and task handles
(``asyncio.create_task`` / ``ensure_future``, including the
``loop.create_task`` attribute form; TaskGroup-style receivers are exempt
because the group owns its children). Releases: using the variable in a
``with``, ``await var``, ``os.close(var)``, or a method call from the
release vocabulary (``close``, ``cancel``, ``join``,
``add_done_callback``, ...).

Any *other* use — returned, stored on ``self``, passed to a non-``os``
call, yielded — is an **escape**: ownership moved somewhere flow analysis
cannot follow, and the rule drops the variable entirely rather than
guess. The rule is therefore precise exactly on the pattern that
matters: a resource that provably never leaves the function must be
released inside it, on every path.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.cfg import Node, cfg_for
from tpudfs.analysis.dataflow import MayAnalysis, solve
from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: Dotted callable names whose result is an owned resource.
_ACQUIRE_CALLS = {
    "open": "file",
    "os.open": "file descriptor",
    "os.fdopen": "file",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "tempfile.mkdtemp": "temporary directory",
    "tempfile.TemporaryDirectory": "temporary directory",
    "tempfile.NamedTemporaryFile": "temporary file",
    "asyncio.create_task": "task handle",
    "asyncio.ensure_future": "task handle",
}

#: Attribute-call tails that also acquire (``loop.create_task(...)``),
#: unless the receiver is a task group that owns its children.
_ACQUIRE_ATTRS = {"create_task": "task handle", "ensure_future": "task handle"}
_GROUP_RECEIVERS = {"tg", "taskgroup", "task_group", "group", "nursery"}

#: Method names on the resource variable that end ownership.
_RELEASE_METHODS = {
    "close", "aclose", "cancel", "cleanup", "terminate", "kill", "join",
    "shutdown", "release", "stop", "detach", "unlink", "add_done_callback",
}

#: Parents under which a bare Load of the variable is just a test,
#: not a transfer of ownership.
_NEUTRAL_PARENTS = (ast.Compare, ast.BoolOp, ast.UnaryOp, ast.If, ast.While,
                    ast.Assert, ast.IfExp)


def _acquire_kind(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name in _ACQUIRE_CALLS:
        return _ACQUIRE_CALLS[name]
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in _ACQUIRE_ATTRS:
        recv = dotted_name(call.func.value) or ""
        if recv.split(".")[-1].lower() in _GROUP_RECEIVERS:
            return None
        return _ACQUIRE_ATTRS[call.func.attr]
    return None


class _Site:
    """One acquisition: variable name + the binding statement."""

    __slots__ = ("var", "kind", "stmt", "lineno")

    def __init__(self, var: str, kind: str, stmt: ast.stmt):
        self.var = var
        self.kind = kind
        self.stmt = stmt
        self.lineno = stmt.lineno


class _FnFacts:
    """Escape-checked acquire sites and release uses for one function."""

    def __init__(self, module: ModuleInfo, fn: ast.AST):
        self.sites: dict[int, _Site] = {}        # id(assign stmt) -> site
        self.by_var: dict[str, set[int]] = {}    # var -> site ids
        self.release_uses: dict[int, str] = {}   # id(Name load) -> var
        parents: dict[int, ast.AST] = {}
        subs: list[ast.AST] = []
        for sub in ast.walk(fn):
            if module.enclosing_function(sub) is not fn:
                continue
            subs.append(sub)
            for child in ast.iter_child_nodes(sub):
                parents[id(child)] = sub

        for sub in subs:
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call):
                kind = _acquire_kind(sub.value)
                if kind is not None:
                    site = _Site(sub.targets[0].id, kind, sub)
                    self.sites[id(sub)] = site
                    self.by_var.setdefault(site.var, set()).add(id(sub))
        if not self.sites:
            return

        escaped: set[str] = set()
        for sub in subs:
            if not (isinstance(sub, ast.Name) and sub.id in self.by_var):
                continue
            if isinstance(sub.ctx, ast.Del):
                escaped.add(sub.id)
                continue
            if isinstance(sub.ctx, ast.Store):
                parent = parents.get(id(sub))
                if not (isinstance(parent, ast.Assign)
                        and id(parent) in self.sites):
                    escaped.add(sub.id)  # rebound from something untracked
                continue
            use = self._classify_use(sub, parents)
            if use == "release":
                self.release_uses[id(sub)] = sub.id
            elif use == "escape":
                escaped.add(sub.id)
        for var in escaped:
            for sid in self.by_var.pop(var, ()):
                self.sites.pop(sid, None)
            self.release_uses = {
                k: v for k, v in self.release_uses.items() if v != var}

    @staticmethod
    def _classify_use(sub: ast.Name,
                      parents: dict[int, ast.AST]) -> str:
        parent = parents.get(id(sub))
        if isinstance(parent, ast.Await) and parent.value is sub:
            return "release"
        if isinstance(parent, ast.withitem) and parent.context_expr is sub:
            return "release"
        if isinstance(parent, ast.Attribute) and parent.value is sub:
            grand = parents.get(id(parent))
            if isinstance(grand, ast.Call) and grand.func is parent \
                    and parent.attr in _RELEASE_METHODS:
                return "release"
            return "neutral"  # fd-less method/attr use: f.read(), t.done()
        if isinstance(parent, ast.Call) and sub in parent.args:
            func = dotted_name(parent.func) or ""
            if func == "os.close":
                return "release"
            if func.startswith("os."):
                return "neutral"  # os.read(fd, ...) and friends
            return "escape"
        if isinstance(parent, _NEUTRAL_PARENTS):
            return "neutral"
        return "escape"


class _LiveResources(MayAnalysis):
    """May-unreleased acquisition sites (tracked by ``id(stmt)``)."""

    def __init__(self, facts: _FnFacts):
        self._facts = facts

    def transfer(self, node: Node, value):
        facts = self._facts
        for sub in node.walk():
            var = facts.release_uses.get(id(sub))
            if var is not None:
                value = frozenset(
                    s for s in value if s not in facts.by_var[var])
        if node.stmt is not None and id(node.stmt) in facts.sites:
            value = value | {id(node.stmt)}
        return value

    def edge_value(self, src: Node, dst: Node, kind: str, value):
        if kind == "exc" and src.stmt is not None \
                and id(src.stmt) in self._facts.sites:
            # The acquire call itself raised: nothing was acquired.
            return value - {id(src.stmt)}
        return value


@register
class ResourceLiveness(Rule):
    id = "TPL022"
    name = "resource-leak-on-path"
    summary = ("file/socket/tempdir/task handle acquired here is not "
               "released on every CFG path out of the function, "
               "exception edges included")
    doc = (
        "A chunkserver leaking one fd per failed read eventually cannot "
        "open its own WAL. The classic shape is almost-right code: "
        "open, use, close — where the use can raise and the close never "
        "runs. A may-analysis over the CFG keeps each acquisition live "
        "until a release kills it; exception edges give unwinding its "
        "own paths, so the happy-path close does not excuse the leak. "
        "Tracked: open/os.open/sockets/tempfiles and task handles "
        "(create_task without a TaskGroup). Any use the rule cannot "
        "prove safe — returned, stored, passed to a non-os call — is an "
        "escape: ownership left the function and the rule goes quiet."
    )
    example = """\
def probe(path):
    fd = os.open(path, os.O_RDONLY)
    data = os.read(fd, 64)     # raises on EIO -> fd leaks
    os.close(fd)
    return data
"""
    fix = ("`with open(...)` / try-finally around the use; await, "
           "cancel, or register task handles so something owns them.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for fn in ast.walk(module.tree):
            if isinstance(fn, _FUNC_NODES):
                yield from self._check_fn(module, fn)

    def _check_fn(self, module: ModuleInfo,
                  fn: ast.FunctionDef | ast.AsyncFunctionDef) -> \
            Iterator[Finding]:
        facts = _FnFacts(module, fn)
        if not facts.sites:
            return
        cfg = cfg_for(module, fn)
        res = solve(cfg, _LiveResources(facts))

        def in_value(node: Node) -> frozenset:
            pair = res.get(node.index)
            return pair[0] if pair and pair[0] is not None else frozenset()

        leak_exc = in_value(cfg.raise_exit)
        leak_ret = in_value(cfg.exit)
        for sid in sorted(leak_exc | leak_ret,
                          key=lambda s: facts.sites[s].lineno):
            site = facts.sites[sid]
            if sid in leak_exc and sid in leak_ret:
                how = ("is not released on every path — including when an "
                       "exception unwinds past it")
            elif sid in leak_exc:
                how = ("leaks when an exception is raised before the "
                       "release — close it in a `finally` or use `with`")
            else:
                how = ("is not released on every return path — some branch "
                       "skips the close")
            yield self.finding(
                module, site.stmt,
                f"{site.kind} `{site.var}` acquired here {how}; every "
                "acquisition needs a release on all paths (with/try-finally"
                ", or await/cancel for task handles)",
            )
