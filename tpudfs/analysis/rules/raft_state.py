"""TPL004 — Raft core state mutated outside the sans-io step functions.

The consensus core (tpudfs/raft/core.py) is a pure state machine: ``term``,
``voted_for``, ``log``, ``commit_index`` and ``last_applied`` change only
inside its step functions, which emit the matching persistence effects
(PersistHardState / AppendLog / TruncateLog). A write from the shell or any
other layer bypasses that effect discipline — state diverges from what the
WAL records, which is exactly the crash-recovery hole Raft's proof forbids.

Heuristic: a write (assign, augmented assign, delete, subscript store, or a
mutating method call like ``.append``/``.clear``) to one of the protected
attributes on a receiver that names a Raft core — a dotted path whose final
component is ``core``, ``_core``, ``raft`` or ``raft_core`` (``self.core``,
``node.raft.core``, ...). tpudfs/raft/core.py itself is exempt: it IS the
step-function home.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

PROTECTED_ATTRS = {
    "term", "current_term", "voted_for", "log", "commit_index",
    "last_applied", "role", "snapshot",
}
_MUTATORS = {
    "append", "extend", "insert", "pop", "remove", "clear", "sort",
    "reverse", "update", "setdefault",
}
_CORE_TAILS = {"core", "_core", "raft", "_raft", "raft_core"}

EXEMPT_MODULES = ("tpudfs/raft/core.py",)


def _core_receiver(node: ast.AST) -> str | None:
    """Dotted name of ``node`` if it plausibly denotes a RaftCore."""
    name = dotted_name(node)
    if not name:
        return None
    if name.split(".")[-1] in _CORE_TAILS:
        return name
    return None


def _protected_target(node: ast.AST) -> tuple[str, str] | None:
    """(receiver, attr) when ``node`` is ``<core>.<protected attr>`` or a
    subscript thereof (``<core>.log[i]``)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if not isinstance(node, ast.Attribute):
        return None
    if node.attr not in PROTECTED_ATTRS:
        return None
    recv = _core_receiver(node.value)
    if recv is None:
        return None
    return recv, node.attr


@register
class RaftStateMutation(Rule):
    id = "TPL004"
    name = "raft-state-mutation"
    summary = ("Raft core state (term/voted_for/log/commit_index) mutated "
               "outside raft/core.py — bypasses the persistence effects")
    doc = (
        "The Raft core is sans-io: state transitions happen only inside "
        "raft/core.py step functions, which emit explicit persistence "
        "effects the node must apply (and fsync) before acting. A direct "
        "`core.term = x` from node/transport code skips that contract — "
        "the change is never persisted, and a crash restores the old "
        "term, which can double-vote. TPL023 proves the complementary "
        "runtime property: effects are persisted before messages leave."
    )
    example = """\
def on_vote(core, req):
    core.term = req["term"]      # no persistence effect emitted
    core.log.append(req["e"])    # WAL never sees this entry
"""
    fix = ("Route every mutation through the core's step functions and "
           "apply the returned effects; read-only access from outside is "
           "fine.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if module.rel_path in EXEMPT_MODULES:
            return
        for node in ast.walk(module.tree):
            targets: list[ast.AST] = []
            verb = "assignment to"
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = list(node.targets)
                verb = "deletion of"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                hit = _protected_target(node.func.value)
                if hit:
                    recv, attr = hit
                    yield self.finding(
                        module, node,
                        f"in-place mutation `{recv}.{attr}.{node.func.attr}"
                        "(...)` outside raft/core.py — route through a core "
                        "step function so the persistence effect is emitted",
                    )
                continue
            for t in targets:
                hit = _protected_target(t)
                if hit:
                    recv, attr = hit
                    yield self.finding(
                        module, node,
                        f"{verb} Raft core state `{recv}.{attr}` outside "
                        "raft/core.py — only core step functions may mutate "
                        "consensus state (and must emit persistence effects)",
                    )
