"""TPL024 — RPC call site with no explicit timeout and no deadline budget.

``RpcClient.call`` and ``BlockConnPool.call`` default their ``timeout``
(10 s / 30 s). A call site that omits it inherits that flat default — and
when nothing above it installs a deadline budget
(``tpudfs.common.resilience.deadline_scope``), nothing clamps the attempt
to the caller's remaining time either. Under overload that is exactly the
site that turns a 2-second user budget into a 10-second hang: every other
hop finishes fast, this one parks on the default.

Detection mirrors TPL012's call-site shape (a resolvable service string
followed by a method string among the positional args, cross-checked
against registered ``add_service`` tables), so it tracks the same set of
real RPC invocations and skips unrelated ``.call(...)`` methods.

A site is compliant when any of:

- it passes ``timeout`` (keyword or positional — constant or derived, the
  clamp inside ``RpcClient.call`` bounds it to the remaining budget);
- its enclosing function installs a deadline budget itself
  (``deadline_scope(...)`` / ``set_deadline(...)`` in the body, or a
  ``@_budgeted`` decorator);
- interprocedurally (like TPL010's transitive reachability, but walked
  against the reverse call graph): **some** analyzed caller chain installs
  a budget above it. Conservative by design — one budgeted path means the
  site was written deadline-aware, and flagging it anyway would train
  people to scatter redundant constants.

``timeout=None`` is NOT compliant: it removes the transport bound
entirely, which is the hang this rule exists to prevent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    ProjectRule,
    dotted_name,
    register,
)

#: Calls that install a deadline budget for everything beneath them.
_BUDGET_CALLS = {"deadline_scope", "set_deadline"}
#: Decorators that wrap a method in a deadline scope (client.py idiom).
_BUDGET_DECORATORS = {"_budgeted", "budgeted"}


def _installs_budget(fn: FunctionInfo) -> bool:
    """Does this function put a deadline budget in scope — via decorator or
    by calling the resilience primitives directly?"""
    for dec in fn.node.decorator_list:
        name = dotted_name(dec) or (
            dotted_name(dec.func) if isinstance(dec, ast.Call) else None
        )
        if name is not None and name.rsplit(".", 1)[-1] in _BUDGET_DECORATORS:
            return True
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None \
                    and name.rsplit(".", 1)[-1] in _BUDGET_CALLS:
                return True
    return False


@register
class RpcDeadlineDiscipline(ProjectRule):
    id = "TPL024"
    name = "rpc-deadline-discipline"
    summary = ("RPC call site passes no timeout and no caller installs a "
               "deadline budget — the call parks on the transport default "
               "under overload")
    doc = (
        "`RpcClient.call`/`BlockConnPool.call` clamp each attempt to the "
        "caller's remaining deadline budget, but only if a budget exists. "
        "A site with no explicit `timeout` and no `deadline_scope(...)` "
        "anywhere up its (analyzed) call chains falls back to the flat "
        "transport default — 10 s — which is how a 2 s end-to-end budget "
        "quietly becomes a 10 s hang on the one slow hop. `timeout=None` "
        "is flagged too: it removes the bound entirely. Call sites whose "
        "method/service strings are dynamic, or that talk to services not "
        "registered in this tree, are out of scope (TPL012 shares the "
        "same horizon)."
    )
    example = """\
async def fetch(self):
    # no timeout=, and no deadline_scope() on any path to fetch()
    return await self.rpc.call(addr, CS, "ReadBlock", req)
"""
    fix = ("Pass an explicit `timeout=` sized for the hop, or run the "
           "operation under `deadline_scope(budget)` (the client's "
           "`op_budget` / `@_budgeted` idiom) so `RpcClient.call` derives "
           "per-attempt timeouts from the remaining budget.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        tables = _server_tables(project)
        if not tables:
            return
        budgeted = {fn for fn in project.functions.values()
                    if _installs_budget(fn)}
        callers = _reverse_edges(project)

        for mod in project.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr != "call":
                    continue
                idx = _service_index(project, mod, node, tables)
                if idx is None:
                    continue
                if _has_timeout(node, idx):
                    continue
                fn = project.enclosing_function_info(mod, node)
                if fn is not None and _budget_reaches(fn, budgeted, callers):
                    continue
                yield self.finding(
                    mod, node,
                    "RPC call passes no `timeout` and no analyzed caller "
                    "installs a deadline budget (`deadline_scope`) — under "
                    "overload this attempt parks on the flat transport "
                    "default instead of the caller's remaining budget",
                )


def _server_tables(project: Project) -> set[str]:
    """Service names registered anywhere via ``add_service`` — the same
    horizon TPL012 uses, so both rules skip out-of-tree services."""
    names: set[str] = set()
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add_service" \
                    and node.args:
                service = project.resolve_str_const(mod, node.args[0])
                if service is not None:
                    names.add(service)
    return names


def _service_index(project: Project, mod: ModuleInfo, node: ast.Call,
                   tables: set[str]) -> int | None:
    """Positional index of the service-name arg when this ``*.call(...)``
    names a registered service followed by a method string."""
    for i in range(len(node.args) - 1):
        service = project.resolve_str_const(mod, node.args[i])
        if service is None or service not in tables:
            continue
        if project.resolve_str_const(mod, node.args[i + 1]) is None:
            return None  # dynamic method variable: stay silent (TPL012 too)
        return i
    return None


def _has_timeout(node: ast.Call, service_idx: int) -> bool:
    """Explicit timeout at this site. Both transports place ``timeout``
    three positions after the service name (addr/_, service, method, req,
    timeout). ``timeout=None`` does not count — it UNbounds the call."""
    for kw in node.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    if len(node.args) > service_idx + 3:
        extra = node.args[service_idx + 3]
        return not (isinstance(extra, ast.Constant) and extra.value is None)
    return False


def _reverse_edges(
    project: Project,
) -> dict[FunctionInfo, list[FunctionInfo]]:
    rev: dict[FunctionInfo, list[FunctionInfo]] = {}
    for fn in project.functions.values():
        for edge in fn.calls:
            rev.setdefault(edge.callee, []).append(edge.caller)
    return rev


def _budget_reaches(fn: FunctionInfo, budgeted: set[FunctionInfo],
                    callers: dict) -> bool:
    """Walk the reverse call graph from ``fn``: is any (transitive) caller
    a budget-installing function?"""
    seen = {fn}
    stack = [fn]
    while stack:
        cur = stack.pop()
        if cur in budgeted:
            return True
        for parent in callers.get(cur, ()):
            if parent not in seen:
                seen.add(parent)
                stack.append(parent)
    return False
