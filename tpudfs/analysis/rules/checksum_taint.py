"""TPL013 — interprocedural checksum taint through the read path.

TPL005 judges one function at a time and credits *any* delegation to a
read-named callee, because it cannot see what that callee does. The gap:
a wrapper that delegates to the **declared-raw** primitive —

    def read_cached(self, block_id):
        return self.store.read(block_id)   # raw pread, disable=TPL005

— passes TPL005 on both sides (the wrapper delegates; the primitive is
suppressed with justification), yet unverified bytes escape the data
plane. That is precisely the bug class behind silent-corruption reads.

This rule walks the resolved call graph instead of trusting names. A
function whose ``# tpulint: disable=TPL005`` sits on its ``def`` line is
*declared raw*: intentionally unverified, safe only under a verifying
caller. For every other data-plane read function, taint propagates along
resolved read-delegation edges (plain calls and ``to_thread``/executor
bridges alike — threading changes where code runs, not whether bytes were
checked): a function is flagged when it performs no verification of its
own and some resolved chain reaches a declared-raw read with no
verification anywhere between. The full chain appears in the message.

One exemption mirrors ``_VERIFIED_RPC_METHODS`` in checksum.py: some RPC
methods ship raw payloads *by contract* — every consumer re-verifies
per-slot (the batch ``ReadBlocks`` path: read_combiner checks
``expected_crc`` before any byte reaches a caller). The server handler
registered for such a method is the server half of that contract, so its
chain down to the raw primitive is the documented design, not an escape.
The contract is codified here, in the handler-table registration — not
with a suppression, which would hide genuinely new escapes in the same
function.

Unresolved delegation stays TPL005's territory — no resolution, no
finding.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.rules.checksum import (
    DATA_PLANE_PREFIXES,
    _has_verification,
    _is_read_name,
    _returns_value,
)

#: RPC methods whose payloads ship unverified by documented contract:
#: every consumer re-verifies per-slot before bytes escape. Keep in sync
#: with the "deliberately absent" note on checksum.py's
#: ``_VERIFIED_RPC_METHODS``.
_CONSUMER_VERIFIED_RPCS = {"ReadBlocks"}


def _declared_raw(fn: FunctionInfo) -> bool:
    return fn.module.suppressed("TPL005", fn.node.lineno)


def _serves_consumer_verified_rpc(fn: FunctionInfo) -> bool:
    """True when ``fn`` is registered in a handler table as the server
    handler for a consumer-verified RPC method (``{"ReadBlocks":
    self.rpc_read_blocks}``)."""
    for node in ast.walk(fn.module.tree):
        if not isinstance(node, ast.Dict):
            continue
        for key, value in zip(node.keys, node.values):
            if not (isinstance(key, ast.Constant)
                    and key.value in _CONSUMER_VERIFIED_RPCS):
                continue
            if isinstance(value, ast.Attribute) and value.attr == fn.name:
                return True
            if isinstance(value, ast.Name) and value.id == fn.name:
                return True
    return False


def _is_read_fn(fn: FunctionInfo) -> bool:
    return _is_read_name(fn.name) and _returns_value(fn.node, fn.module)


@register
class ChecksumTaintEscape(ProjectRule):
    id = "TPL013"
    name = "checksum-taint-escape"
    summary = ("data-plane read path resolves (transitively) to a "
               "declared-raw read with no CRC32C verification on the way — "
               "unverified bytes escape the data plane")
    doc = (
        "TPL005 credits any delegation to a read-named callee, so a "
        "wrapper over the *declared-raw* primitive (`# tpulint: "
        "disable=TPL005` on its `def` line) passes both checks while "
        "returning unverified bytes. This rule follows the resolved "
        "call graph instead of names: taint flows from declared-raw "
        "reads up through unverified read hops (to_thread bridges "
        "included — threading moves code, not verification) until a "
        "verifying hop stops it. Handlers registered for "
        "consumer-verified RPCs (ReadBlocks: every consumer re-verifies "
        "per-slot) are the codified exception."
    )
    example = """\
def read_cached(self, block_id):
    # Store.read is declared raw (disable=TPL005 on its def line)
    return self.store.read(block_id)   # unverified bytes escape
"""
    fix = ("Verify in the wrapper, route through a verified variant, or "
           "— for a genuinely raw-by-contract API — declare the wrapper "
           "raw on its own `def` line with justification.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        #: fn -> chain down to the raw primitive, or None if clean
        memo: dict[FunctionInfo, list[FunctionInfo] | None] = {}

        def raw_chain(fn: FunctionInfo,
                      stack: set[FunctionInfo]) -> list[FunctionInfo] | None:
            """Chain from ``fn`` to a declared-raw read it taints from,
            given that ``fn`` itself does not verify."""
            if fn in memo:
                return memo[fn]
            if fn in stack:
                return None
            stack.add(fn)
            result = None
            for edge in fn.calls:
                if edge.kind == "task":
                    continue  # spawned readers return via their own awaiters
                callee = edge.callee
                if not _is_read_name(callee.name):
                    continue
                if _declared_raw(callee):
                    result = [fn, callee]
                    break
                if _has_verification(callee.node):
                    continue  # verified hop: taint stops here
                sub = raw_chain(callee, stack)
                if sub is not None:
                    result = [fn] + sub
                    break
            stack.discard(fn)
            memo[fn] = result
            return result

        for fn in project.functions.values():
            if not fn.module.rel_path.startswith(DATA_PLANE_PREFIXES):
                continue
            if not _is_read_fn(fn) or _declared_raw(fn):
                continue
            if _has_verification(fn.node):
                continue
            if _serves_consumer_verified_rpc(fn):
                continue
            chain = raw_chain(fn, set())
            if chain is None:
                continue
            path = " -> ".join(f.short() for f in chain)
            yield self.finding(
                fn.module, fn.node,
                f"read path `{fn.short()}` returns bytes from the "
                f"declared-raw primitive `{chain[-1].short()}` "
                f"({path}) with no checksum verification on the chain — "
                "verify here, or route through a verified variant, or mark "
                "this function raw on its `def` line with justification",
            )
