"""TPL013 — interprocedural checksum taint through the read path.

TPL005 judges one function at a time and credits *any* delegation to a
read-named callee, because it cannot see what that callee does. The gap:
a wrapper that delegates to the **declared-raw** primitive —

    def read_cached(self, block_id):
        return self.store.read(block_id)   # raw pread, disable=TPL005

— passes TPL005 on both sides (the wrapper delegates; the primitive is
suppressed with justification), yet unverified bytes escape the data
plane. That is precisely the bug class behind silent-corruption reads.

This rule walks the resolved call graph instead of trusting names. A
function whose ``# tpulint: disable=TPL005`` sits on its ``def`` line is
*declared raw*: intentionally unverified, safe only under a verifying
caller. For every other data-plane read function, taint propagates along
resolved read-delegation edges (plain calls and ``to_thread``/executor
bridges alike — threading changes where code runs, not whether bytes were
checked): a function is flagged when it performs no verification of its
own and some resolved chain reaches a declared-raw read with no
verification anywhere between. The full chain appears in the message.

Unresolved delegation stays TPL005's territory — no resolution, no
finding.
"""

from __future__ import annotations

from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.rules.checksum import (
    DATA_PLANE_PREFIXES,
    _has_verification,
    _is_read_name,
    _returns_value,
)


def _declared_raw(fn: FunctionInfo) -> bool:
    return fn.module.suppressed("TPL005", fn.node.lineno)


def _is_read_fn(fn: FunctionInfo) -> bool:
    return _is_read_name(fn.name) and _returns_value(fn.node, fn.module)


@register
class ChecksumTaintEscape(ProjectRule):
    id = "TPL013"
    name = "checksum-taint-escape"
    summary = ("data-plane read path resolves (transitively) to a "
               "declared-raw read with no CRC32C verification on the way — "
               "unverified bytes escape the data plane")

    def check_project(self, project: Project) -> Iterator[Finding]:
        #: fn -> chain down to the raw primitive, or None if clean
        memo: dict[FunctionInfo, list[FunctionInfo] | None] = {}

        def raw_chain(fn: FunctionInfo,
                      stack: set[FunctionInfo]) -> list[FunctionInfo] | None:
            """Chain from ``fn`` to a declared-raw read it taints from,
            given that ``fn`` itself does not verify."""
            if fn in memo:
                return memo[fn]
            if fn in stack:
                return None
            stack.add(fn)
            result = None
            for edge in fn.calls:
                if edge.kind == "task":
                    continue  # spawned readers return via their own awaiters
                callee = edge.callee
                if not _is_read_name(callee.name):
                    continue
                if _declared_raw(callee):
                    result = [fn, callee]
                    break
                if _has_verification(callee.node):
                    continue  # verified hop: taint stops here
                sub = raw_chain(callee, stack)
                if sub is not None:
                    result = [fn] + sub
                    break
            stack.discard(fn)
            memo[fn] = result
            return result

        for fn in project.functions.values():
            if not fn.module.rel_path.startswith(DATA_PLANE_PREFIXES):
                continue
            if not _is_read_fn(fn) or _declared_raw(fn):
                continue
            if _has_verification(fn.node):
                continue
            chain = raw_chain(fn, set())
            if chain is None:
                continue
            path = " -> ".join(f.short() for f in chain)
            yield self.finding(
                fn.module, fn.node,
                f"read path `{fn.short()}` returns bytes from the "
                f"declared-raw primitive `{chain[-1].short()}` "
                f"({path}) with no checksum verification on the chain — "
                "verify here, or route through a verified variant, or mark "
                "this function raw on its `def` line with justification",
            )
