"""TPL025 — checkpoint publish-before-durable ordering (sibling of TPL023).

The two-phase checkpoint commit's all-or-nothing guarantee rests on one
ordering invariant, one level above TPL023's Raft version: nothing may make
a checkpoint *visible* — the atomic manifest publish, a rename-publish, an
ack to the coordinator — until the shard data it references is durably
written and verified. Publish first and crash (or lose the chunkserver)
before the shards land, and readers restore a manifest whose payloads
don't exist: a torn checkpoint that the staging discipline exists to make
impossible.

Proven on the CFG with a forward **must**-analysis: the lattice value is
the set of durable-write sites executed on *every* path into a node; any
publish-classified call whose in-state is empty has some path on which the
checkpoint becomes visible before anything was durably staged. (TPL023 is
the may-analysis dual — "did a send already happen on some path before
this persist"; here dominance is the property, so the join is
intersection.) A durable call only counts when it is actually awaited
(directly or inside an awaited expression such as ``asyncio.gather`` —
a ``create_task`` that is never awaited has merely *scheduled* the write).

Publish calls: ``publish_*`` method tails (``publish_checkpoint``,
``publish_manifest``, …), ``rename_file`` (the generic atomic-publish
namespace primitive), and commit-acks (``ack``/``send_ack``). Durable
calls: ``create_file``/``complete_file``/``publish_staged_batch``/
``save_shard`` tails, ``write_staged*``/``verify_*``/``persist*``
prefixes, and ``_verify_staged``. Scoped to checkpoint modules
(``tpudfs/**/*checkpoint*``): these names are only a commit-protocol
contract there.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.cfg import Node, cfg_for
from tpudfs.analysis.dataflow import MustAnalysis, solve
from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

_PUBLISH_TAILS = {"rename_file", "ack", "send_ack"}
_PUBLISH_PREFIXES = ("publish_",)
_DURABLE_TAILS = {"create_file", "complete_file", "publish_staged_batch",
                  "save_shard", "fsync"}
_DURABLE_PREFIXES = ("write_staged", "verify_", "_verify_", "persist")


def _classify_call(call: ast.Call) -> str | None:
    """"publish" | "durable" | None for one call site."""
    name = dotted_name(call.func) or ""
    tail = name.split(".")[-1]
    if tail in _PUBLISH_TAILS or tail.startswith(_PUBLISH_PREFIXES):
        return "publish"
    if tail in _DURABLE_TAILS or tail.startswith(_DURABLE_PREFIXES):
        return "durable"
    return None


class _DurablesSeen(MustAnalysis):
    """Must-set of durable-write sites executed on every path in."""

    def __init__(self, durables: dict[int, ast.Call]):
        self._durables = durables

    def transfer(self, node: Node, value):
        for sub in node.walk():
            if id(sub) in self._durables:
                value = value | {id(sub)}
        return value


@register
class CheckpointPublishOrdering(Rule):
    id = "TPL025"
    name = "ckpt-publish-before-durable"
    summary = ("a checkpoint publish/ack is not dominated by a durable "
               "shard write or verification — on some path the manifest "
               "becomes visible before the data it references is durable")
    doc = (
        "The two-phase checkpoint commit is all-or-nothing only if "
        "nothing makes the checkpoint visible (manifest publish, rename-"
        "publish, coordinator ack) before its shard data is durably "
        "written and verified. This rule proves the ordering on the CFG "
        "with a must-analysis: the set of awaited durable-write sites "
        "executed on EVERY path is tracked forward, and any publish call "
        "whose must-set is empty is flagged — some path reaches it with "
        "nothing staged, so a crash right after leaves readers a manifest "
        "over missing payloads. A durable write merely scheduled via "
        "create_task does not count; only awaited writes do. Scoped to "
        "checkpoint modules (tpudfs/**/*checkpoint*)."
    )
    example = """\
async def commit(self, step):
    await self.client.publish_checkpoint(       # visible first...
        self.base, step, src, dst)
    await self.client.create_file(src, body)    # ...durable after
"""
    fix = ("Stage and verify every shard (awaited create_file / "
           "_verify_staged / publish_staged_batch) BEFORE the publish or "
           "ack; never fire-and-forget the durable writes.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not module.rel_path.startswith("tpudfs/"):
            return
        stem = module.rel_path.rsplit("/", 1)[-1]
        if "checkpoint" not in stem:
            return
        for fn in ast.walk(module.tree):
            if isinstance(fn, ast.AsyncFunctionDef):
                yield from self._check_fn(module, fn)

    def _check_fn(self, module: ModuleInfo,
                  fn: ast.AsyncFunctionDef) -> Iterator[Finding]:
        parents: dict[int, ast.AST] = {}
        publishes: dict[int, ast.Call] = {}
        durables: dict[int, ast.Call] = {}
        for sub in ast.walk(fn):
            if module.enclosing_function(sub) is not fn:
                continue
            for child in ast.iter_child_nodes(sub):
                parents[id(child)] = sub
            if not isinstance(sub, ast.Call):
                continue
            kind = _classify_call(sub)
            if kind == "publish":
                publishes[id(sub)] = sub
            elif kind == "durable" and self._is_awaited(sub, parents):
                durables[id(sub)] = sub
        if not publishes:
            return

        cfg = cfg_for(module, fn)
        res = solve(cfg, _DurablesSeen(durables))
        locator: dict[int, Node] = {}
        for node in cfg.nodes:
            for sub in node.walk():
                locator.setdefault(id(sub), node)

        for call in sorted(publishes.values(), key=lambda c: c.lineno):
            node = locator.get(id(call))
            if node is None:
                continue
            pair = res.get(node.index)
            seen = pair[0] if pair and pair[0] is not None else frozenset()
            # Durable calls in the SAME node that precede the publish
            # lexically also dominate it (statement-granular CFG).
            same = {
                did for did in durables
                if locator.get(did) is node
                and self._precedes(durables[did], call)
            }
            if seen or same:
                continue
            name = dotted_name(call.func) or "publish"
            yield self.finding(
                module, call,
                f"checkpoint publish ordering: `{name.split('.')[-1]}` "
                "makes the checkpoint visible here, but no awaited "
                "durable shard write/verification dominates this call — "
                "on some path the manifest publishes before the data it "
                "references is durable, and a crash right after leaves "
                "readers a manifest over missing payloads; stage and "
                "verify the shards first, then publish",
            )

    @staticmethod
    def _is_awaited(call: ast.Call, parents: dict[int, ast.AST]) -> bool:
        """True when ``call`` sits inside an awaited expression (directly,
        or e.g. as an ``asyncio.gather`` argument) — walking the parent
        chain up to the enclosing statement."""
        node: ast.AST = call
        while True:
            parent = parents.get(id(node))
            if parent is None or isinstance(parent, ast.stmt):
                return isinstance(node, ast.Await) or isinstance(parent, ast.Await)
            if isinstance(parent, ast.Await):
                return True
            node = parent

    @staticmethod
    def _precedes(a: ast.AST, b: ast.AST) -> bool:
        return (a.lineno, a.col_offset) < (b.lineno, b.col_offset)
