"""TPL060-TPL064 — tpuflow: zero-copy rules on the byte-cost ledger.

The tpuperf rules (TPL030-034) catch local copy shapes; the byteflow
ledger (:mod:`tpudfs.analysis.byteflow`) adds the whole-route view.
These five rules sit between the two: four site-level zero-copy shapes
that the ledger counts but the TPL03x heuristics deliberately skip, and
one route-level budget comparison the ledger alone can express.

- **TPL060** — memoryview escape: a value with zero-copy ``memoryview``
  provenance coerced back to ``bytes`` in a hot function. The view was
  the optimization; ``bytes(view)`` silently undoes it.
- **TPL061** — per-frame allocation in a stream loop: a fresh buffer
  (``bytearray(n)`` / ``np.zeros``) allocated every iteration of a hot
  loop with a loop-invariant size and no escape from the iteration —
  hoist it or use a ring like ``writestream.py`` does.
- **TPL062** — hidden stdlib copy: ``b"".join([one_part])``,
  ``bytes(bytearray(...))`` round-trips, and full-buffer ``.hex()`` /
  ``.decode()`` on data payloads in hot functions.
- **TPL063** — double serialization: the same unmodified buffer passed
  through ``pack``/``packb``/``dumps`` twice on one path (a forward
  may-analysis over the CFG, killed on reassignment).
- **TPL064** — cache-route copy budget: the byteflow ledger's
  cache-hit route must not cost more copies per byte than the direct
  warm-infeed read it exists to beat.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis import byteflow
from tpudfs.analysis.bufferflow import (
    PAYLOAD_NAME_RE,
    buffer_flow,
    env_from,
    kind_of,
)
from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.cfg import cfg_for
from tpudfs.analysis.hotpath import hot_paths
from tpudfs.analysis.linter import (Finding, ProjectRule, profile_units,
                                    register)

#: Serialize-direction callees for TPL063 (deserializers cannot
#: "double-serialize" a buffer; unpack of a packed buffer is the normal
#: wire round-trip).
_PACK_CALLS = {"pack", "packb", "dumps"}

#: Allocation callees for TPL061: each call materializes a fresh
#: len(n) buffer.
_ALLOC_CALLS = {"bytearray", "zeros", "empty"}


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _hot_functions(
    project: Project, rule_id: str | None = None
) -> Iterator[FunctionInfo]:
    hp = hot_paths(project)
    fns = (fn for fn in project.functions.values() if hp.is_hot(fn))
    yield from profile_units(rule_id, fns, lambda fn: fn.qualname)


def _own_nodes(fn: FunctionInfo):
    return cfg_for(fn.module, fn.node).nodes


def _in_env(fn: FunctionInfo, node):
    flow = buffer_flow(fn.module, fn.node)
    in_facts, _ = flow.get(node.index, (None, None))
    return env_from(in_facts)


def _payloadish(expr: ast.AST, env) -> bool:
    """Payload-name anchored buffer evidence (mirrors byteflow)."""
    if isinstance(expr, ast.Name):
        return bool(PAYLOAD_NAME_RE.match(expr.id)) or bool(env.get(expr.id))
    if isinstance(expr, ast.Attribute):
        return bool(PAYLOAD_NAME_RE.match(expr.attr))
    return False


@register
class MemoryviewEscape(ProjectRule):
    id = "TPL060"
    name = "memoryview-escape"
    summary = ("a zero-copy `memoryview` coerced back to `bytes` in a "
               "hot function — the copy the view existed to avoid")
    doc = (
        "A `memoryview` on the data plane is an explicit zero-copy "
        "decision: frames are sliced, checksummed and scattered to the "
        "socket without materializing. `bytes(view)` silently reverses "
        "it — one full-buffer memcpy, usually to satisfy a consumer "
        "that would have accepted the view (msgpack bin-packs any "
        "buffer; sockets `writelines` scatter lists; caches store "
        "buffer-protocol objects unchanged). The rule uses buffer "
        "provenance from the dataflow solver and fires only where the "
        "coerced value provably has `memoryview` provenance in a "
        "hot-path function; `.tobytes()` on a view is flagged the same "
        "way. Cold config/tool code stays silent."
    )
    example = """\
view = memoryview(frame)[off:off + n]   # zero-copy slice
await cache.put(block_id, bytes(view))  # full memcpy right back
"""
    fix = ("Keep the view: every data-plane consumer (msgpack, "
           "writelines, crc32c, the block cache) accepts buffer-protocol "
           "objects. If an immutable owner is truly required, copy once "
           "at the producer, not per consumer.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for fn in _hot_functions(project, self.id):
            module = fn.module
            seen: set[tuple[int, int]] = set()
            for node in _own_nodes(fn):
                env = _in_env(fn, node)
                for top in node.exprs():
                    for expr in ast.walk(top):
                        hit = self._escape(expr, env)
                        if hit is None:
                            continue
                        key = (getattr(expr, "lineno", 0),
                               getattr(expr, "col_offset", 0))
                        if key in seen:
                            continue
                        seen.add(key)
                        yield self.finding(
                            module, expr,
                            f"{hit} coerces a zero-copy `memoryview` "
                            f"back to `bytes` in hot `{fn.short()}` — "
                            "one full-buffer memcpy; data-plane "
                            "consumers accept the view unchanged",
                        )

    @staticmethod
    def _escape(expr: ast.AST, env) -> str | None:
        if not isinstance(expr, ast.Call):
            return None
        name = _call_name(expr)
        if name == "bytes" and len(expr.args) == 1:
            arg = expr.args[0]
            if isinstance(arg, ast.Name) \
                    and "memoryview" in env.get(arg.id, set()):
                return f"`bytes({arg.id})`"
            if isinstance(arg, ast.Call) \
                    and _call_name(arg) == "memoryview":
                return "`bytes(memoryview(...))`"
            if kind_of(arg, env) == "memoryview":
                return "`bytes(<view>)`"
        if name == "tobytes" and isinstance(expr.func, ast.Attribute) \
                and isinstance(expr.func.value, ast.Name) \
                and "memoryview" in env.get(expr.func.value.id, set()):
            return f"`{expr.func.value.id}.tobytes()`"
        return None


@register
class PerFrameAllocation(ProjectRule):
    id = "TPL061"
    name = "per-frame-allocation"
    summary = ("fresh buffer allocated every iteration of a hot stream "
               "loop with a loop-invariant size — hoist it or reuse a "
               "ring like writestream.py does")
    doc = (
        "`bytearray(FRAME_SIZE)` inside a per-frame loop allocates and "
        "zeroes the same-size buffer thousands of times per block; the "
        "stream engine (`writestream.py`) carries a reusable frame "
        "buffer for exactly this reason. The rule fires on "
        "`bytearray(n)` / `np.zeros(n)` / `np.empty(n)` at loop depth "
        ">= 1 in a hot function when the size arguments are loop-"
        "invariant (constants or names not rebound in the loop) and "
        "the buffer does not escape the iteration (not appended, "
        "stored, returned or yielded) — i.e. when hoisting the "
        "allocation above the loop is a semantics-preserving edit."
    )
    example = """\
while remaining:                     # hot per-frame loop
    buf = bytearray(FRAME_SIZE)      # fresh allocation every frame
    n = await r.readinto(buf)
    consume(buf[:n])
"""
    fix = ("Allocate once above the loop and reuse: `buf = "
           "bytearray(FRAME_SIZE)` outside, `readinto(buf)` inside — "
           "or adopt the writestream ring if frames overlap in flight.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for fn in _hot_functions(project, self.id):
            module = fn.module
            for loop in ast.walk(fn.node):
                if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                    continue
                if module.enclosing_function(loop) is not fn.node:
                    continue
                rebound = self._rebound_names(loop)
                for stmt in loop.body:
                    for n in ast.walk(stmt):
                        if not (isinstance(n, ast.Assign)
                                and len(n.targets) == 1
                                and isinstance(n.targets[0], ast.Name)
                                and isinstance(n.value, ast.Call)):
                            continue
                        call = n.value
                        cname = _call_name(call)
                        if cname not in _ALLOC_CALLS or not call.args:
                            continue
                        if not all(self._invariant(a, rebound)
                                   for a in call.args):
                            continue
                        target = n.targets[0].id
                        if self._escapes(loop, target, n):
                            continue
                        yield self.finding(
                            module, call,
                            f"`{cname}(...)` allocates a fresh buffer "
                            f"every iteration of a hot loop in "
                            f"`{fn.short()}` with a loop-invariant size "
                            "— hoist the allocation above the loop (or "
                            "reuse the stream ring) and refill it in "
                            "place",
                        )

    @staticmethod
    def _rebound_names(loop: ast.AST) -> set[str]:
        """Names assigned anywhere in the loop (incl. the loop target):
        a size argument drawn from these is not loop-invariant."""
        out: set[str] = set()
        if isinstance(loop, (ast.For, ast.AsyncFor)):
            for t in ast.walk(loop.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
        for n in ast.walk(loop):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            out.add(leaf.id)
        return out

    @staticmethod
    def _invariant(arg: ast.AST, rebound: set[str]) -> bool:
        for n in ast.walk(arg):
            if isinstance(n, ast.Call):
                return False
            if isinstance(n, ast.Name) and n.id in rebound:
                return False
        return True

    @staticmethod
    def _escapes(loop: ast.AST, name: str, defining: ast.AST) -> bool:
        """Does ``name`` leave the iteration? Appends, container/attr
        stores, returns, yields and task spawns all retain the buffer —
        hoisting would alias every retained copy to one ring slot."""
        for n in ast.walk(loop):
            if isinstance(n, ast.Call):
                cname = _call_name(n)
                if cname in ("append", "extend", "put", "put_nowait",
                             "create_task", "ensure_future"):
                    if any(isinstance(leaf, ast.Name) and leaf.id == name
                           for a in n.args for leaf in ast.walk(a)):
                        return True
            if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and n.value is not None:
                if any(isinstance(leaf, ast.Name) and leaf.id == name
                       for leaf in ast.walk(n.value)):
                    return True
            if isinstance(n, ast.Assign) and n is not defining:
                for t in n.targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        if any(isinstance(leaf, ast.Name)
                               and leaf.id == name
                               for leaf in ast.walk(n.value)):
                            return True
        return False


@register
class HiddenStdlibCopy(ProjectRule):
    id = "TPL062"
    name = "hidden-stdlib-copy"
    summary = ("stdlib idiom that copies a full buffer without looking "
               "like one: one-part `join`, `bytes(bytearray(...))` "
               "round-trip, payload `.hex()`/`.decode()`")
    doc = (
        "Three stdlib shapes memcpy a whole buffer while reading as "
        "bookkeeping: `b\"\".join([part])` of a single-element literal "
        "(the join of one part IS a copy of it), "
        "`bytes(bytearray(data))` (two full copies to end up with the "
        "bytes you started from), and `.hex()` / `.decode()` over a "
        "data payload (2x-expansion string materialization — fine for "
        "a 16-byte digest, catastrophic for a 1 MiB block in a log "
        "line). Fires in hot-path functions only; payload evidence "
        "comes from the buffer-provenance dataflow plus payload "
        "naming, so header peeks stay silent."
    )
    example = """\
frame = b"".join([payload])       # one part: the join is a pure copy
logger.debug("got %s", payload.hex())  # 2 MiB string per 1 MiB block
"""
    fix = ("Use the part directly (`frame = payload`), keep the "
           "original `bytes` instead of round-tripping through "
           "`bytearray`, and log sizes/digests (`len(payload)`, "
           "`crc32c(payload)`), never hex dumps of payloads.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for fn in _hot_functions(project, self.id):
            module = fn.module
            seen: set[tuple[int, int]] = set()
            for node in _own_nodes(fn):
                env = _in_env(fn, node)
                for top in node.exprs():
                    for expr in ast.walk(top):
                        msg = self._hidden_copy(expr, env)
                        if msg is None:
                            continue
                        key = (getattr(expr, "lineno", 0),
                               getattr(expr, "col_offset", 0))
                        if key in seen:
                            continue
                        seen.add(key)
                        yield self.finding(
                            module, expr,
                            f"{msg} in hot `{fn.short()}` — a hidden "
                            "full-buffer copy; see TPL062 fix",
                        )

    @staticmethod
    def _hidden_copy(expr: ast.AST, env) -> str | None:
        if not isinstance(expr, ast.Call):
            return None
        name = _call_name(expr)
        if name == "join" and isinstance(expr.func, ast.Attribute) \
                and len(expr.args) == 1 \
                and isinstance(expr.args[0], (ast.List, ast.Tuple)) \
                and len(expr.args[0].elts) == 1:
            return "`join` of a single-element literal"
        if name == "bytes" and len(expr.args) == 1 \
                and isinstance(expr.args[0], ast.Call) \
                and _call_name(expr.args[0]) == "bytearray" \
                and expr.args[0].args:
            return "`bytes(bytearray(...))` round-trip"
        if name in ("hex", "decode") and not expr.args \
                and isinstance(expr.func, ast.Attribute) \
                and _payloadish(expr.func.value, env):
            return f"payload `.{name}()`"
        return None


@register
class DoubleSerialization(ProjectRule):
    id = "TPL063"
    name = "double-serialization"
    summary = ("the same unmodified buffer serialized twice on one "
               "path — two O(n) pack passes where one envelope would do")
    doc = (
        "Packing a payload with msgpack/struct and then packing the "
        "result (or the same buffer) again — e.g. a handler that packs "
        "`data` into a response dict that the transport packs once "
        "more — doubles the serialization cost of every byte and is "
        "why scatter framing keeps payload bytes OUT of the envelope. "
        "The rule runs a forward may-analysis over the CFG: a "
        "`pack`/`packb`/`dumps` of a payload-provenance name generates "
        "a serialized fact, reassignment of the name kills it, and a "
        "second pack of a name whose fact is still live fires on that "
        "path. Hot-path functions only."
    )
    example = """\
body = packb({"data": payload})        # pass 1 over the payload
frame = packb({"hdr": hdr, "body": body, "raw": payload})  # pass 2
"""
    fix = ("Serialize once: keep the payload out of the packed "
           "envelope and carry it as a separate scatter segment "
           "(`writelines([header, payload])`), the blockport `_d` "
           "framing shape.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for fn in _hot_functions(project, self.id):
            module = fn.module
            for call, name in self._double_packs(fn):
                yield self.finding(
                    module, call,
                    f"`{name}` is serialized again on a path where it "
                    f"was already packed unmodified in `{fn.short()}` "
                    "— two O(n) passes over the same bytes; pack once "
                    "and scatter the payload outside the envelope",
                )

    def _double_packs(self, fn: FunctionInfo):
        cfg = cfg_for(fn.module, fn.node)
        flow = buffer_flow(fn.module, fn.node)
        gens: dict[int, set[str]] = {}
        kills: dict[int, set[str]] = {}
        for node in cfg.nodes:
            env = env_from(flow.get(node.index, (None, None))[0])
            g: set[str] = set()
            k: set[str] = set()
            for top in node.exprs():
                for expr in ast.walk(top):
                    packed = self._packed_name(expr, env)
                    if packed is not None:
                        g.add(packed)
                if isinstance(top, (ast.Assign, ast.AugAssign,
                                    ast.AnnAssign)):
                    targets = top.targets if isinstance(top, ast.Assign) \
                        else [top.target]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            k.add(t.id)
            gens[node.index], kills[node.index] = g, k

        ins: dict[int, set[str]] = {n.index: set() for n in cfg.nodes}
        changed = True
        while changed:
            changed = False
            for node in cfg.rpo():
                in_facts: set[str] = set()
                for pred, _kind in node.preds:
                    in_facts |= (ins[pred.index] - kills[pred.index]) \
                        | gens[pred.index]
                if in_facts != ins[node.index]:
                    ins[node.index] = in_facts
                    changed = True

        reported: set[tuple[str, int]] = set()
        for node in cfg.nodes:
            live = ins[node.index]
            if not live:
                continue
            env = env_from(flow.get(node.index, (None, None))[0])
            for top in node.exprs():
                for expr in ast.walk(top):
                    name = self._packed_name(expr, env)
                    if name is None or name not in live:
                        continue
                    key = (name, getattr(expr, "lineno", 0))
                    if key in reported:
                        continue
                    reported.add(key)
                    yield expr, name

    @staticmethod
    def _packed_name(expr: ast.AST, env) -> str | None:
        if not (isinstance(expr, ast.Call)
                and _call_name(expr) in _PACK_CALLS):
            return None
        for a in expr.args:
            if isinstance(a, ast.Name) and _payloadish(a, env):
                return a.id
        return None


@register
class CacheRouteCopyBudget(ProjectRule):
    id = "TPL064"
    name = "cache-route-copy-budget"
    summary = ("the cache-hit read route costs more ledger copies per "
               "byte than the direct read path it exists to beat")
    doc = (
        "A cache hit that re-buffers and re-serializes what the direct "
        "path scatters is slower than no cache — the 0.109 GB/s "
        "cache_read regression against a 1.3 GB/s direct read. This "
        "rule compares two routes of the byteflow ledger "
        "(`tpudfs/analysis/byteflow.py`): the `cache_hit_read` route's "
        "statically-counted full-buffer copies must not exceed the "
        "`warm_infeed_read` route's. It fires with the exact excess "
        "hops (`file:line`), so the diff that adds a copy to the cache "
        "path shows up as a named regression, not a benchmark mystery. "
        "The committed ledger gate (`--check-ledger`) enforces the "
        "same budget in CI per route; this rule enforces the "
        "cache-vs-direct *relation* inside the tree itself."
    )
    example = """\
# cache hit: stat + dict copy + msgpack of the payload (3 copies)
return {"data": bytes(cached), "total_size": total}
# direct read: scatter-framed memoryview straight to the socket (1)
"""
    fix = ("Serve cache hits the way direct reads are served: return "
           "`{\"data_parts\": [memoryview(cached)]}` through the "
           "blockport scatter framing, and skip per-hit disk stats the "
           "signature check already covers.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        ledger = byteflow.compute_ledger(project)
        routes = ledger.get("routes", {})
        cache = routes.get(byteflow.CACHE_ROUTE)
        direct = routes.get(byteflow.DIRECT_ROUTE)
        if not cache or not direct:
            return
        if not cache["functions"] or not direct["functions"]:
            return  # routes absent from this tree (fixture subsets)
        if cache["copies"] <= direct["copies"]:
            return
        anchor = self._anchor(project, cache["functions"])
        if anchor is None:
            return
        cache_hops = [h for h in cache["hops"] if " copy:" in h]
        yield self.finding(
            anchor.module, anchor.node,
            f"cache-hit route costs {cache['copies']} full-buffer "
            f"copies vs {direct['copies']} on the direct read path "
            f"({'; '.join(cache_hops[:4])}) — serve cached blocks "
            "through the scatter-framing path so a hit is never "
            "slower than a miss",
        )

    @staticmethod
    def _anchor(project: Project, quals) -> FunctionInfo | None:
        """Prefer a route *entry* function (the reader-facing handler)
        over the alphabetically-first helper as the finding anchor."""
        import re

        spec = next(s for s in byteflow.ROUTES
                    if s.name == byteflow.CACHE_ROUTE)
        pats = [re.compile(p) for p in spec.entries]
        for qual in quals:
            if any(p.fullmatch(qual) for p in pats) \
                    and qual in project.functions:
                return project.functions[qual]
        for qual in quals:
            if qual in project.functions:
                return project.functions[qual]
        return None
