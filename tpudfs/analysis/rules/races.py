"""TPL020 — static cross-executor race detector.

tpudfs runs its control plane on one asyncio loop and offloads disk I/O
with ``asyncio.to_thread`` / ``run_in_executor``. That split creates the
codebase's highest-risk bug class: instance attributes and module globals
touched both *on the loop* and *on a worker thread*. Coroutines interleave
only at ``await``, so loop-only state needs no lock at all — which makes
it easy to forget that the moment one access moves behind ``to_thread``,
that comfortable model is gone and only a ``threading.Lock`` (held on
BOTH sides) restores it. ``asyncio.Lock`` does not help: it serializes
coroutines on the loop and cannot even be acquired from a worker thread.

The detector:

1. classifies every function's execution context from call-graph roots
   (:meth:`Project.execution_contexts`): event-loop coroutine, ``to_thread``
   / executor / ``threading.Thread`` worker, background ``create_task``
   task — collapsed to the OS-thread dimension (task == loop thread);
2. collects every ``self.*`` attribute access (receiver chains resolved
   through inferred attribute types, mutator calls and subscript stores
   count as writes) and every module-global access (a global is tracked
   once some function declares ``global X`` and writes it) per context;
3. flags state written in one thread dimension and accessed in the other
   when no common ``threading`` lock is provably held on both paths —
   "provably held" is the interprocedural must-analysis in
   :class:`~tpudfs.analysis.lockinfo.HeldLockMap`, so the
   ``_locked_helper`` idiom (callers hold the mutex) is credited.

Out of scope, deliberately: worker-vs-worker races (the executor pool is
ours; today every offloaded callable touches disjoint state — a dedicated
pass can ratchet this later), writes inside ``__init__``-family methods
(construction happens-before publication), and containers whose
thread-safety comes from the GIL'd method granularity — a single ``dict``
get/set is atomic, but the rule still flags it because check-then-act
sequences on it are not.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo, Project, module_qualname
from tpudfs.analysis.linter import Finding, ProjectRule, dotted_name, register
from tpudfs.analysis.lockinfo import HeldLockMap, LockRegistry

#: Writes in these methods happen before the object is visible to any
#: other context.
_CTOR_NAMES = {"__init__", "__new__", "__post_init__", "__setstate__"}

#: Receiver-method calls that mutate the receiver's state.
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "clear", "sort", "reverse", "update", "setdefault", "add", "discard",
    "appendleft", "extendleft", "difference_update", "intersection_update",
    "symmetric_difference_update", "put_nowait", "__setitem__",
}

_DIM_LABEL = {
    "worker": "a to_thread/executor worker thread",
    "loop": "the event loop",
}


@dataclass
class _Access:
    fn: FunctionInfo
    site: ast.AST
    kind: str  # "read" | "write"
    dims: frozenset  # OS-thread dimensions of fn
    labels: frozenset  # full context labels, for the message


def _chain_parts(node: ast.Attribute) -> list[str] | None:
    name = dotted_name(node)
    return name.split(".") if name else None


def _module_globals(project: Project) -> dict[str, set[str]]:
    """Per module (dotted name): globals some function writes via a
    ``global`` declaration — the only module state that can race."""
    out: dict[str, set[str]] = {}
    for mod in project.modules.values():
        modname = module_qualname(mod.rel_path)
        names: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Global):
                names.update(node.names)
        if names:
            out[modname] = names
    return out


def _fn_local_names(fn: FunctionInfo) -> set[str]:
    """Names that are local to ``fn`` (params + stores without a global
    declaration) — accesses to these shadow any module global."""
    node = fn.node
    args = node.args
    local = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        local.add(args.vararg.arg)
    if args.kwarg:
        local.add(args.kwarg.arg)
    declared_global: set[str] = set()
    for sub in ast.walk(node):
        if fn.module.enclosing_function(sub) is not node:
            continue
        if isinstance(sub, ast.Global):
            declared_global.update(sub.names)
        elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            local.add(sub.id)
    return local - declared_global


@register
class CrossExecutorRace(ProjectRule):
    id = "TPL020"
    name = "cross-executor-race"
    summary = ("state written on one side of the loop/worker-thread "
               "boundary and accessed on the other with no common "
               "threading.Lock held on both paths")
    doc = (
        "Coroutines interleave only at `await`, so loop-only state needs "
        "no lock — until one access moves behind asyncio.to_thread and "
        "the comfortable model silently stops applying. The detector "
        "classifies every function's execution context from call-graph "
        "roots (loop coroutine / to_thread-executor worker / create_task "
        "task, collapsed to the OS-thread dimension), collects self.* "
        "and module-global accesses per context, and flags state written "
        "in one thread dimension and touched in the other unless one "
        "threading.Lock is provably held on every path at both sites "
        "(interprocedural must-analysis, so the `_locked_helper` idiom "
        "is credited). asyncio.Lock does NOT count: it serializes "
        "coroutines on the loop and cannot be held by executor code."
    )
    example = """\
class Cache:
    async def refresh(self):
        await asyncio.to_thread(self._scan)   # worker thread...
    def _scan(self):
        self.stats = compute()                # ...writes self.stats
    async def report(self):
        return self.stats                     # loop reads it, no lock
"""
    fix = ("Guard both sides with one threading.Lock (short holds only), "
           "or confine the state to one context and pass snapshots "
           "across the boundary.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        contexts = project.execution_contexts()
        classified = {
            fn: labels for fn, labels in contexts.items()
            if project.thread_dim(labels)
        }
        if not classified:
            return

        #: access key -> accesses. Keys: ("attr", class_qualname, attr) |
        #: ("global", module, name)
        by_key: dict[tuple, list[_Access]] = {}
        globals_by_mod = _module_globals(project)

        for fn, labels in classified.items():
            dims = project.thread_dim(labels)
            self._collect_attr_accesses(project, fn, dims, labels, by_key)
            self._collect_global_accesses(
                project, fn, dims, labels, globals_by_mod, by_key)

        # Candidate races first; the lock analysis only runs for them.
        held: HeldLockMap | None = None
        for key in sorted(by_key, key=str):
            accesses = by_key[key]
            writes = [a for a in accesses if a.kind == "write"]
            if not writes:
                continue
            racy = self._racy_pair(writes, accesses)
            if racy is None:
                continue
            if held is None:
                held = HeldLockMap(project, LockRegistry(project))
            finding = self._verify_pair(key, racy, accesses, writes, held)
            if finding is not None:
                yield finding

    # ------------------------------------------------------------ collection

    @staticmethod
    def _self_class(project: Project, fn: FunctionInfo):
        """The class ``self`` refers to inside ``fn`` — its own class, or
        for a closure nested in a method (the ``to_thread(scan)`` idiom),
        the enclosing method's class via the captured ``self``."""
        if fn.cls is not None:
            return fn.cls
        mod = fn.module
        modname = module_qualname(mod.rel_path)
        for anc in mod.ancestors(fn.node):
            if isinstance(anc, ast.ClassDef):
                return project.classes.get(f"{modname}.{mod.qualname(anc)}")
        return None

    def _collect_attr_accesses(self, project: Project, fn: FunctionInfo,
                               dims: frozenset, labels: frozenset,
                               by_key: dict) -> None:
        self_cls = self._self_class(project, fn)
        if self_cls is None:
            return
        exempt_writes = fn.name in _CTOR_NAMES
        mod = fn.module
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Attribute):
                continue
            if mod.enclosing_function(node) is not fn.node:
                continue
            parent = mod.parent(node)
            if isinstance(parent, ast.Attribute) \
                    and _chain_parts(parent) is not None:
                continue  # handled at the maximal chain
            parts = _chain_parts(node)
            if parts is None or parts[0] not in ("self", "cls"):
                continue

            def record(owner_parts: list[str], attr: str, kind: str,
                       site: ast.AST) -> None:
                if kind == "write" and exempt_writes:
                    return
                owner = project.attr_chain_class(self_cls, owner_parts) \
                    if owner_parts else self_cls
                if owner is None:
                    return
                key = ("attr", owner.qualname, attr)
                by_key.setdefault(key, []).append(
                    _Access(fn, site, kind, dims, labels))

            # Intermediate hops of the chain are reads of those attrs.
            for i in range(1, len(parts) - 1):
                record(parts[1:i], parts[i], "read", node)

            last = parts[-1]
            if isinstance(parent, ast.Call) and parent.func is node:
                # self.a.b.m(...) — a method call: `m` is behavior, the
                # accessed state is `b`; mutator names make it a write.
                if len(parts) >= 3:
                    kind = "write" if last in _MUTATORS else "read"
                    record(parts[1:-2], parts[-2], kind, node)
                # self.m(...) contributes nothing: the method's own
                # accesses are collected under its own contexts.
                return_read = False
            elif isinstance(node.ctx, (ast.Store, ast.Del)):
                record(parts[1:-1], last, "write", node)
                return_read = False
            elif isinstance(parent, ast.Subscript) and parent.value is node:
                sub_parent = mod.parent(parent)
                stored = isinstance(parent.ctx, (ast.Store, ast.Del))
                aug = isinstance(sub_parent, ast.AugAssign) \
                    and sub_parent.target is parent
                record(parts[1:-1], last,
                       "write" if stored or aug else "read", node)
                return_read = False
            else:
                return_read = True
            if return_read:
                aug_parent = mod.parent(node)
                if isinstance(aug_parent, ast.AugAssign) \
                        and aug_parent.target is node:
                    record(parts[1:-1], last, "write", node)
                else:
                    record(parts[1:-1], last, "read", node)

    def _collect_global_accesses(self, project: Project, fn: FunctionInfo,
                                 dims: frozenset, labels: frozenset,
                                 globals_by_mod: dict,
                                 by_key: dict) -> None:
        modname = module_qualname(fn.module.rel_path)
        tracked = globals_by_mod.get(modname)
        if not tracked:
            return
        local = _fn_local_names(fn)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Name) or node.id not in tracked:
                continue
            if node.id in local:
                continue
            if fn.module.enclosing_function(node) is not fn.node:
                continue
            kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) \
                else "read"
            key = ("global", modname, node.id)
            by_key.setdefault(key, []).append(
                _Access(fn, node, kind, dims, labels))

    # ---------------------------------------------------------- verification

    @staticmethod
    def _racy_pair(writes: list[_Access],
                   accesses: list[_Access]) -> tuple[_Access, _Access] | None:
        for w in writes:
            for a in accesses:
                if a is w:
                    continue
                if ("worker" in w.dims and "loop" in a.dims) or \
                        ("loop" in w.dims and "worker" in a.dims):
                    return w, a
        return None

    def _verify_pair(self, key: tuple, racy: tuple[_Access, _Access],
                     accesses: list[_Access], writes: list[_Access],
                     held: HeldLockMap) -> Finding | None:
        # A pair is safe when one threading lock is must-held at both
        # sites; the finding needs one UNSAFE pair.
        def guarded(w: _Access, a: _Access) -> bool:
            common = held.thread_locks_at(w.fn, w.site) \
                & held.thread_locks_at(a.fn, a.site)
            return bool(common)

        unsafe: tuple[_Access, _Access] | None = None
        for w in writes:
            for a in accesses:
                if a is w:
                    continue
                if not (("worker" in w.dims and "loop" in a.dims)
                        or ("loop" in w.dims and "worker" in a.dims)):
                    continue
                if not guarded(w, a):
                    unsafe = (w, a)
                    break
            if unsafe:
                break
        if unsafe is None:
            return None

        w, a = unsafe
        w_dim = "worker" if "worker" in w.dims else "loop"
        a_dim = "loop" if w_dim == "worker" else "worker"
        if key[0] == "attr":
            what = f"`{key[1].rsplit('.', 1)[-1]}.{key[2]}`"
        else:
            what = f"module global `{key[2]}` ({key[1]})"
        other = (f"{a.fn.module.rel_path}:"
                 f"{getattr(a.site, 'lineno', 0)} in `{a.fn.short()}`")
        return self.finding(
            w.fn.module, w.site,
            f"{what} is written on {_DIM_LABEL[w_dim]} in `{w.fn.short()}` "
            f"but {'written' if a.kind == 'write' else 'read'} on "
            f"{_DIM_LABEL[a_dim]} at {other} with no common threading.Lock "
            "held on both paths — a schedule-dependent race; guard both "
            "sides with one threading.Lock (asyncio.Lock does not protect "
            "against worker threads: it serializes coroutines on the loop "
            "and cannot be held by executor code)",
        )
