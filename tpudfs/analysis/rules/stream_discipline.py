"""TPL026 — stream discipline on the framed write path.

Sub-block framing is the write-path contract
(``tpudfs/common/writestream.py``, docs/write-pipeline.md): payload
moves as ~256 KiB frames that are CRC-folded, staged to disk and fanned
out downstream *as they arrive*. Code on that path that gulps a whole
header-declared payload in one ``await r.read(size)`` — or loops reads
into a local buffer that nothing consumes until the last byte lands —
reintroduces exactly the store-and-forward latency and O(block) memory
the pipeline removed, one layer at a time.

Scope is deliberately narrow: hot-path *async* functions (reachability
from the bench/data-plane roots, :mod:`tpudfs.analysis.hotpath`) whose
qualified name marks them as write-path or serve-loop code. The
disciplined idioms that remain legitimate stay silent:

- fixed-size reads (header peeks, constant chunk sizes);
- reads capped with ``min(...)`` — the bounded scatter-chunk loop;
- reads of a size the function first validates against a protocol cap
  (``if plen > _MAX_PAYLOAD: raise``) — the generic frame reader shape;
- accumulation where each chunk is ALSO handed to a per-iteration
  consumer (staged disk append, downstream relay send): the buffer is
  then a declared fallback alongside the streaming path, not the path.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo
from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.rules.perf import _call_name, _hot_functions

#: Function qualnames this rule polices: the write/forward/staging path
#: and the transport serve loops that carry it. Read paths are exempt by
#: design — a read's caller asked for whole bytes; the write path's
#: contract is frames.
_WRITE_PATH_RE = re.compile(
    r"write|replicat|stream|stage|persist|ingest|upload|_handle|_serve",
    re.IGNORECASE,
)

#: Stream-reader methods whose await pulls payload off a socket.
_READ_ATTRS = {"read", "readexactly"}

#: Call-name prefixes treated as "reads a chunk" for the accumulation
#: detector (covers in-tree helpers like ``_read_frame``).
_READ_CALL_PREFIXES = ("read", "_read", "recv", "_recv")

#: Constructors of local grow-only buffers. Scatter writes into a
#: buffer handed in from elsewhere (``segments[i][off:] = chunk``) are
#: the caller's discipline, not accumulation, and are not matched.
_CONTAINER_FACTORIES = {"bytearray", "list", "deque", "BytesIO"}


def _cap_guarded_names(fn_node: ast.AST) -> set[str]:
    """Names the function bounds-checks with a compare that raises or
    returns — ``if plen > _MAX_PAYLOAD: raise`` marks ``plen`` as a
    protocol-capped size, so reading it is a frame read, not a gulp."""
    out: set[str] = set()
    for n in ast.walk(fn_node):
        if not isinstance(n, ast.If):
            continue
        test = n.test
        if not isinstance(test, ast.Compare):
            continue
        if not any(isinstance(op, (ast.Gt, ast.GtE, ast.Lt, ast.LtE))
                   for op in test.ops):
            continue
        if not any(isinstance(b, (ast.Raise, ast.Return)) for b in n.body):
            continue
        for name in ast.walk(test):
            if isinstance(name, ast.Name):
                out.add(name.id)
    return out


def _container_names(fn_node: ast.AST) -> set[str]:
    """Local names bound to a fresh grow-only container."""
    out: set[str] = set()
    for n in ast.walk(fn_node):
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)):
            continue
        v = n.value
        if isinstance(v, ast.Call) and _call_name(v) in _CONTAINER_FACTORIES:
            out.add(n.targets[0].id)
        elif isinstance(v, ast.List) and not v.elts:
            out.add(n.targets[0].id)
        elif isinstance(v, ast.Constant) and v.value == b"":
            out.add(n.targets[0].id)
    return out


@register
class WritePathStreamDiscipline(ProjectRule):
    id = "TPL026"
    name = "write-path-stream-discipline"
    summary = ("whole-block `await r.read(size)` gulp or read-loop that "
               "only accumulates bytes on the framed write hot path — "
               "stage/forward each frame as it arrives instead of "
               "materializing the block")
    doc = (
        "Sub-block framing is the write-path contract (writestream.py, "
        "docs/write-pipeline.md): each ~256 KiB frame is CRC-folded, "
        "staged to disk and relayed downstream the moment it arrives, "
        "so chain latency is ~one block time plus a frame time per hop "
        "instead of a full store-and-forward per hop. This rule flags "
        "the two shapes that silently undo that on hot write/serve "
        "functions: (1) a single `await r.read(size)`/`readexactly(size)` "
        "of a variable, un-capped size — the whole-payload gulp; (2) a "
        "read loop whose chunks' ONLY use is growing a local buffer, so "
        "nothing downstream sees a byte until the loop ends. Fixed-size "
        "reads, `min(...)`-capped chunk reads, sizes the function "
        "bounds-checks against a protocol cap before reading, and loops "
        "that also hand each chunk to a per-iteration consumer (staged "
        "append, relay send) all stay silent."
    )
    example = """\
async def rpc_write_block(self, r, w, req):
    size = req["size"]
    data = await r.readexactly(size)     # whole-block gulp
    await self.store.write(req["block_id"], data)
"""
    fix = ("Consume the payload frame-at-a-time: read bounded chunks "
           "(`await r.read(min(FRAME_SIZE, remaining))` or the "
           "writestream frame protocol) and hand each one to the staged "
           "writer / downstream relay as it lands — see "
           "tpudfs/common/writestream.py and docs/write-pipeline.md.")

    def check_project(self, project) -> Iterator[Finding]:
        for fn, _entry in _hot_functions(project, self.id):
            if not fn.is_async:
                continue
            if not _WRITE_PATH_RE.search(fn.qualname):
                continue
            yield from self._gulp_reads(fn)
            yield from self._accumulate_only_loops(fn)

    # -------------------------------------------------- whole-payload gulp

    def _gulp_reads(self, fn: FunctionInfo) -> Iterator[Finding]:
        module = fn.module
        guarded = _cap_guarded_names(fn.node)
        for n in ast.walk(fn.node):
            if not isinstance(n, ast.Await):
                continue
            call = n.value
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in _READ_ATTRS):
                continue
            if module.enclosing_function(n) is not fn.node:
                continue
            if not call.args:
                yield self.finding(
                    module, call,
                    f"`{call.func.attr}()` with no size in hot "
                    f"`{fn.short()}` reads the entire remaining payload "
                    "in one await — consume it as bounded frames "
                    "(writestream discipline)")
                continue
            size = call.args[0]
            if isinstance(size, ast.Constant):
                continue
            walked = list(ast.walk(size))
            if any(isinstance(s, ast.Call) and _call_name(s) == "min"
                   for s in walked):
                continue
            names = [s.id for s in walked if isinstance(s, ast.Name)]
            if names and all(nm in guarded for nm in names):
                continue
            yield self.finding(
                module, call,
                f"`{call.func.attr}(...)` of a variable, un-capped size "
                f"in hot `{fn.short()}` gulps a whole header-declared "
                "payload into memory — read bounded frames and stage/"
                "forward each as it arrives (writestream discipline)")

    # -------------------------------------------- accumulate-only read loop

    def _accumulate_only_loops(self, fn: FunctionInfo) -> Iterator[Finding]:
        module = fn.module
        containers = _container_names(fn.node)
        if not containers:
            return
        for loop in ast.walk(fn.node):
            if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                continue
            if module.enclosing_function(loop) is not fn.node:
                continue
            for accum, chunk, container in self._accum_only(
                    module, loop, containers):
                yield self.finding(
                    module, accum,
                    f"read loop in hot `{fn.short()}` only accumulates "
                    f"`{chunk}` into `{container}` — nothing consumes a "
                    "byte until the last frame lands; CRC/stage/forward "
                    "each frame per iteration instead of materializing "
                    "the whole block (writestream discipline)")

    @classmethod
    def _accum_only(cls, module, loop: ast.AST, containers: set[str]
                    ) -> Iterator[tuple[ast.AST, str, str]]:
        body = [n for stmt in loop.body for n in ast.walk(stmt)]
        for chunk, defining in cls._chunk_vars(body):
            accum = consumed = None
            for use in body:
                if not (isinstance(use, ast.Name) and use.id == chunk
                        and isinstance(use.ctx, ast.Load)):
                    continue
                if any(anc is defining for anc in module.ancestors(use)):
                    continue
                hit = cls._accumulation_use(module, use, containers)
                if hit is not None:
                    accum = hit
                elif not cls._neutral_use(module, use):
                    consumed = use
            if accum is not None and consumed is None:
                node, container = accum
                yield node, chunk, container

    @staticmethod
    def _chunk_vars(body: list[ast.AST]) -> Iterator[tuple[str, ast.AST]]:
        """(name, defining assignment) for loop-body names bound from an
        awaited read-like call (tuple unpack included)."""
        for n in body:
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Await)
                    and isinstance(n.value.value, ast.Call)):
                continue
            if not _call_name(n.value.value).startswith(_READ_CALL_PREFIXES):
                continue
            for t in n.targets:
                targets = t.elts if isinstance(t, ast.Tuple) else [t]
                for tt in targets:
                    if isinstance(tt, ast.Name):
                        yield tt.id, n

    @staticmethod
    def _accumulation_use(module, use: ast.Name, containers: set[str]
                          ) -> tuple[ast.AST, str] | None:
        parent = module.parent(use)
        if isinstance(parent, ast.AugAssign) \
                and isinstance(parent.op, ast.Add) \
                and isinstance(parent.target, ast.Name) \
                and parent.target.id in containers:
            return parent, parent.target.id
        if isinstance(parent, ast.Call) and use in parent.args \
                and isinstance(parent.func, ast.Attribute) \
                and parent.func.attr in ("append", "extend", "write") \
                and isinstance(parent.func.value, ast.Name) \
                and parent.func.value.id in containers:
            return parent, parent.func.value.id
        return None

    @staticmethod
    def _neutral_use(module, use: ast.Name) -> bool:
        """len()/truthiness/comparison: flow control, not consumption."""
        parent = module.parent(use)
        if isinstance(parent, ast.Call) and _call_name(parent) == "len":
            return True
        if isinstance(parent, (ast.UnaryOp, ast.Compare, ast.BoolOp)):
            return True
        if isinstance(parent, (ast.If, ast.While)) and use is parent.test:
            return True
        return False
