"""TPL014 — coroutine escape: a task handle is bound but never observed.

TPL007 catches the blatant form — ``asyncio.create_task(...)`` with the
result thrown away on the spot. The subtler leak binds the handle and then
forgets it:

    task = asyncio.create_task(self._replicate(block))
    if fast_path:
        return await self._local(block)      # `task` never escapes
    await task

On the fast path the only strong reference dies with the frame: the event
loop keeps tasks weakly, so the replication forward can be garbage-
collected mid-flight and its exception is never observed. The same applies
to handles appended to a list that is itself never read.

For every local bound from a spawn, this rule checks that the name
*escapes* somewhere in the function: awaited, returned, yielded, passed as
an argument (``gather``, ``wait``, done-callback registration, a
registry's ``add``), stored onto an attribute/subscript, cancelled, or
captured by a nested function. Any single escape anywhere in the body
counts — path-sensitive liveness would flag half-legitimate patterns, and
this rule's contract (like all tpulint rules) is: if it fires, it's real.
Handles stored to ``self.*`` or containers are out of scope here — their
lifetime belongs to the owner object, which TPL007 already accepts.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.callgraph import Project
from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.rules.tasks import _is_spawn

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@register
class CoroutineEscape(ProjectRule):
    id = "TPL014"
    name = "coroutine-escape"
    summary = ("a local bound to asyncio.create_task(...) is never awaited, "
               "returned, passed on, stored, or cancelled — the handle dies "
               "with the frame and the task can be GC'd mid-flight")
    doc = (
        "Binding the task handle to a local satisfies TPL007 but saves "
        "nothing: when the frame returns, the only strong reference "
        "dies and the loop's weak reference cannot keep the task alive. "
        "This rule checks what happens to the binding — awaited, "
        "returned, stored on self, passed to another call, registered, "
        "or cancelled all count as escapes that transfer ownership; a "
        "binding with none of them is a dressed-up fire-and-forget."
    )
    example = """\
async def fire(work):
    task = asyncio.create_task(work())   # bound...
    return 1                             # ...and dead with the frame
"""
    fix = ("Await it, return it, store it (`self._t = task`), or "
           "register it with a collection/TaskGroup that outlives the "
           "frame.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for fn in project.functions.values():
            spawns: dict[str, ast.Assign] = {}
            for node in ast.walk(fn.node):
                if fn.module.enclosing_function(node) is not fn.node:
                    continue
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id != "_" \
                        and _is_spawn(node.value):
                    spawns[node.targets[0].id] = node
            if not spawns:
                continue
            escaped = self._escaped_names(fn.node, spawns.keys())
            for name, assign in spawns.items():
                if name in escaped:
                    continue
                yield self.finding(
                    fn.module, assign,
                    f"task handle `{name}` in `{fn.short()}` is bound but "
                    "never awaited, returned, passed on, stored, or "
                    "cancelled — the only strong reference dies with the "
                    "frame and the task can be GC'd mid-flight; await it, "
                    "keep it on the instance, or register it in a task set",
                )

    @staticmethod
    def _escaped_names(fn_node: ast.AST, names) -> set[str]:
        """Names from ``names`` that are used anywhere in ``fn_node``
        beyond their spawn binding (loads, attribute access, deletes,
        capture by a nested function — any observation counts)."""
        names = set(names)
        escaped: set[str] = set()
        for node in ast.walk(fn_node):
            # Nested defs/lambdas are walked too: a closure capture shows
            # up as a Load in their body and counts. Stores (including the
            # spawn binding itself) do not.
            if isinstance(node, ast.Name) and node.id in names \
                    and not isinstance(node.ctx, ast.Store):
                escaped.add(node.id)
        return escaped
