"""TPL041 — wire-contract conformance between Python and the native engine.

The blockport wire protocol exists twice: once in Python
(``blocknet.py`` framing + ``writestream.py`` stream protocol +
``service.py`` handlers) and once re-implemented by hand in C++
(``native/dataplane.cc``). PR 8's chain-hop outage was exactly this
class of bug — one side packed a float ``_db`` header the other side's
integer-only reader dropped — and nothing but an integration test deep
in a chain topology could see it. This rule diffs the contract
lexically, on every lint:

- paired numeric constants (``ACK_EVERY`` ↔ ``kAckEvery``,
  ``_MAX_HEADER`` ↔ ``kMaxHeader``, ``_MAX_PAYLOAD`` ↔ ``kMaxPayload``,
  ``MAX_STREAM_BYTES`` ↔ ``kMaxStreamBytes``, the CRC32C polynomial,
  and — since ABI 6 — the QoS admission defaults ``QOS_DRR_QUANTUM`` ↔
  ``kQosDrrQuantum`` etc.) must exist on both sides with equal values —
  edit one and lint fails;
- every required msgpack header key (``m``/``q``/``c``/``w``/``final``/
  ``_d``/``_db``/``_tn``/... ) must appear as a string literal on both
  sides — a renamed or dropped key is drift even before values diverge;
- every status code the native engine sends (``respond_err``) must be a
  canonical ``grpc.StatusCode`` name, because the Python side mints the
  enum from that string and silently degrades unknown names to
  ``INTERNAL``;
- ``blocknet.py`` must keep its ``"<I"``/``"<Q"`` little-endian framing
  structs — the C++ side hardcodes LE u32/u64 framing, so changing the
  Python structs breaks interop with zero type errors.

A pair is only enforced when both of its files are in the analyzed set,
so single-file fixture lints stay quiet.
"""

from __future__ import annotations

from typing import Iterator

from tpudfs.analysis.linter import Finding, ProjectRule, register
from tpudfs.analysis.nativesrc import (
    py_int_constants,
    py_string_literals,
)
from tpudfs.analysis.rules.native_abi import (
    native_context,
    native_finding,
    py_finding,
)

#: (python rel path, python constant, native rel path, C++ constant).
#: Enforced only when both files are present in the analyzed set.
CONSTANT_PAIRS: tuple[tuple[str, str, str, str], ...] = (
    ("tpudfs/common/writestream.py", "ACK_EVERY",
     "native/dataplane.cc", "kAckEvery"),
    ("tpudfs/common/writestream.py", "MAX_STREAM_BYTES",
     "native/dataplane.cc", "kMaxStreamBytes"),
    ("tpudfs/common/blocknet.py", "_MAX_HEADER",
     "native/dataplane.cc", "kMaxHeader"),
    ("tpudfs/common/blocknet.py", "_MAX_PAYLOAD",
     "native/dataplane.cc", "kMaxPayload"),
    ("tpudfs/common/checksum.py", "_POLY",
     "native/dataplane.cc", "kCrcPoly"),
    ("tpudfs/common/checksum.py", "_POLY",
     "native/crc32c.cc", "kPoly"),
    # ABI 6: QoS admission ladder defaults. The native engine re-implements
    # QosShedder's degradation ladder; these tuning constants must stay in
    # lockstep or the two planes shed at different thresholds.
    ("tpudfs/common/resilience.py", "QOS_DRR_QUANTUM",
     "native/dataplane.cc", "kQosDrrQuantum"),
    ("tpudfs/common/resilience.py", "QOS_QUEUE_DEPTH_DEFAULT",
     "native/dataplane.cc", "kQosQueueDepthDefault"),
    ("tpudfs/common/resilience.py", "QOS_MIN_BURST",
     "native/dataplane.cc", "kQosMinBurst"),
    ("tpudfs/common/resilience.py", "_LATENCY_RING",
     "native/dataplane.cc", "kQosLatencyRing"),
)

#: Python modules whose (non-docstring) string literals form the Python
#: side of the header-key contract.
WIRE_MODULES: tuple[str, ...] = (
    "tpudfs/common/writestream.py",
    "tpudfs/common/blocknet.py",
    "tpudfs/common/resilience.py",
    "tpudfs/chunkserver/service.py",
)

#: msgpack header keys both sides must spell out. Grouped for messages.
REQUIRED_KEYS: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("blockport envelope", ("m", "_d", "code", "message")),
    ("deadline/tenant propagation", ("_db", "_tn")),
    ("stream begin", ("WriteStream", "block_id", "size", "frame_size",
                      "expected_crc32c", "master_term", "master_shard",
                      "next_servers", "next_data_ports")),
    ("stream acks", ("ok", "ready", "q", "c", "w", "final", "success",
                     "error_message", "replicas_written")),
    # ABI 6: the native QoS plane's shed envelope and the detail strings
    # parity tests key on. RESOURCE_EXHAUSTED itself is covered by the
    # status-code check; the Overloaded| prefix and the retry_after header
    # key are what the client retry-budget path parses.
    ("qos shed envelope", ("retry_after", "Overloaded|", "rate limited",
                           "tenant queue full",
                           "deadline expired in admission queue",
                           "failpoint forced shed")),
    # ABI 6: qos_wire_config msgpack keys consumed by
    # tpudfs_dataplane_set_qos.
    ("qos config", ("enabled", "max_inflight", "base_retry_after", "rate",
                    "burst", "queue_depth", "queue_wait", "default_weight",
                    "weights", "jitter_seed")),
)

#: The canonical grpc.StatusCode names. Hardcoded (not imported from
#: grpc) so fixture lints don't need the dependency — and so the rule
#: pins the *wire* vocabulary, not whatever the installed grpc exposes.
GRPC_STATUS_NAMES = frozenset({
    "OK", "CANCELLED", "UNKNOWN", "INVALID_ARGUMENT", "DEADLINE_EXCEEDED",
    "NOT_FOUND", "ALREADY_EXISTS", "PERMISSION_DENIED",
    "RESOURCE_EXHAUSTED", "FAILED_PRECONDITION", "ABORTED", "OUT_OF_RANGE",
    "UNIMPLEMENTED", "INTERNAL", "UNAVAILABLE", "DATA_LOSS",
    "UNAUTHENTICATED",
})

#: The LE framing structs blocknet.py must keep (C++ hardcodes them).
FRAMING_STRUCTS = ("<I", "<Q")

BLOCKNET_REL = "tpudfs/common/blocknet.py"
DATAPLANE_REL = "native/dataplane.cc"


@register
class NativeWireConformance(ProjectRule):
    id = "TPL041"
    name = "native-wire-conformance"
    summary = ("wire-protocol drift between the Python blockport/stream "
               "implementation and native/dataplane.cc — a paired "
               "constant, msgpack header key, status code, or framing "
               "struct edited on one side only")
    doc = (
        "dataplane.cc re-implements the blockport framing and the "
        "WriteStream protocol byte-for-byte; mixed native/asyncio "
        "chains interop only while both copies agree. This rule "
        "extracts the contract from both sides — evaluated constexpr "
        "constants from the C++ (via the tpulint C++ tokenizer) and "
        "module constants/string literals from the Python AST — and "
        "diffs them: paired constants (ack cadence, header/payload "
        "caps, stream size gate, CRC polynomial) must be equal; every "
        "required msgpack header key must appear as a literal on both "
        "sides; every respond_err status code must be a canonical "
        "grpc.StatusCode name (unknown names silently degrade to "
        "INTERNAL on the Python side, hiding the real error); and "
        "blocknet.py must keep its '<I'/'<Q' little-endian structs, "
        "which the C++ reader hardcodes. PR 8's float-_db bug — one "
        "side packing a header the other dropped — is the class this "
        "catches at lint time instead of in a chain topology test."
    )
    example = """\
# writestream.py
ACK_EVERY = 4          # retuned ack cadence...
// dataplane.cc (unchanged)
constexpr uint64_t kAckEvery = 8;   // ...but only on one side
"""
    fix = ("Change both sides in the same commit — the paired constant "
           "in native/dataplane.cc is commented with its Python twin "
           "(and vice versa); for header keys, add the literal to the "
           "reader AND writer on the lagging side. If a constant is "
           "genuinely one-sided now, remove it from the pair table in "
           "tpudfs/analysis/rules/native_wire.py with a comment saying "
           "why.")

    def check_project(self, project) -> Iterator[Finding]:
        root, sources = native_context(project)
        if not sources:
            return
        by_rel = {src.rel: src for src in sources}
        yield from self._constant_pairs(project, by_rel)
        dataplane = by_rel.get(DATAPLANE_REL)
        if dataplane is not None:
            yield from self._header_keys(project, dataplane)
            yield from self._status_codes(dataplane)
            yield from self._framing_pin(project, dataplane)

    # -------------------------------------------------- constant pairs

    def _constant_pairs(self, project, by_rel) -> Iterator[Finding]:
        for py_rel, py_name, cc_rel, cc_name in CONSTANT_PAIRS:
            module = project.modules.get(py_rel)
            src = by_rel.get(cc_rel)
            if module is None or src is None:
                continue
            py_consts = py_int_constants(module.tree)
            py_hit = py_consts.get(py_name)
            cc_val = src.constants.get(cc_name)
            if py_hit is None and cc_val is None:
                continue
            if py_hit is None:
                f = native_finding(
                    self.id, src, src.constant_lines.get(cc_name, 1),
                    cc_name,
                    f"`{cc_name}` has no Python twin — `{py_name}` is "
                    f"missing from {py_rel}; the wire contract exists "
                    "on one side only")
                if f is not None:
                    yield f
                continue
            py_val, py_line = py_hit
            if cc_val is None:
                yield py_finding(
                    self.id, module, py_line, py_name,
                    f"`{py_name}` ({py_val:#x}) has no native twin — "
                    f"`{cc_name}` is missing from {cc_rel}; the native "
                    "engine does not enforce this wire constant")
                continue
            if py_val != cc_val:
                f = native_finding(
                    self.id, src, src.constant_lines.get(cc_name, 1),
                    cc_name,
                    f"`{cc_name}` = {cc_val} here but its Python twin "
                    f"`{py_name}` = {py_val} ({py_rel}:{py_line}) — "
                    "the two protocol implementations disagree")
                if f is not None:
                    yield f

    # ---------------------------------------------------- header keys

    def _header_keys(self, project, dataplane) -> Iterator[Finding]:
        wire_mods = [project.modules[rel] for rel in WIRE_MODULES
                     if rel in project.modules]
        if not wire_mods:
            return
        py_lits: dict[str, tuple[str, int]] = {}
        for mod in wire_mods:
            for lit, line in py_string_literals(mod.tree).items():
                py_lits.setdefault(lit, (mod.rel_path, line))
        wire_rels = ", ".join(m.rel_path for m in wire_mods)
        for group, keys in REQUIRED_KEYS:
            for key in keys:
                in_py = key in py_lits
                in_cc = key in dataplane.string_literals
                if in_py and in_cc:
                    continue
                if in_py and not in_cc:
                    rel, line = py_lits[key]
                    yield py_finding(
                        self.id, project.modules[rel], line, key,
                        f"required {group} header key `{key}` appears "
                        f"here but nowhere in {DATAPLANE_REL} — the "
                        "native engine will drop or never send it")
                elif in_cc and not in_py:
                    f = native_finding(
                        self.id, dataplane,
                        dataplane.string_literals[key], key,
                        f"required {group} header key `{key}` appears "
                        f"here but in none of the Python wire modules "
                        f"({wire_rels}) — the asyncio side will drop "
                        "or never send it")
                    if f is not None:
                        yield f
                # Missing on BOTH sides: the contract table is stale for
                # this tree (fixture lints); stay quiet.

    # --------------------------------------------------- status codes

    def _status_codes(self, dataplane) -> Iterator[Finding]:
        for code, line in dataplane.status_codes:
            if code in GRPC_STATUS_NAMES:
                continue
            f = native_finding(
                self.id, dataplane, line, "respond_err",
                f"native error frame uses status code `{code}`, which "
                "is not a grpc.StatusCode name — the Python side "
                "(writestream._raise_error_frame) silently degrades "
                "unknown codes to INTERNAL, hiding the real error from "
                "fallback logic")
            if f is not None:
                yield f

    # ---------------------------------------------------- framing pin

    def _framing_pin(self, project, dataplane) -> Iterator[Finding]:
        blocknet = project.modules.get(BLOCKNET_REL)
        if blocknet is None:
            return
        lits = py_string_literals(blocknet.tree)
        for fmt in FRAMING_STRUCTS:
            if fmt in lits:
                continue
            yield py_finding(
                self.id, blocknet, 1, "framing",
                f"blocknet.py no longer defines a struct.Struct("
                f"'{fmt}') — {DATAPLANE_REL} hardcodes little-endian "
                "u32/u64 blockport framing, so changing the Python "
                "framing structs breaks native interop")
