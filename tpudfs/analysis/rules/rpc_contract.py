"""TPL012 — RPC contract conformance across server and client modules.

The RPC substrate is stringly typed: servers register
``server.add_service(SERVICE, {"ReadBlock": self.rpc_read_block, ...})``
and clients invoke ``await rpc.call(addr, SERVICE, "ReadBlock", req)``.
A typo'd method name, a client module's stale private copy of a service
constant, or a handler with the wrong signature all pass every unit test
that doesn't happen to cross that exact wire — and then fail at runtime
as an ``unknown method`` error three layers from the typo.

This rule cross-checks the two sides project-wide:

- **Server tables** are collected from every ``add_service(name, table)``
  call. The table may be a dict literal, a local variable bound to one, or
  a call to a method/function whose ``return`` is one (the
  ``self.handlers()`` idiom). Multiple registrations of one service name
  merge — masters and chunkservers both register per-process.
- **Client sites** are any ``*.call(...)`` whose positional args contain a
  resolvable service-name string immediately followed by a method string
  (literal or module constant). This shape survives the arg shifts between
  ``RpcClient.call(addr, service, method, req)`` and
  ``pool.call(rpc, addr, service, method, req)``. Dynamic method variables
  produce no finding — conservatism over guesses.
- **Handlers** must resolve to a real function taking exactly one request
  parameter (plus ``self``) — the dispatcher calls ``handler(request)``.

Unknown service names on the client side are skipped entirely: tests and
tools talk to services defined outside the scanned tree.
"""

from __future__ import annotations

import ast
import difflib
from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    ProjectRule,
    dotted_name,
    register,
)


def _dict_literal(node: ast.AST) -> ast.Dict | None:
    return node if isinstance(node, ast.Dict) else None


def _returned_dict(fn: FunctionInfo) -> ast.Dict | None:
    """The dict literal a table-builder function returns, if that is the
    only shape it returns."""
    result = None
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Return) and node.value is not None \
                and fn.module.enclosing_function(node) is fn.node:
            d = _dict_literal(node.value)
            if d is None:
                return None
            result = d
    return result


def _local_dict(fn: FunctionInfo, var: str) -> ast.Dict | None:
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == var:
            return _dict_literal(node.value)
    return None


def _request_params(fn: FunctionInfo) -> int:
    """Positional parameters the dispatcher must fill: everything except
    an implicit self/cls, minus parameters with defaults."""
    args = fn.node.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if fn.cls is not None and names and names[0] in ("self", "cls"):
        names = names[1:]
    return len(names) - len(args.defaults)


@register
class RpcContractConformance(ProjectRule):
    id = "TPL012"
    name = "rpc-contract-conformance"
    summary = ("client RPC call names a method no server registers for that "
               "service, or a registered handler has the wrong signature")
    doc = (
        "RPC methods are strings: a typo'd method name type-checks, "
        "imports, and fails only at runtime — usually as a timeout on "
        "the first call, in production. This rule cross-references every "
        "`rpc.call(addr, SERVICE, \"Method\", req)` against the handler "
        "tables servers register (`add_service`), flags unknown methods "
        "with a did-you-mean suggestion, and checks registered handlers "
        "take exactly one request argument. Dynamic method variables and "
        "services not registered in the tree are out of scope."
    )
    example = """\
await rpc.call(addr, CS, "ReadBlok", req)   # server registers "ReadBlock"
"""
    fix = ("Fix the method string (the finding suggests the closest "
           "registered name), or register the handler with the standard "
           "`async def rpc_x(self, req)` shape.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        #: service name -> method name -> handler (or None if unresolved)
        tables: dict[str, dict[str, FunctionInfo | None]] = {}
        handler_findings: list[Finding] = []

        for mod in project.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr != "add_service" \
                        or len(node.args) < 2:
                    continue
                caller = project.enclosing_function_info(mod, node)
                if caller is None:
                    continue
                service = project.resolve_str_const(mod, node.args[0])
                resolved = self._resolve_table(project, caller, node.args[1])
                if service is None or resolved is None:
                    continue
                table, owner = resolved
                dest = tables.setdefault(service, {})
                handler_findings.extend(
                    self._ingest_table(project, owner, service, table, dest))

        yield from handler_findings
        if not tables:
            return

        for mod in project.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr != "call":
                    continue
                hit = self._call_site(project, mod, node, tables)
                if hit is None:
                    continue
                service, method = hit
                if method in tables[service]:
                    continue
                close = difflib.get_close_matches(
                    method, tables[service], n=1)
                suggest = f"; did you mean `{close[0]}`?" if close \
                    else ""
                yield self.finding(
                    mod, node,
                    f"RPC call to `{service}.{method}` — no server "
                    f"registers a `{method}` handler for service "
                    f"`{service}`{suggest}",
                )

    # ------------------------------------------------------------ server side

    def _resolve_table(
        self, project: Project, caller: FunctionInfo, arg: ast.AST,
    ) -> tuple[ast.Dict, FunctionInfo] | None:
        """The handler table's dict literal plus the function whose scope
        owns it — the dict may live in another module entirely (the
        ``self.handlers()`` idiom on the service class), and handler refs
        must resolve against the owner, not the registration site."""
        d = _dict_literal(arg)
        if d is not None:
            return d, caller
        if isinstance(arg, ast.Name):
            local = _local_dict(caller, arg.id)
            return (local, caller) if local is not None else None
        if isinstance(arg, ast.Call):
            builder = project.resolve_call(caller, arg.func)
            if builder is not None:
                returned = _returned_dict(builder)
                if returned is not None:
                    return returned, builder
        return None

    def _ingest_table(self, project: Project, owner: FunctionInfo,
                      service: str, table: ast.Dict,
                      dest: dict) -> Iterator[Finding]:
        for key, value in zip(table.keys, table.values):
            if key is None:
                continue
            method = project.resolve_str_const(owner.module, key)
            if method is None:
                continue
            handler = project.resolve_call(owner, value)
            dest.setdefault(method, handler)
            ref = dotted_name(value)
            if handler is None and ref is not None \
                    and ref.startswith(("self.", "cls.")):
                yield self.finding(
                    owner.module, value,
                    f"service `{service}` registers method `{method}` "
                    f"with handler `{ref}`, which does not resolve to any "
                    "method on this class — a startup-time AttributeError "
                    "or a silently dead RPC",
                )
            elif handler is not None and _request_params(handler) != 1:
                yield self.finding(
                    owner.module, value,
                    f"handler `{handler.short()}` for "
                    f"`{service}.{method}` must take exactly one request "
                    f"argument (the dispatcher calls `handler(request)`), "
                    f"but its signature requires "
                    f"{_request_params(handler)}",
                )

    # ------------------------------------------------------------ client side

    @staticmethod
    def _call_site(project: Project, mod: ModuleInfo, node: ast.Call,
                   tables: dict) -> tuple[str, str] | None:
        """(service, method) when this ``*.call(...)`` names a known
        service followed by a resolvable method string."""
        for i in range(len(node.args) - 1):
            service = project.resolve_str_const(mod, node.args[i])
            if service is None or service not in tables:
                continue
            method = project.resolve_str_const(mod, node.args[i + 1])
            if method is None:
                return None  # dynamic method variable: stay silent
            return service, method
        return None
