"""TPL007 — fire-and-forget ``asyncio.create_task``.

CPython keeps only a WEAK reference to tasks: a task whose handle is dropped
can be garbage-collected mid-flight, silently cancelling a replication
forward, heartbeat loop or scrubber iteration. Dropped handles also lose the
exception — the task dies, nobody logs it.

Flagged:

- ``asyncio.create_task(...)`` / ``asyncio.ensure_future(...)`` /
  ``loop.create_task(...)`` as a bare expression statement;
- the same assigned to ``_`` (explicitly discarded).

Keep the handle (``self._task = asyncio.create_task(...)``), add it to a
collection with a done-callback, or use structured concurrency
(``asyncio.TaskGroup``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

_SPAWN_EXACT = {"asyncio.create_task", "asyncio.ensure_future"}
_SPAWN_ATTRS = {"create_task", "ensure_future"}


def _is_spawn(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name in _SPAWN_EXACT:
        return name
    if isinstance(call.func, ast.Attribute) and call.func.attr in _SPAWN_ATTRS:
        # loop.create_task / self._loop.create_task / tg.create_task —
        # TaskGroup.create_task keeps its own strong reference, so exempt
        # receivers that look like task groups.
        receiver = dotted_name(call.func.value) or ""
        tail = receiver.split(".")[-1].lstrip("_")
        if tail in ("tg", "taskgroup", "task_group", "group"):
            return None
        return f"{receiver or '<expr>'}.{call.func.attr}"
    return None


@register
class DroppedTaskHandle(Rule):
    id = "TPL007"
    name = "dropped-task-handle"
    summary = ("fire-and-forget asyncio.create_task — a weakly-referenced "
               "task can be GC'd mid-flight and its exception lost")
    doc = (
        "The event loop keeps only a weak reference to tasks: a "
        "`create_task` whose result is dropped on the floor can be "
        "garbage collected mid-flight, and if it fails, the exception "
        "is reported to nobody. A background scrubber that dies this "
        "way looks exactly like a healthy one. The rule flags spawns "
        "whose handle is not bound, stored, or group-owned; TPL014 "
        "chases the harder case where a handle is bound but still dies "
        "with its frame."
    )
    example = """\
async def start(self):
    asyncio.create_task(self.scrub_loop())   # handle dropped
"""
    fix = ("Store the handle (`self._scrub_task = asyncio.create_task("
           "...)`) and cancel/await it on stop, or spawn through a "
           "TaskGroup that owns it.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            call: ast.Call | None = None
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and all(isinstance(t, ast.Name) and t.id == "_"
                            for t in node.targets):
                call = node.value
            if call is None:
                continue
            name = _is_spawn(call)
            if name is None:
                continue
            yield self.finding(
                module, node,
                f"`{name}(...)` handle dropped — the event loop holds only "
                "a weak reference, so the task can be GC'd mid-flight; keep "
                "the handle and observe its result/exception",
            )
