"""TPL002 — thread locks mixed into async control flow.

``await`` while holding a ``threading.Lock`` is a classic distributed-systems
deadlock: the coroutine parks, the lock stays held, and any thread (or any
other coroutine on the same loop reaching the same lock) blocks the whole
event loop waiting for it. Thread locks also have no cancellation semantics,
so a cancelled coroutine leaks the acquisition.

Detected patterns:

- ``with <thread lock>:`` whose body contains ``await`` (directly, not in a
  nested function);
- ``<thread lock>.acquire()`` called from an ``async def``.

A "thread lock" is any symbol assigned from ``threading.Lock()``,
``threading.RLock()``, ``threading.Condition()`` or ``threading.Semaphore()``
anywhere in the same module (tracked as plain names and ``self.attr``
targets). asyncio primitives (``asyncio.Lock`` etc.) are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

_THREAD_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}


def _lock_symbols(module: ModuleInfo) -> set[str]:
    """Dotted names assigned from a threading lock constructor. ``self.x``
    targets are tracked as ``self.x`` — receiver identity across methods of
    the same class is assumed, which is the common case."""
    symbols: set[str] = set()
    for node in ast.walk(module.tree):
        value = None
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        if value is None or not isinstance(value, ast.Call):
            continue
        ctor = dotted_name(value.func)
        if ctor not in _THREAD_LOCK_CTORS:
            continue
        for t in targets:
            name = dotted_name(t)
            if name:
                symbols.add(name)
    return symbols


def _awaits_directly_in(body: list[ast.stmt]) -> ast.Await | None:
    """First Await in ``body`` that is not inside a nested function/lambda."""

    class V(ast.NodeVisitor):
        found: ast.Await | None = None

        def visit_Await(self, node: ast.Await) -> None:
            if self.found is None:
                self.found = node

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            pass  # different execution context

        def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
            pass

        def visit_Lambda(self, node: ast.Lambda) -> None:
            pass

    v = V()
    for stmt in body:
        v.visit(stmt)
        if v.found is not None:
            return v.found
    return None


@register
class AwaitUnderThreadLock(Rule):
    id = "TPL002"
    name = "await-under-thread-lock"
    summary = ("`await` while holding a threading.Lock (or acquiring one "
               "from async code) can deadlock the event loop")
    doc = (
        "A threading.Lock blocks the whole OS thread. Awaiting while "
        "holding one parks the coroutine but keeps the mutex locked, so "
        "every other coroutine (and thread) that wants it stalls — and if "
        "the awaited work itself needs the lock, the loop deadlocks. "
        "Acquiring a threading lock from async code has the same hazard "
        "in the other direction: the loop thread can block on acquire. "
        "This rule is the lexical check; TPL021 proves the path-sensitive "
        "variants over the CFG."
    )
    example = """\
class S:
    def __init__(self):
        self._mu = threading.Lock()
    async def flush(self, sink):
        with self._mu:
            await sink.drain()   # loop parks holding the mutex
"""
    fix = ("Use asyncio.Lock for coroutine-only state; for state shared "
           "with worker threads, keep the threading.Lock but only touch "
           "it from sync code via `await asyncio.to_thread(...)`.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        locks = _lock_symbols(module)
        if not locks:
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.With):
                for item in node.items:
                    expr = item.context_expr
                    # `with self._lock:` and `with self._lock.acquire():`
                    target = expr.func if isinstance(expr, ast.Call) else expr
                    if isinstance(target, ast.Attribute) \
                            and target.attr in ("acquire", "locked"):
                        target = target.value
                    name = dotted_name(target)
                    if name not in locks:
                        continue
                    awaited = _awaits_directly_in(node.body)
                    if awaited is not None:
                        yield self.finding(
                            module, awaited,
                            f"`await` inside `with {name}` — thread lock "
                            "held across a suspension point; use "
                            "`asyncio.Lock` or release before awaiting",
                        )
            elif isinstance(node, ast.Call):
                func = node.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr == "acquire"):
                    continue
                name = dotted_name(func.value)
                if name in locks and module.in_async_context(node):
                    yield self.finding(
                        module, node,
                        f"thread lock `{name}.acquire()` called from async "
                        "code; blocks the event loop — use `asyncio.Lock` "
                        "or `asyncio.to_thread`",
                    )
