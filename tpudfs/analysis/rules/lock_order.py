"""TPL011 — lock-order inversion and thread-lock pressure on the event loop,
detected across the whole project.

TPL002 sees one module: an ``await`` under a ``threading.Lock`` in the same
file. The deadlocks that survive review are split: ``raft/node.py`` takes
lock A then calls into ``common/rpc.py`` which takes lock B, while another
path takes B then A — no single file contains the cycle. This rule builds
the project-wide lock-acquisition graph and reports:

1. **Inversions** — a cycle in the held-lock -> acquired-lock graph, where
   "acquired while held" includes acquisitions reached through any resolved
   call chain from inside the ``with`` body. Both ``threading`` and
   ``asyncio`` locks participate: ABBA between coroutines deadlocks just as
   hard as between threads.
2. **Thread locks on async paths** — an ``async def`` whose call chain
   (or body) acquires a ``threading`` lock that is elsewhere held across an
   ``await`` or a blocking call. Such a lock can be held for a long time,
   so the event-loop thread can block on ``acquire`` — every coroutine on
   the loop stalls, not just the caller. Short hand-off locks (never held
   across slow work anywhere) are deliberately NOT flagged: guarding a few
   assignments with a mutex from async code is harmless and common.

Lock identity — the owning scope plus attribute (``pkg.mod.Class._mu`` /
``pkg.mod.global_mu``), registered from ``threading.Lock()`` /
``asyncio.Lock()``-style constructor assignments anywhere in the project —
lives in the shared :class:`~tpudfs.analysis.lockinfo.LockRegistry`, which
the TPL020 race detector reuses.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator

from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.linter import (
    Finding,
    ProjectRule,
    register,
)
from tpudfs.analysis.lockinfo import LockRegistry
from tpudfs.analysis.rules.blocking import blocking_call

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclass
class _Acq:
    """One lock acquisition site."""

    lock: str
    kind: str  # "thread" | "async"
    fn: FunctionInfo
    site: ast.AST
    body: list[ast.stmt] | None  # with-body when held as a context manager


class _LockWorld:
    """Shared registry + per-function acquisitions + transitive closures."""

    def __init__(self, project: Project):
        self.project = project
        self.registry = LockRegistry(project)
        self.acqs: dict[FunctionInfo, list[_Acq]] = {}
        self._closure_memo: dict[FunctionInfo, dict[str, list[str]]] = {}
        self._slow_memo: dict[FunctionInfo, bool] = {}
        for fn in project.functions.values():
            self.acqs[fn] = list(self._function_acqs(fn))

    @property
    def locks(self) -> dict[str, str]:
        return self.registry.locks

    def resolve_lock(self, fn: FunctionInfo, expr: ast.AST) -> str | None:
        return self.registry.resolve_lock(fn, expr)

    # -- acquisition sites --------------------------------------------------

    def _function_acqs(self, fn: FunctionInfo) -> Iterator[_Acq]:
        for node in ast.walk(fn.node):
            if fn.module.enclosing_function(node) is not fn.node:
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = self.resolve_lock(fn, item.context_expr)
                    if lock is not None:
                        yield _Acq(lock, self.locks[lock], fn, node,
                                   node.body)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                lock = self.resolve_lock(fn, node.func.value)
                if lock is not None:
                    yield _Acq(lock, self.locks[lock], fn, node, None)

    # -- closures -----------------------------------------------------------

    def closure(self, fn: FunctionInfo,
                _stack: frozenset = frozenset()) -> dict[str, list[str]]:
        """Locks acquired by ``fn`` or anything it (transitively) calls in
        the same execution context: lock id -> call chain of function
        names. Task/thread edges are other contexts and excluded."""
        if fn in self._closure_memo:
            return self._closure_memo[fn]
        if fn in _stack:
            return {}
        out: dict[str, list[str]] = {}
        for acq in self.acqs.get(fn, ()):
            out.setdefault(acq.lock, [fn.short()])
        for edge in fn.calls:
            if edge.kind != "call":
                continue
            for lock, chain in self.closure(
                    edge.callee, _stack | {fn}).items():
                out.setdefault(lock, [fn.short()] + chain)
        self._closure_memo[fn] = out
        return out

    # -- "slow" locks -------------------------------------------------------

    def _fn_blocks_or_awaits(self, fn: FunctionInfo,
                             _stack: frozenset = frozenset()) -> bool:
        """fn (or its same-context callees) awaits or calls a blocking
        leaf — holding a lock across a call to it is a long hold."""
        if fn in self._slow_memo:
            return self._slow_memo[fn]
        if fn in _stack:
            return False
        result = False
        for node in ast.walk(fn.node):
            if fn.module.enclosing_function(node) is not fn.node:
                continue
            if isinstance(node, ast.Await):
                result = True
                break
            if isinstance(node, ast.Call) and blocking_call(node):
                result = True
                break
        if not result:
            for edge in fn.calls:
                if edge.kind == "call" and self._fn_blocks_or_awaits(
                        edge.callee, _stack | {fn}):
                    result = True
                    break
        self._slow_memo[fn] = result
        return result

    def slow_locks(self) -> dict[str, str]:
        """Locks held somewhere across an await / blocking call / slow
        callee: lock id -> 'file:line' of the slow hold."""
        slow: dict[str, str] = {}
        for fn, acqs in self.acqs.items():
            for acq in acqs:
                if acq.body is None or acq.lock in slow:
                    continue
                where = (f"{fn.module.rel_path}:"
                         f"{getattr(acq.site, 'lineno', 0)}")
                for node in self._body_nodes(fn, acq.body):
                    if isinstance(node, ast.Await):
                        slow[acq.lock] = where
                        break
                    if isinstance(node, ast.Call) and blocking_call(node):
                        slow[acq.lock] = where
                        break
                if acq.lock in slow:
                    continue
                for edge in fn.calls:
                    if edge.kind == "call" \
                            and self._in_body(fn, acq.body, edge.site) \
                            and self._fn_blocks_or_awaits(edge.callee):
                        slow[acq.lock] = where
                        break
        return slow

    # -- body membership ----------------------------------------------------

    @staticmethod
    def _body_nodes(fn: FunctionInfo,
                    body: list[ast.stmt]) -> Iterator[ast.AST]:
        """Nodes lexically inside ``body``, excluding nested function
        subtrees (they execute in another context/at another time)."""
        stack: list[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES):
                    continue
                stack.append(child)

    def _in_body(self, fn: FunctionInfo, body: list[ast.stmt],
                 site: ast.AST) -> bool:
        for node in self._body_nodes(fn, body):
            if node is site:
                return True
        return False


@dataclass
class _Edge:
    held: str
    acquired: str
    fn: FunctionInfo
    site: ast.AST
    chain: list[str]


@register
class LockOrderInversion(ProjectRule):
    id = "TPL011"
    name = "lock-order-inversion"
    summary = ("cyclic lock-acquisition order across the project, or a "
               "threading.Lock that async code can block on while another "
               "path holds it across slow work")
    doc = (
        "ABBA deadlocks that survive review are split across files: one "
        "module takes lock A then calls into another that takes B, while "
        "a reverse path takes B then A — no single file contains the "
        "cycle. This rule builds the project-wide held->acquired graph "
        "(with acquisitions reached through resolved calls inside `with` "
        "bodies) and reports cycles; it also flags async code that can "
        "block on a threading lock which some other path holds across "
        "slow work. Lock identity lives in the shared LockRegistry "
        "(lockinfo.py), the same one TPL020 uses."
    )
    example = """\
# alpha.py                       # beta.py
def fwd():                       def rev():
    with LOCK_A:                     with LOCK_B:
        beta.take_b()                    alpha.take_a()
"""
    fix = ("Pick one global acquisition order (document it where the "
           "locks are defined) or merge the locks; keep thread locks "
           "reachable from async code short-hold only.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        world = _LockWorld(project)
        if not world.locks:
            return

        # ---- build the held -> acquired graph
        edges: list[_Edge] = []
        for fn, acqs in world.acqs.items():
            for acq in acqs:
                if acq.body is None:
                    continue
                body_nodes = set(map(id, world._body_nodes(fn, acq.body)))
                # direct nested acquisitions
                for other in acqs:
                    if other is acq or other.lock == acq.lock:
                        continue
                    if id(other.site) in body_nodes:
                        edges.append(_Edge(acq.lock, other.lock, fn,
                                           other.site, [fn.short()]))
                # acquisitions via calls made while held
                for edge in fn.calls:
                    if edge.kind != "call" or id(edge.site) not in body_nodes:
                        continue
                    for lock, chain in world.closure(edge.callee).items():
                        if lock != acq.lock:
                            edges.append(_Edge(acq.lock, lock, fn,
                                               edge.site,
                                               [fn.short()] + chain))

        adj: dict[str, set[str]] = {}
        for e in edges:
            adj.setdefault(e.held, set()).add(e.acquired)

        def reachable(src: str, dst: str) -> bool:
            seen, stack = set(), [src]
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(adj.get(cur, ()))
            return False

        reported: set[frozenset] = set()
        for e in sorted(edges, key=lambda e: (e.fn.module.rel_path,
                                              getattr(e.site, "lineno", 0))):
            if not reachable(e.acquired, e.held):
                continue
            cycle = frozenset((e.held, e.acquired))
            if cycle in reported:
                continue
            reported.add(cycle)
            via = " -> ".join(e.chain)
            yield self.finding(
                e.fn.module, e.site,
                f"lock-order inversion: `{e.held}` is held here while "
                f"acquiring `{e.acquired}` (via {via}), but another path "
                f"acquires them in the opposite order — a timing-dependent "
                "deadlock; pick one global order or merge the locks",
            )

        # ---- thread locks reachable from async context
        slow = world.slow_locks()
        for fn in project.functions.values():
            if not fn.is_async:
                continue
            # direct: `with self._mu:` in the async body (no await inside —
            # that exact case is TPL002's)
            for acq in world.acqs.get(fn, ()):
                if acq.kind != "thread" or acq.lock not in slow:
                    continue
                if acq.body is not None and any(
                        isinstance(n, ast.Await)
                        for n in world._body_nodes(fn, acq.body)):
                    continue  # TPL002 reports await-under-lock
                yield self.finding(
                    fn.module, acq.site,
                    f"async `{fn.short()}` acquires threading lock "
                    f"`{acq.lock}`, which is held across slow work at "
                    f"{slow[acq.lock]} — the event loop can block on "
                    "acquire; use asyncio.Lock or move this off-loop",
                )
            for edge in project.sync_call_edges(fn):
                for lock, chain in world.closure(edge.callee).items():
                    if world.locks.get(lock) != "thread" or lock not in slow:
                        continue
                    via = " -> ".join([fn.short()] + chain)
                    yield self.finding(
                        fn.module, edge.site,
                        f"async `{fn.short()}` reaches a threading lock "
                        f"`{lock}` ({via}) that is held across slow work "
                        f"at {slow[lock]} — the event loop can block on "
                        "acquire; use asyncio.Lock or asyncio.to_thread",
                    )
                    break  # one finding per call edge is enough
