"""TPL023 — Raft durability ordering, proven with dataflow.

Raft's safety argument leans on one storage invariant: hard state (term,
vote, log entries) must be durable **before** any message advertising it
leaves the node. Grant a vote, reply "granted", then crash before the
vote hits disk — after restart the node votes again in the same term and
two leaders can be elected. Acknowledge an AppendEntries before the
entries are fsync'd and a crashed follower silently forgets entries the
leader already counted toward commit.

The old heuristic (raft_state.py) checked shapes: a reply statement
lexically before a persist statement in the same function body. This
rule upgrades that to a CFG property: a forward may-analysis accumulates
outbound-send sites along paths, and any persist call whose in-state
already contains a send is flagged — across branches, early returns and
try/except routing, which the lexical check could not see. Loop back
edges are cut before solving (``solve(..., skip_edges=cfg.back_edges())``)
so the ordering is judged *per iteration*: persisting at the top of
iteration N+1 after sending at the bottom of iteration N is the normal
drive-loop shape, not a violation.

A second check catches fire-and-forget persistence: a persist wrapped in
``asyncio.to_thread(...)`` (or scheduled via ``create_task``) whose
result is not awaited on the spot — the write has merely been *scheduled*
when execution continues toward the send.

Persist calls: a receiver chain through a storage/WAL attribute ending in
a durability method (``save_hard_state``, ``append_entries``,
``truncate_from``, ``save_snapshot``, or any ``save_*``/``append_*``/
``persist*`` name), either called directly or passed as the callable to
``asyncio.to_thread`` / ``run_in_executor``. Sends: ``_send`` / ``send``
/ ``send_message`` / ``broadcast`` calls, or ``.call(...)`` on an
rpc/client receiver — including ones wrapped in ``create_task``.

Scoped to ``tpudfs/raft/``: these method names are only a contract there.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpudfs.analysis.cfg import Node, cfg_for
from tpudfs.analysis.dataflow import MayAnalysis, solve
from tpudfs.analysis.linter import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)

_STORAGE_PARTS = {"storage", "_storage", "wal", "_wal"}
_PERSIST_METHODS = {"save_hard_state", "append_entries", "truncate_from",
                    "save_snapshot"}
_PERSIST_PREFIXES = ("save_", "append_", "persist")
_SEND_NAMES = {"_send", "send", "send_message", "broadcast"}
_RPC_RECEIVER_PARTS = {"client", "clients", "rpc", "transport", "peer",
                       "peers"}
_OFFLOAD_TAILS = {"to_thread", "run_in_executor"}


def _persist_target(expr: ast.AST) -> str | None:
    """The persisted method name if ``expr`` is a storage durability
    method reference/call, else None."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if not name:
        return None
    parts = name.split(".")
    method = parts[-1]
    if not any(p in _STORAGE_PARTS for p in parts[:-1]):
        return None
    if method in _PERSIST_METHODS or method.startswith(_PERSIST_PREFIXES):
        return method
    return None


def _classify_call(call: ast.Call) -> tuple[str, str] | None:
    """("persist"|"persist_offload"|"send", description) or None."""
    func_name = dotted_name(call.func) or ""
    tail = func_name.split(".")[-1]

    if tail in _OFFLOAD_TAILS and call.args:
        # asyncio.to_thread(self.storage.save_hard_state, ...) /
        # loop.run_in_executor(None, self._storage.append_entries, ...)
        for arg in call.args[:2]:
            method = _persist_target(arg)
            if method is not None:
                return ("persist_offload", method)
        return None

    method = _persist_target(call)
    if method is not None:
        return ("persist", method)

    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = dotted_name(call.func.value) or ""
        recv_parts = set(recv.split("."))
        if attr in _SEND_NAMES:
            return ("send", func_name or attr)
        if attr == "call" and recv_parts & _RPC_RECEIVER_PARTS:
            return ("send", func_name)
    return None


class _SendsSeen(MayAnalysis):
    """May-set of send-call ids already executed on some path in."""

    def __init__(self, sends: dict[int, ast.Call]):
        self._sends = sends

    def transfer(self, node: Node, value):
        for sub in node.walk():
            if id(sub) in self._sends:
                value = value | {id(sub)}
        return value


@register
class RaftDurabilityOrdering(Rule):
    id = "TPL023"
    name = "raft-durability-ordering"
    summary = ("a Raft storage write (term/vote/log) happens after an "
               "outbound message on some path, or is scheduled without "
               "being awaited — state is advertised before it is durable")
    doc = (
        "Raft's safety proof assumes hard state is durable before any "
        "message advertising it leaves the node: reply \"vote granted\" "
        "before the vote hits disk and a crash+restart votes again in "
        "the same term — two leaders. This rule proves the ordering on "
        "the CFG: a may-analysis accumulates outbound sends along paths "
        "(loop back edges cut, so iteration N's send does not poison "
        "iteration N+1's persist) and flags any storage write whose "
        "in-state already contains a send. It also flags persistence "
        "offloaded via to_thread/create_task but not awaited — merely "
        "scheduled is not durable. Scoped to tpudfs/raft/."
    )
    example = """\
async def on_vote(self, req):
    await self._send(req.frm, granted_reply())       # reply first...
    await asyncio.to_thread(
        self.storage.save_hard_state, t, v)          # ...persist after
"""
    fix = ("`await` the storage write first, then send; never wrap "
           "hard-state persistence in fire-and-forget create_task.")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not module.rel_path.startswith("tpudfs/raft/"):
            return
        for fn in ast.walk(module.tree):
            if isinstance(fn, ast.AsyncFunctionDef):
                yield from self._check_fn(module, fn)

    def _check_fn(self, module: ModuleInfo,
                  fn: ast.AsyncFunctionDef) -> Iterator[Finding]:
        persists: dict[int, tuple[ast.Call, str, str]] = {}
        sends: dict[int, ast.Call] = {}
        parents: dict[int, ast.AST] = {}
        for sub in ast.walk(fn):
            if module.enclosing_function(sub) is not fn:
                continue
            for child in ast.iter_child_nodes(sub):
                parents[id(child)] = sub
            if not isinstance(sub, ast.Call):
                continue
            kind = _classify_call(sub)
            if kind is None:
                continue
            if kind[0] == "send":
                sends[id(sub)] = sub
            else:
                persists[id(sub)] = (sub, kind[0], kind[1])
        if not persists:
            return

        # -- fire-and-forget persistence: offloaded but not awaited here.
        for call, kind, method in persists.values():
            if kind != "persist_offload":
                continue
            parent = parents.get(id(call))
            if isinstance(parent, ast.Await) and parent.value is call:
                continue
            yield self.finding(
                module, call,
                f"storage write `{method}` is offloaded here but its "
                "result is never awaited at this point — execution "
                "continues (and may reply) while the write is merely "
                "scheduled; `await` the offload before advertising the "
                "state it persists",
            )

        if not sends:
            return

        # -- send-before-persist on some same-iteration path.
        cfg = cfg_for(module, fn)
        res = solve(cfg, _SendsSeen(sends), skip_edges=cfg.back_edges())
        locator: dict[int, Node] = {}
        for node in cfg.nodes:
            for sub in node.walk():
                locator.setdefault(id(sub), node)

        for call, _kind, method in sorted(
                persists.values(), key=lambda p: p[0].lineno):
            node = locator.get(id(call))
            if node is None:
                continue
            pair = res.get(node.index)
            seen = pair[0] if pair and pair[0] is not None else frozenset()
            if not seen:
                continue
            first = min(sends[sid].lineno for sid in seen)
            yield self.finding(
                module, call,
                f"Raft durability ordering: `{method}` persists hard "
                f"state here, but an outbound message already left on "
                f"this path (send at line {first}) — a peer can observe "
                "a vote/term/log entry that a crash right now would "
                "forget, which breaks Raft's safety argument; await the "
                "storage write first, then send",
            )
