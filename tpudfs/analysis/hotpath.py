"""Hot-path annotation for tpulint's performance rules (tpuperf).

BENCH r01-r05 located the system's cost in a handful of code paths: the
block transports, the chunkserver read/write handlers, the client bulk
API, and the TPU infeed. A performance finding is only worth a
developer's time when it sits on one of those paths *and* runs more
than once per request — an O(n) copy in a config loader is noise; the
same copy per frame of a chain write is the whole write-pipeline gap.

This module computes, once per :class:`~tpudfs.analysis.callgraph.Project`:

- **hot-path membership** — reachability over resolved call edges from a
  fixed root set of bench/data-plane entry points (``BlockPortServer``
  frame loop, chunkserver ``rpc_*`` handlers, the client's bulk
  read/write API, the TPU infeed/combiner/write-group classes, the
  blockstore primitives those offload to). ``thread``/``task`` edges
  propagate: ``to_thread(store.read, ...)`` moves the bytes, not the
  heat.
- **entry loop depth** — how many loops already enclose a function's
  *call sites* when execution reaches it. A helper called from a
  per-frame ``while`` loop inherits depth 1 even though its own body is
  loop-free; the TPL03x rules add the local CFG depth on top, so "copy
  in a hot loop" means the effective depth, not the lexical one.

Loop depth at a statement comes from the CFG (:attr:`Node.loop_depth`),
with comprehension nesting counted on top — ``[f(x) for x in frames]``
runs ``f`` per frame exactly like the spelled-out loop.

Everything is conservative in the *finding-suppressing* direction:
unresolved calls propagate nothing, so a function is only "hot" when a
resolved chain from a root actually reaches it.
"""

from __future__ import annotations

import ast
import re
from collections import deque

from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.cfg import cfg_for

__all__ = ["HotPaths", "hot_paths", "loop_depth_at"]

#: Effective-depth cap: bounds the fixpoint and keeps a pathological
#: loop-in-loop-in-loop chain from dominating every report.
_DEPTH_CAP = 4

#: Qualname patterns of the data-plane roots. These mirror what bench.py
#: drives (bench itself lives outside the linted tree): every scenario
#: enters through the client bulk API or the infeed, which fan out to
#: the transports and chunkserver handlers below.
_ROOT_PATTERNS = [
    # Block transport: the per-frame serve loop and the client pool call.
    r"^tpudfs\.common\.blocknet\.BlockPortServer\._handle$",
    r"^tpudfs\.common\.blocknet\.BlockConnPool\.call$",
    r"^tpudfs\.common\.blocknet\._call_blockport$",
    # Chunkserver request handlers (both transports dispatch here) and
    # the collective-write persist entry.
    r"^tpudfs\.chunkserver\.service\.ChunkServer\."
    r"(rpc_\w+|persist_ici_replica)$",
    # Blockstore primitives: handlers offload to them per block.
    r"^tpudfs\.chunkserver\.blockstore\.BlockStore\."
    r"(read\w*|write\w*|verify\w*|publish\w*)$",
    # Client bulk data API (what `put`/`get`/benchmark drive).
    r"^tpudfs\.client\.client\.Client\."
    r"(create_file|read_file\w*|_read_\w+|_write_\w+)$",
    # TPU data plane: infeed sources, HBM reader, combiner, write group.
    r"^tpudfs\.tpu\.grain_infeed\.(DfsSourceBase|DfsRecordSource|"
    r"_ClientLoop)\.\w+$",
    r"^tpudfs\.tpu\.hbm_reader\.HbmReader\.\w+$",
    r"^tpudfs\.tpu\.read_combiner\.ReadCombiner\.\w+$",
    r"^tpudfs\.tpu\.write_group\.IciWriteGroup\.\w+$",
]

_ROOT_RE = re.compile("|".join(f"(?:{p})" for p in _ROOT_PATTERNS))

_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)


def _stmt_depths(module, fn: ast.AST) -> dict[int, int]:
    """``id(stmt) -> loop_depth`` over the function's CFG nodes; a stmt
    represented by several nodes (with_enter/with_exit) takes the max."""
    cfg = cfg_for(module, fn)
    depths = getattr(cfg, "_stmt_depths", None)
    if depths is None:
        depths = {}
        for node in cfg.nodes:
            if node.stmt is None:
                continue
            key = id(node.stmt)
            if node.loop_depth > depths.get(key, -1):
                depths[key] = node.loop_depth
        cfg._stmt_depths = depths
    return depths


def loop_depth_at(module, fn: ast.AST, node: ast.AST) -> int:
    """Lexical loop-nesting depth of ``node`` inside ``fn``: the CFG
    depth of its enclosing statement, plus one per comprehension between
    the statement and ``node``."""
    depths = _stmt_depths(module, fn)
    comp = 0
    cur: ast.AST | None = node
    while cur is not None and cur is not fn:
        if id(cur) in depths:
            return depths[id(cur)] + comp
        if isinstance(cur, _COMPREHENSIONS):
            comp += 1
        cur = module.parent(cur)
    return comp


class HotPaths:
    """Hot-path membership + entry loop depth for every reachable fn."""

    __slots__ = ("roots", "_depth")

    def __init__(self, roots: set[FunctionInfo],
                 depth: dict[FunctionInfo, int]) -> None:
        self.roots = roots
        self._depth = depth

    def is_hot(self, fn: FunctionInfo) -> bool:
        return fn in self._depth

    def entry_depth(self, fn: FunctionInfo) -> int:
        """Loops already enclosing execution when ``fn`` is entered (max
        over resolved call chains from the roots); 0 for roots and for
        functions that are not hot at all — combine with :meth:`is_hot`."""
        return self._depth.get(fn, 0)

    def effective_depth(self, fn: FunctionInfo, local_depth: int) -> int:
        """Entry depth + the CFG depth of a statement inside ``fn``."""
        return min(_DEPTH_CAP, self.entry_depth(fn) + local_depth)


def hot_paths(project: Project) -> HotPaths:
    """Memoized hot-path computation for the project (one BFS-to-fixpoint
    over call edges; depths only grow and are capped, so it terminates)."""
    cached = getattr(project, "_hotpaths", None)
    if cached is not None:
        return cached

    roots = {fn for qual, fn in project.functions.items()
             if _ROOT_RE.match(qual)}
    depth: dict[FunctionInfo, int] = {fn: 0 for fn in roots}
    work: deque[FunctionInfo] = deque(roots)
    while work:
        fn = work.popleft()
        base = depth[fn]
        for edge in fn.calls:
            site_depth = loop_depth_at(fn.module, fn.node, edge.site)
            new = min(_DEPTH_CAP, base + site_depth)
            if new > depth.get(edge.callee, -1):
                depth[edge.callee] = new
                work.append(edge.callee)

    hp = HotPaths(roots, depth)
    project._hotpaths = hp
    return hp
