"""Machine-readable tpulint output: ``--format json`` and ``--format sarif``.

JSON is the stable programmatic surface (one object, full finding dicts).
SARIF 2.1.0 is the interchange format CI viewers understand (GitHub code
scanning, VS Code SARIF viewer); ``scripts/run_all_tests.py`` drops a
``tpulint.sarif`` artifact per run so lint regressions are diffable across
CI runs the same way BENCH_*.json series are.
"""

from __future__ import annotations

import json

from tpudfs.analysis.linter import Finding, RunResult, all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_json(result: RunResult, *, baselined: bool = True) -> str:
    payload = {
        "tool": "tpulint",
        "new": [f.to_full_dict() for f in result.new],
        "baselined": [f.to_full_dict() for f in result.baselined]
        if baselined else [],
        "stale_baseline": sorted(result.stale_baseline),
        "summary": {
            "new": len(result.new),
            "baselined": len(result.baselined),
            "stale_baseline": len(result.stale_baseline),
        },
    }
    return json.dumps(payload, indent=2) + "\n"


def _sarif_result(f: Finding, *, baselined: bool) -> dict:
    return {
        "ruleId": f.rule,
        "level": "note" if baselined else "error",
        "message": {"text": f.message},
        "partialFingerprints": {"tpulint/v1": f.fingerprint},
        "baselineState": "unchanged" if baselined else "new",
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {
                    "startLine": max(f.line, 1),
                    "startColumn": max(f.col + 1, 1),
                },
            },
            "logicalLocations": [{"fullyQualifiedName": f.scope or
                                  "<module>"}],
        }],
    }


def render_sarif(result: RunResult) -> str:
    rules_meta = [
        {
            "id": rule.id,
            "name": rule.name,
            "shortDescription": {"text": rule.summary},
        }
        for rule in all_rules().values()
    ]
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "tpulint",
                    "informationUri":
                        "docs/static-analysis.md",
                    "rules": rules_meta,
                }
            },
            "results": [
                *(_sarif_result(f, baselined=False) for f in result.new),
                *(_sarif_result(f, baselined=True)
                  for f in result.baselined),
            ],
        }],
    }
    return json.dumps(doc, indent=2) + "\n"
