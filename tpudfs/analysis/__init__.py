"""tpulint: distributed-systems-aware static analysis for tpudfs.

Run ``python -m tpudfs.analysis`` (or ``scripts/lint.py``) to lint the tree;
see tpudfs/analysis/linter.py for the framework and docs/static-analysis.md
for the rule catalogue.
"""

from tpudfs.analysis.linter import (  # noqa: F401
    Finding,
    ModuleInfo,
    Rule,
    all_rules,
    analyze_file,
    analyze_tree,
    load_baseline,
    register,
    run,
    write_baseline,
)
