"""Generic forward fixed-point dataflow over :mod:`tpudfs.analysis.cfg`.

Rules plug a small lattice into :class:`ForwardAnalysis` and call
:func:`solve`; the solver runs a worklist to a fixed point and hands back
per-node in/out values. Two lattice families cover every current rule:

- **may** analyses (union join, e.g. "a resource acquired on *some* path
  into this node is still unreleased") — used by TPL021's leak check,
  TPL022, TPL023;
- **must** analyses (intersection join, e.g. "a lock is held on *every*
  path into this node") — used by the TPL020 race detector's
  is-this-access-guarded question.

Values must be hashable immutable sets (``frozenset``) or ``None``;
``None`` is the "unreached" bottom that any join absorbs, which is what
makes intersection-style must-analyses startable from an empty worklist
seed without poisoning every meet with the empty set.

Termination: transfer functions must be monotone and the value domain
finite (site sets within one function), so the worklist settles in
O(nodes × domain) steps; a generous iteration cap turns a buggy lattice
into a loud failure instead of a hang.
"""

from __future__ import annotations

from typing import Callable, Hashable

from tpudfs.analysis.cfg import CFG, Node

__all__ = ["ForwardAnalysis", "MayAnalysis", "MustAnalysis", "solve"]

Value = Hashable  # frozenset in practice; None = unreached


class ForwardAnalysis:
    """Override :meth:`transfer`; pick a join by subclassing
    :class:`MayAnalysis` or :class:`MustAnalysis`."""

    def initial(self) -> Value:
        """Value at function entry."""
        return frozenset()

    def join(self, a: Value, b: Value) -> Value:
        raise NotImplementedError

    def transfer(self, node: Node, value: Value) -> Value:
        """Out-value of ``node`` given its in-value. ``value`` is never
        None (unreached nodes are not transferred)."""
        return value

    def edge_filter(self, src: Node, dst: Node, kind: str) -> bool:
        """Return False to ignore an edge (e.g. cut loop back edges)."""
        return True

    def edge_value(self, src: Node, dst: Node, kind: str,
                   value: Value) -> Value:
        """Value carried along one outgoing edge; defaults to the node's
        out-value. Lets a rule model e.g. "if the acquire statement itself
        raised, nothing was acquired" on ``exc`` edges."""
        return value


class MayAnalysis(ForwardAnalysis):
    """Union join: a fact holds at a node if it holds on some path in."""

    def join(self, a: Value, b: Value) -> Value:
        if a is None:
            return b
        if b is None:
            return a
        return a | b  # type: ignore[operator]


class MustAnalysis(ForwardAnalysis):
    """Intersection join: a fact holds only if it holds on every path in."""

    def join(self, a: Value, b: Value) -> Value:
        if a is None:
            return b
        if b is None:
            return a
        return a & b  # type: ignore[operator]


def solve(
    cfg: CFG,
    analysis: ForwardAnalysis,
    skip_edges: set[tuple[int, int]] | None = None,
) -> dict[int, tuple[Value, Value]]:
    """Run ``analysis`` forward over ``cfg`` to a fixed point.

    Returns ``{node.index: (in_value, out_value)}`` for reachable nodes;
    an in/out of ``None`` means the node was never reached under the
    (possibly edge-filtered) path set. ``skip_edges`` removes specific
    ``(src_index, dst_index)`` edges — pass ``cfg.back_edges()`` for
    per-iteration ordering properties.
    """
    order = cfg.rpo()
    position = {n.index: i for i, n in enumerate(order)}

    in_vals: dict[int, Value] = {cfg.entry.index: analysis.initial()}
    out_vals: dict[int, Value] = {}

    # Worklist seeded in RPO; a priority re-queue keeps passes near-linear
    # on reducible graphs.
    pending = list(order)
    queued = {n.index for n in pending}
    steps = 0
    cap = 64 * (len(order) + 8) * (len(order) + 8)

    while pending:
        pending.sort(key=lambda n: position[n.index], reverse=True)
        node = pending.pop()
        queued.discard(node.index)
        steps += 1
        if steps > cap:  # pragma: no cover - lattice bug guard
            raise RuntimeError(
                f"dataflow did not converge in {cap} steps "
                f"({cfg.fn.name} at line {cfg.fn.lineno})")

        in_val = in_vals.get(node.index)
        if in_val is None:
            continue
        out_val = analysis.transfer(node, in_val)
        if node.index in out_vals and out_vals[node.index] == out_val:
            continue
        out_vals[node.index] = out_val

        for succ, kind in node.succs:
            if skip_edges and (node.index, succ.index) in skip_edges:
                continue
            if not analysis.edge_filter(node, succ, kind):
                continue
            carried = analysis.edge_value(node, succ, kind, out_val)
            merged = analysis.join(in_vals.get(succ.index), carried)
            if merged != in_vals.get(succ.index):
                in_vals[succ.index] = merged
                if succ.index not in queued:
                    pending.append(succ)
                    queued.add(succ.index)

    return {
        idx: (in_vals.get(idx), out_vals.get(idx))
        for idx in set(in_vals) | set(out_vals)
    }
