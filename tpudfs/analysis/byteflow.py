"""tpuflow: a static byte-cost ledger for the data-plane routes.

TPL030-034 catch *local* copy shapes (a slice in a hot loop, a
``bytes(mv)`` under a lock). What they cannot see is the whole-route
picture: how many times one payload byte is copied, checksummed and
(de)serialized between the client API and the disk or HBM it lands in.
This module builds that view statically, on top of the existing layers:

- the call graph (:mod:`tpudfs.analysis.callgraph`) resolves each named
  route entry point and the helpers it reaches,
- the CFG + dataflow solver (:mod:`tpudfs.analysis.cfg`,
  :mod:`tpudfs.analysis.dataflow`) orders the statements,
- buffer provenance (:mod:`tpudfs.analysis.bufferflow`) tells a payload
  buffer from a header int.

A **route** is a named slice of the data plane — client chain write,
warm-infeed read, chunkserver cache hit, EC encode/scatter, checkpoint
stage→publish — pinned by entry-function qualnames and bounded by the
modules the route's bytes actually traverse. For every function on a
route the walker counts, with ``file:line`` attribution ("hops"):

- **copies** — full-buffer O(n) events: ``bytes(mv)``, slicing a
  ``bytes``, concat, ``b"".join``, ``struct.pack``/msgpack of a payload
  buffer, ``.tobytes()``/``.hex()``/``.decode()`` on payloads;
- **crc_passes** — calls into :mod:`tpudfs.common.checksum`;
- **serializations** — pack/unpack/dumps/loads crossings.

The result is the committed ledger ``tpudfs/analysis/copy_ledger.json``.
CI recomputes it and fails when any route's copy count rises above the
committed budget (see :func:`check_ledger`), turning "we added a copy to
the hot path" into a red diff the same way the suppression ratchet turns
"we silenced a rule" into one. ``python -m tpudfs.analysis
--write-ledger`` regenerates the file but refuses silent growth.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass

from tpudfs.analysis import bufferflow
from tpudfs.analysis.bufferflow import CRC_CALLS, PAYLOAD_NAME_RE
from tpudfs.analysis.callgraph import FunctionInfo, Project
from tpudfs.analysis.cfg import cfg_for

__all__ = [
    "CACHE_ROUTE",
    "DIRECT_ROUTE",
    "LEDGER_REL_PATH",
    "LEDGER_VERSION",
    "ROUTES",
    "RouteSpec",
    "check_ledger",
    "compute_ledger",
    "ledger_for_project",
    "load_committed_ledger",
    "load_project",
    "route_functions",
    "routes_for_files",
    "write_ledger_file",
]

LEDGER_REL_PATH = "tpudfs/analysis/copy_ledger.json"
LEDGER_VERSION = 1

#: Route names TPL064 compares: the cache-hit path must not cost more
#: copies per byte than the direct (warm-infeed) read path it shortcuts.
CACHE_ROUTE = "cache_hit_read"
DIRECT_ROUTE = "warm_infeed_read"


@dataclass(frozen=True)
class RouteSpec:
    """One named data-plane route.

    ``entries`` are full-match regexes over function qualnames; the
    route's function set is those entries plus everything they reach
    over resolved call edges within ``modules``, ``depth`` hops deep
    (nested ``def``s of a member are always included — their statements
    live outside the enclosing function's own CFG nodes). ``exclude``
    patterns reject qualnames that share a module with the route but
    belong to a different route's budget (e.g. the EC degraded-read
    helpers reachable from the cache-hit entry).
    """

    name: str
    title: str
    entries: tuple[str, ...]
    modules: tuple[str, ...]
    depth: int = 2
    exclude: tuple[str, ...] = ()


ROUTES: tuple[RouteSpec, ...] = (
    RouteSpec(
        name="chain_write",
        title="client chain write -> frame pipeline -> staged disk",
        entries=(
            r"tpudfs\.client\.client\.Client\.create_file",
            r"tpudfs\.client\.client\.Client\._write_blocks_and_complete",
            r"tpudfs\.client\.client\.Client\._write_replicated_block",
            r"tpudfs\.common\.writestream\.send_block_stream",
            r"tpudfs\.chunkserver\.service\.ChunkServer\.rpc_write_stream",
            r"tpudfs\.chunkserver\.service\.ChunkServer\.rpc_write_block",
        ),
        modules=(
            "tpudfs/client/client.py",
            "tpudfs/common/writestream.py",
            "tpudfs/common/blocknet.py",
            "tpudfs/chunkserver/service.py",
            "tpudfs/chunkserver/blockstore.py",
        ),
    ),
    RouteSpec(
        name="warm_infeed_read",
        title="HBM / warm-infeed read (fused ReadBlocks scatter)",
        entries=(
            r"tpudfs\.tpu\.hbm_reader\.HbmReader\.sweep_metas_to_device",
            r"tpudfs\.tpu\.read_combiner\.ReadCombiner\._fetch_remote",
            r"tpudfs\.chunkserver\.service\.ChunkServer\.rpc_read_blocks",
        ),
        modules=(
            "tpudfs/tpu/hbm_reader.py",
            "tpudfs/tpu/read_combiner.py",
            "tpudfs/chunkserver/service.py",
            "tpudfs/common/blocknet.py",
            "tpudfs/chunkserver/blockstore.py",
        ),
    ),
    RouteSpec(
        name="cache_hit_read",
        title="chunkserver cache hit (per-block ReadBlock)",
        entries=(
            r"tpudfs\.tpu\.hbm_reader\.HbmReader\._read_block_inner",
            r"tpudfs\.client\.client\.Client\._read_block_range",
            r"tpudfs\.chunkserver\.service\.ChunkServer\.rpc_read_block",
        ),
        modules=(
            "tpudfs/tpu/hbm_reader.py",
            "tpudfs/client/client.py",
            "tpudfs/chunkserver/service.py",
            "tpudfs/common/blocknet.py",
        ),
        # Reaches the blockport transport: _read_block_range ->
        # _data_call -> BlockConnPool.call -> _call_blockport ->
        # _pack_frame/_read_frame.
        depth=4,
        # EC degraded-read helpers are reachable from _read_block_inner
        # but their copies are the EC route's budget, not the cache
        # hit's (TPL064 compares cache vs direct on like-for-like hops).
        exclude=(
            r".*\._ec_block_to_device(\..*)?",
            r".*\._read_ec_shards(\..*)?",
            r".*\._read_ec_block(\..*)?",
        ),
    ),
    RouteSpec(
        name="ec_encode_scatter",
        title="EC encode/scatter write + degraded shard read",
        entries=(
            r"tpudfs\.client\.client\.Client\._write_ec_block",
            r"tpudfs\.client\.client\.Client\._read_ec_shards",
            r"tpudfs\.client\.client\.Client\._read_ec_block",
            r"tpudfs\.tpu\.hbm_reader\.HbmReader\._ec_block_to_device",
            r"tpudfs\.common\.erasure\.encode",
        ),
        modules=(
            "tpudfs/client/client.py",
            "tpudfs/common/erasure.py",
            "tpudfs/common/blocknet.py",
            "tpudfs/tpu/hbm_reader.py",
        ),
    ),
    RouteSpec(
        name="ckpt_stage_publish",
        title="checkpoint stage -> verify -> publish",
        entries=(
            r"tpudfs\.tpu\.checkpoint\.CheckpointManager\.save_shard",
            r"tpudfs\.tpu\.checkpoint\.CheckpointManager\._put_if_absent",
            r"tpudfs\.tpu\.checkpoint\.CheckpointManager\.commit",
        ),
        modules=("tpudfs/tpu/checkpoint.py",),
    ),
)

#: pack/unpack family: every call is a serialization crossing; with a
#: payload-provenance argument it is additionally a full-buffer copy.
_SER_CALLS = {
    "pack", "packb", "dumps", "loads", "unpack", "unpackb",
    "pack_into", "unpack_from",
}
#: attribute calls that materialize a fresh full-size buffer.
_COPY_ATTR_CALLS = {"tobytes", "hex", "decode"}
#: repo helpers that are known full-buffer materializations when fed a
#: payload (checksum.bytes_to_words zero-pads + casts into a new array).
_COPY_HELPERS = {"bytes_to_words": "pad-cast"}


def _callee(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _payloadish(expr: ast.AST, env: dict[str, set[str]]) -> bool:
    """Does ``expr`` plausibly hold a full data payload? Deliberately
    name-anchored: an inline ``readexactly(4)`` header read is a bytes
    *producer* but not a payload, so serialize calls over it are a wire
    crossing, not a full-buffer copy."""
    if isinstance(expr, ast.Name):
        return bool(PAYLOAD_NAME_RE.match(expr.id)) or bool(env.get(expr.id))
    if isinstance(expr, ast.Attribute):
        return bool(PAYLOAD_NAME_RE.match(expr.attr))
    return False


def _rx_rebuffer(call: ast.Call) -> bool:
    """A ``Read*`` data call without a ``payload_into`` scatter target:
    the response payload materializes in a fresh ``bytes`` (blockport
    ``readexactly`` or the gRPC plane) instead of landing in the caller's
    buffer — one full-buffer copy attributable to the call site."""
    if _callee(call) != "_data_call":
        return False
    method = next((a.value for a in call.args
                   if isinstance(a, ast.Constant)
                   and isinstance(a.value, str)), "")
    if not method.startswith("Read"):
        return False
    for kw in call.keywords:
        if kw.arg == "payload_into" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None):
            return False
    return True


def _classify(expr: ast.AST,
              env: dict[str, set[str]]) -> list[tuple[str, str]]:
    """Byte-cost events a single expression incurs:
    ``[(kind, label)]`` with kind in {"copy", "crc", "serialize"}."""
    events: list[tuple[str, str]] = []
    label = bufferflow.is_copy_expr(expr, env)
    if label is not None:
        events.append(("copy", label))
    if not isinstance(expr, ast.Call):
        return events
    name = _callee(expr)
    if name in CRC_CALLS:
        events.append(("crc", name))
    if name in _SER_CALLS:
        events.append(("serialize", name))
        if any(_payloadish(a, env) for a in expr.args):
            events.append(("copy", f"{name}(payload)"))
    if name == "tobytes" and isinstance(expr.func, ast.Attribute) \
            and not expr.args:
        # Always a full materialization — that is the method's purpose.
        events.append(("copy", name))
    elif name in _COPY_ATTR_CALLS and isinstance(expr.func, ast.Attribute) \
            and not expr.args and _payloadish(expr.func.value, env):
        events.append(("copy", name))
    if name in _COPY_HELPERS \
            and any(_payloadish(a, env) for a in expr.args):
        events.append(("copy", _COPY_HELPERS[name]))
    if _rx_rebuffer(expr):
        events.append(("copy", "rx-rebuffer"))
    return events


def _walk_own(top: ast.AST):
    """``ast.walk`` that does not descend into nested ``def`` bodies —
    those are separate route members with their own CFGs, and walking
    them here would double-count every hop they contain."""
    nested = (ast.FunctionDef, ast.AsyncFunctionDef)
    if isinstance(top, nested):
        # A nested-def statement: its decorators/defaults run here, the
        # body belongs to the nested function's own cost walk.
        stack: list[ast.AST] = [*top.decorator_list,
                                *top.args.defaults, *top.args.kw_defaults]
        stack = [n for n in stack if n is not None]
    else:
        stack = [top]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, nested):
                continue
            stack.append(child)


def function_costs(fn: FunctionInfo) -> list[dict]:
    """Byte-cost hops inside one function, ordered by line."""
    module = fn.module
    flow = bufferflow.buffer_flow(module, fn.node)
    cfg = cfg_for(module, fn.node)
    hops: list[dict] = []
    seen: set[tuple[int, int, str, str]] = set()
    for node in cfg.nodes:
        in_facts, _out = flow.get(node.index, (None, None))
        env = bufferflow.env_from(in_facts)
        for top in node.exprs():
            for expr in _walk_own(top):
                events = _classify(expr, env)
                if not events:
                    continue
                line = getattr(expr, "lineno", node.lineno)
                col = getattr(expr, "col_offset", 0)
                for kind, label in events:
                    key = (line, col, kind, label)
                    if key in seen:
                        continue
                    seen.add(key)
                    hops.append({
                        "file": module.rel_path, "line": line,
                        "kind": kind, "label": label, "fn": fn.short(),
                    })
    hops.sort(key=lambda h: (h["file"], h["line"], h["kind"], h["label"]))
    return hops


def route_functions(project: Project,
                    spec: RouteSpec) -> list[FunctionInfo]:
    """Entry functions plus scope-bounded BFS over resolved call edges,
    plus the nested ``def``s of every member (their bodies are separate
    CFGs)."""
    pats = [re.compile(p) for p in spec.entries]
    excl = [re.compile(p) for p in spec.exclude]
    members: dict[str, FunctionInfo] = {}
    by_prefix = sorted(project.functions.items())

    def _admit(fn: FunctionInfo, frontier: list[FunctionInfo]) -> None:
        """Add ``fn`` and its nested defs (scatter callbacks, hedged
        read-closure bodies — separate CFGs, same logical hop)."""
        if fn.qualname in members:
            return
        if any(x.fullmatch(fn.qualname) for x in excl):
            return
        members[fn.qualname] = fn
        frontier.append(fn)
        prefix = fn.qualname + "."
        for qual, nested in by_prefix:
            if qual.startswith(prefix):
                _admit(nested, frontier)

    frontier: list[FunctionInfo] = []
    for qual, fn in by_prefix:
        if any(p.fullmatch(qual) for p in pats):
            _admit(fn, frontier)
    for _hop in range(spec.depth):
        nxt: list[FunctionInfo] = []
        for fn in frontier:
            for edge in fn.calls:
                if edge.callee.module.rel_path in spec.modules:
                    _admit(edge.callee, nxt)
        frontier = nxt
    return [members[q] for q in sorted(members)]


def compute_ledger(project: Project) -> dict:
    """The full per-route byte-cost ledger for one parsed project.
    Memoized on the project: TPL064 and the CLI gate share one walk."""
    cached = getattr(project, "_byteflow_ledger", None)
    if cached is not None:
        return cached
    routes: dict[str, dict] = {}
    for spec in ROUTES:
        fns = route_functions(project, spec)
        hops: list[dict] = []
        for fn in fns:
            hops.extend(function_costs(fn))
        hops.sort(key=lambda h: (h["file"], h["line"], h["kind"],
                                 h["label"]))
        routes[spec.name] = {
            "title": spec.title,
            "copies": sum(h["kind"] == "copy" for h in hops),
            "crc_passes": sum(h["kind"] == "crc" for h in hops),
            "serializations": sum(h["kind"] == "serialize" for h in hops),
            "functions": sorted(fn.qualname for fn in fns),
            "hops": [
                f"{h['file']}:{h['line']} {h['kind']}:{h['label']}"
                f" [{h['fn']}]"
                for h in hops
            ],
        }
    ledger = {"version": LEDGER_VERSION, "routes": routes}
    project._byteflow_ledger = ledger
    return ledger


def load_project(root: pathlib.Path) -> Project:
    """Parse the ``tpudfs`` package under ``root`` (or the whole root
    when there is no package dir) into one Project, with module paths
    relative to ``root`` so they match the route specs."""
    from tpudfs.analysis import linter

    pkg = root / "tpudfs"
    base = pkg if pkg.is_dir() else root
    modules = {}
    for path in linter.iter_python_files(base):
        module, _errors = linter._load_module(path, root)
        if module is not None:
            modules[module.rel_path] = module
    return Project(modules)


def ledger_for_project(root: pathlib.Path) -> dict:
    return compute_ledger(load_project(root))


def load_committed_ledger(root: pathlib.Path) -> dict | None:
    path = root / LEDGER_REL_PATH
    if not path.is_file():
        return None
    return json.loads(path.read_text(encoding="utf-8"))


def write_ledger_file(root: pathlib.Path, ledger: dict) -> None:
    path = root / LEDGER_REL_PATH
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(ledger, indent=2, sort_keys=True) + "\n",
                    encoding="utf-8")


def check_ledger(computed: dict, committed: dict) -> list[str]:
    """Budget breaches: any route whose copy count rose above the
    committed budget (or a committed route that vanished). Returns
    human-readable messages; empty means the budget holds."""
    breaches: list[str] = []
    committed_routes = committed.get("routes", {})
    computed_routes = computed.get("routes", {})
    for name, budget in sorted(committed_routes.items()):
        live = computed_routes.get(name)
        if live is None:
            breaches.append(f"route {name}: present in committed ledger "
                            "but no longer computed")
            continue
        if live["copies"] > budget["copies"]:
            known = set(budget["hops"])
            new_copy = [h for h in live["hops"]
                        if " copy:" in h and h not in known]
            detail = "; ".join(new_copy[:4])
            breaches.append(
                f"route {name}: {live['copies']} copies > committed "
                f"budget {budget['copies']}"
                + (f" (new: {detail})" if detail else "")
            )
    return breaches


def ledger_is_stale(computed: dict, committed: dict | None) -> bool:
    """Exact-sync gate: the committed ledger must match the tree."""
    return committed != computed


def routes_for_files(rel_paths) -> list[str]:
    """Route names whose module scope intersects ``rel_paths`` (plus
    every route when the committed ledger itself changed). Static — no
    project build needed, so ``--changed`` stays inside its budget."""
    paths = set(rel_paths)
    out = []
    for spec in ROUTES:
        if LEDGER_REL_PATH in paths or paths.intersection(spec.modules):
            out.append(spec.name)
    return out
