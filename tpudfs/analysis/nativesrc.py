"""C++ source extraction for the native-conformance rules (TPL040-TPL043).

The native data plane (native/dataplane.cc) re-implements the blockport
wire protocol and the dataplane C ABI that tpudfs/common/native.py binds
with ctypes — two hand-maintained copies of one contract, on opposite
sides of a language boundary no type checker crosses. This module gives
the tpulint rules a view of the C++ side without a real C++ frontend:

- a comment/string-aware tokenizer with multi-char operators,
- ``extern "C"`` export signatures (name, return/param C types, arity)
  normalized into the same canonical vocabulary the ctypes declarations
  map into (:data:`CTYPES_CANON`, :func:`ctype_compatible`),
- file-scope ``constexpr`` integer constants, evaluated (``1 << 20``,
  ``100ull * 1024 * 1024``) so they can be diffed against the Python
  protocol constants,
- every string literal (msgpack header keys, status codes),
- a structural map of classes/fields/methods plus a lexical
  lock-region tracker (``lock_guard``/``unique_lock`` scopes, including
  mid-scope ``.unlock()``/``.lock()`` toggles) for the concurrency
  rules, and
- the ctypes declarations of native.py parsed from its AST
  (:func:`parse_ctypes_decls`).

This is a pragmatic lexical pass, not a compiler: it understands the
subset of C++ the native engine is written in (and that the fixtures
exercise), and the rules built on it bias toward zero false positives on
the real tree. Suppression grammar mirrors the Python one with C++
comments: ``// tpulint: disable=TPL042`` (line or line above) and
``// tpulint: disable-file=TPL042``; ``// tpulint: pre-start`` above a
method marks it as running before any engine thread exists (constructor
and destructor get that for free); ``// tpulint: guarded-by(mu_)`` above
a method asserts that every caller already holds ``mu_`` — the lock
analysis treats the whole body as running under that mutex (the lexical
twin of Clang's ``REQUIRES()`` thread-safety annotation).
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field

__all__ = [
    "NativeSource",
    "CFunc",
    "CParam",
    "CClass",
    "CMethod",
    "CField",
    "PyCtypesDecls",
    "parse_native",
    "load_native_sources",
    "iter_native_files",
    "has_native_sources",
    "project_root",
    "parse_ctypes_decls",
    "py_int_constants",
    "py_string_literals",
    "ctype_compatible",
    "format_ctype_for_human",
]

NATIVE_DIR_NAME = "native"

_NATIVE_SUFFIXES = (".cc", ".h")

# --------------------------------------------------------------- tokenizer

_MULTI_OPS = (
    "<<=", ">>=", "->*", "...",
    "::", "->", "++", "--", "<<", ">>", "<=", ">=", "==", "!=",
    "&&", "||", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=",
)

_ID_START = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_ID_CONT = _ID_START | set("0123456789")
_DIGITS = set("0123456789")


@dataclass(frozen=True)
class Token:
    kind: str  # "id" | "num" | "str" | "char" | "punct"
    text: str
    line: int


def tokenize(text: str) -> tuple[list[Token], list[tuple[int, str]]]:
    """Tokens plus ``(line, comment_text)`` pairs (comments stripped)."""
    toks: list[Token] = []
    comments: list[tuple[int, str]] = []
    i, n, line = 0, len(text), 1
    while i < n:
        c = text[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if c == "#":
            # Preprocessor directive: skip to end of line (no
            # continuations in the sources this pass targets).
            j = text.find("\n", i)
            i = n if j < 0 else j
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            comments.append((line, text[i:j]))
            i = j
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            chunk = text[i:j + 2]
            comments.append((line, chunk))
            line += chunk.count("\n")
            i = j + 2
            continue
        if c == '"':
            j, buf = i + 1, []
            while j < n and text[j] != '"':
                if text[j] == "\\" and j + 1 < n:
                    buf.append(text[j:j + 2])
                    j += 2
                    continue
                buf.append(text[j])
                j += 1
            raw = "".join(buf)
            try:
                # Unescape via the C-ish subset Python shares.
                val = raw.encode().decode("unicode_escape")
            except UnicodeDecodeError:
                val = raw
            toks.append(Token("str", val, line))
            i = j + 1
            continue
        if c == "'":
            j = i + 1
            while j < n and text[j] != "'":
                j += 2 if text[j] == "\\" else 1
            toks.append(Token("char", text[i + 1:j], line))
            i = j + 1
            continue
        if c in _ID_START:
            j = i + 1
            while j < n and text[j] in _ID_CONT:
                j += 1
            toks.append(Token("id", text[i:j], line))
            i = j
            continue
        if c in _DIGITS:
            j = i + 1
            while j < n and (text[j] in _ID_CONT or text[j] in ".'"):
                j += 1
            toks.append(Token("num", text[i:j], line))
            i = j
            continue
        matched = False
        for op in _MULTI_OPS:
            if text.startswith(op, i):
                toks.append(Token("punct", op, line))
                i += len(op)
                matched = True
                break
        if not matched:
            toks.append(Token("punct", c, line))
            i += 1
    return toks, comments


# ------------------------------------------------------ constant evaluation


def _parse_c_int(text: str) -> int | None:
    t = text.replace("'", "")
    while t and t[-1] in "uUlL":
        t = t[:-1]
    try:
        return int(t, 0)
    except ValueError:
        return None


class _ExprEval:
    """Tiny recursive-descent evaluator for constexpr integer RHS."""

    def __init__(self, toks: list[Token], env: dict[str, int]):
        self.toks = toks
        self.env = env
        self.i = 0

    def _peek(self) -> Token | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def eval(self) -> int | None:
        try:
            v = self._or()
        except (ValueError, TypeError):
            return None
        return v if self._peek() is None else None

    def _binop(self, sub, ops):
        v = sub()
        while True:
            t = self._peek()
            if t is None or t.kind != "punct" or t.text not in ops:
                return v
            self.i += 1
            rhs = sub()
            v = ops[t.text](v, rhs)

    def _or(self):
        return self._binop(self._xor, {"|": lambda a, b: a | b})

    def _xor(self):
        return self._binop(self._and, {"^": lambda a, b: a ^ b})

    def _and(self):
        return self._binop(self._shift, {"&": lambda a, b: a & b})

    def _shift(self):
        return self._binop(self._add, {"<<": lambda a, b: a << b,
                                       ">>": lambda a, b: a >> b})

    def _add(self):
        return self._binop(self._mul, {"+": lambda a, b: a + b,
                                       "-": lambda a, b: a - b})

    def _mul(self):
        return self._binop(self._unary, {"*": lambda a, b: a * b,
                                         "/": lambda a, b: a // b,
                                         "%": lambda a, b: a % b})

    def _unary(self):
        t = self._peek()
        if t is None:
            raise ValueError("eof")
        if t.kind == "punct" and t.text == "-":
            self.i += 1
            return -self._unary()
        if t.kind == "punct" and t.text == "~":
            self.i += 1
            return ~self._unary()
        if t.kind == "punct" and t.text == "(":
            self.i += 1
            v = self._or()
            t2 = self._peek()
            if t2 is None or t2.text != ")":
                raise ValueError("unbalanced")
            self.i += 1
            return v
        if t.kind == "num":
            self.i += 1
            v = _parse_c_int(t.text)
            if v is None:
                raise ValueError("bad literal")
            return v
        if t.kind == "id":
            self.i += 1
            if t.text in self.env:
                return self.env[t.text]
            # static_cast<...>(x) and friends are out of scope.
            raise ValueError("unknown name")
        raise ValueError("unexpected")


# ----------------------------------------------------- C type normalization

_SCALAR_CANON = {
    "void": "void", "bool": "bool",
    "char": "char", "signedchar": "i8", "int8_t": "i8",
    "uint8_t": "u8", "unsignedchar": "u8",
    "uint16_t": "u16", "unsignedshort": "u16", "unsignedshortint": "u16",
    "int16_t": "i16", "short": "i16", "shortint": "i16",
    "uint32_t": "u32", "unsigned": "u32", "unsignedint": "u32",
    "int32_t": "i32", "int": "i32",
    # LP64: size_t/unsigned long and ssize_t/long alias the 64-bit
    # families — that is the ABI the ctypes layer targets.
    "uint64_t": "u64", "size_t": "u64", "unsignedlong": "u64",
    "unsignedlonglong": "u64", "unsignedlongint": "u64",
    "int64_t": "i64", "ssize_t": "i64", "long": "i64", "longlong": "i64",
    "longint": "i64", "ptrdiff_t": "i64",
    "float": "f32", "double": "f64",
}


def _canon_c_type(type_toks: list[Token], array: bool = False) -> str:
    """Canonical form of a C parameter/return type. Pointers collapse to
    ``cstr``/``cstr2`` (char*/char**) and ``ptr``/``ptr2`` (anything
    else); scalars map via :data:`_SCALAR_CANON`; arrays decay."""
    stars = sum(1 for t in type_toks if t.kind == "punct" and t.text == "*")
    if array:
        stars += 1
    words = [t.text for t in type_toks
             if t.kind == "id" and t.text not in ("const", "struct",
                                                  "volatile", "restrict")]
    base = "".join(words)
    if stars:
        if base == "char":
            return "cstr" if stars == 1 else "cstr2"
        return "ptr" if stars == 1 else "ptr2"
    return _SCALAR_CANON.get(base, f"other:{base}")


_HUMAN = {
    "void": "void", "bool": "bool", "char": "char",
    "i8": "int8_t", "u8": "uint8_t", "i16": "int16_t", "u16": "uint16_t",
    "i32": "int32_t", "u32": "uint32_t", "i64": "int64_t", "u64": "uint64_t",
    "f32": "float", "f64": "double",
    "cstr": "char*", "cstr2": "char**", "ptr": "T*", "ptr2": "T**",
    "anyptr": "void*",
}


def format_ctype_for_human(canon: str) -> str:
    return _HUMAN.get(canon, canon)


def ctype_compatible(py_canon: str, c_canon: str) -> bool:
    """Is a ctypes declaration (canonical) ABI-compatible with a C type?

    ``c_void_p`` (``anyptr``) matches any pointer; ``c_char_p`` requires
    ``char*`` exactly (an out-buffer ``char*`` is also bound as
    ``c_char_p``); scalars must land in the same width/signedness
    family."""
    if py_canon == "anyptr":
        return c_canon in ("cstr", "cstr2", "ptr", "ptr2")
    if py_canon == "ptr2":
        return c_canon in ("ptr2", "cstr2")
    return py_canon == c_canon


# ------------------------------------------------------------- structures


@dataclass(frozen=True)
class CParam:
    canon: str
    name: str


@dataclass
class CFunc:
    name: str
    ret: str
    params: list[CParam]
    line: int
    defined: bool
    rel: str = ""

    @property
    def signature(self) -> str:
        return f"{self.ret}({','.join(p.canon for p in self.params)})"


@dataclass
class CField:
    name: str
    type_text: str
    line: int
    sync: bool  # atomic / mutex / condition_variable / thread
    const: bool


@dataclass
class CMethod:
    name: str
    line: int
    body: list[Token] = field(default_factory=list)
    is_ctor: bool = False
    is_dtor: bool = False
    pre_start: bool = False
    #: Mutexes every caller is asserted to hold (`// tpulint:
    #: guarded-by(mu_)` above the method) — seeds the lock analysis.
    guarded_by: tuple[str, ...] = ()


@dataclass
class CClass:
    name: str
    line: int
    fields: dict[str, CField] = field(default_factory=dict)
    methods: list[CMethod] = field(default_factory=list)

    @property
    def has_sync(self) -> bool:
        return any(f.sync for f in self.fields.values())


_SYNC_TYPE_WORDS = ("atomic", "mutex", "condition_variable", "thread",
                    "shared_mutex", "once_flag")

_DECL_SKIP_LEADERS = {
    "using", "typedef", "friend", "static", "constexpr", "template",
    "enum", "union", "extern", "namespace", "return", "if", "for",
    "while", "switch", "public", "private", "protected", "operator",
    "include", "define", "inline", "virtual",
}

_KEYWORD_IDS = {
    "nullptr", "true", "false", "sizeof", "new", "delete", "this",
    "const", "volatile", "struct", "class", "void", "auto", "default",
}


def _find_matching(toks: list[Token], i: int, open_t: str,
                   close_t: str) -> int:
    """Index of the token closing the ``open_t`` at ``toks[i]``."""
    depth = 0
    for j in range(i, len(toks)):
        t = toks[j]
        if t.kind == "punct":
            if t.text == open_t:
                depth += 1
            elif t.text == close_t:
                depth -= 1
                if depth == 0:
                    return j
    return len(toks) - 1


def _decl_names(unit: list[Token]) -> list[tuple[str, int, bool]]:
    """Declared variable names in a (non-function) declaration statement:
    ``(name, line, is_array)`` triples. Tracks template/paren/brace/
    bracket depth so initializers and template arguments don't leak
    names."""
    names: list[tuple[str, int, bool]] = []
    angle = paren = brace = bracket = 0
    for idx, t in enumerate(unit):
        if t.kind == "punct":
            if t.text == "<":
                angle += 1
            elif t.text == ">":
                angle = max(0, angle - 1)
            elif t.text == ">>":
                angle = max(0, angle - 2)
            elif t.text == "(":
                paren += 1
            elif t.text == ")":
                paren -= 1
            elif t.text == "{":
                brace += 1
            elif t.text == "}":
                brace -= 1
            elif t.text == "[":
                bracket += 1
            elif t.text == "]":
                bracket -= 1
            continue
        if angle or paren or brace or bracket:
            continue
        if t.kind != "id" or t.text in _KEYWORD_IDS:
            continue
        nxt = unit[idx + 1] if idx + 1 < len(unit) else None
        prv = unit[idx - 1] if idx > 0 else None
        # Units arrive without their trailing ';', so end-of-unit is a
        # terminator too — `std::mutex mu_;` declares mu_ even though
        # no punct follows it inside the unit.
        if nxt is not None and nxt.kind != "punct":
            continue
        if nxt is not None and nxt.text not in (";", ",", "=", "{", "["):
            continue
        if prv is None:
            continue
        prev_ok = (prv.kind == "id" and prv.text not in ("return",)) or \
            (prv.kind == "punct" and prv.text in (">", "*", "&", ",", "]"))
        if not prev_ok:
            continue
        names.append((t.text, t.line,
                      nxt is not None and nxt.text == "["))
    return names


def _first_top_level_paren(unit: list[Token]) -> int | None:
    angle = 0
    for idx, t in enumerate(unit):
        if t.kind != "punct":
            continue
        if t.text == "<":
            angle += 1
        elif t.text == ">":
            angle = max(0, angle - 1)
        elif t.text == ">>":
            angle = max(0, angle - 2)
        elif t.text == "(" and angle == 0:
            return idx
        elif t.text == "=":
            # `= lambda` etc: anything after an initializer is not a
            # function declarator.
            return None
    return None


def _split_params(toks: list[Token]) -> list[list[Token]]:
    """Split a parameter token list on top-level commas."""
    out: list[list[Token]] = [[]]
    angle = paren = 0
    for t in toks:
        if t.kind == "punct":
            if t.text == "<":
                angle += 1
            elif t.text == ">":
                angle = max(0, angle - 1)
            elif t.text == ">>":
                angle = max(0, angle - 2)
            elif t.text == "(":
                paren += 1
            elif t.text == ")":
                paren -= 1
            elif t.text == "," and angle == 0 and paren == 0:
                out.append([])
                continue
        out[-1].append(t)
    return [p for p in out if p]


def _parse_param(toks: list[Token]) -> CParam | None:
    if not toks:
        return None
    if len(toks) == 1 and toks[0].text == "void":
        return None
    # Strip default values.
    for idx, t in enumerate(toks):
        if t.kind == "punct" and t.text == "=":
            toks = toks[:idx]
            break
    array = any(t.kind == "punct" and t.text == "[" for t in toks)
    if array:
        toks = toks[:next(i for i, t in enumerate(toks)
                          if t.kind == "punct" and t.text == "[")]
    name = ""
    if toks and toks[-1].kind == "id" and toks[-1].text not in _SCALAR_CANON \
            and toks[-1].text not in ("const", "void"):
        # `const char* host` — trailing id is the parameter name unless
        # the whole declarator is an unnamed scalar (`uint64_t`).
        if len(toks) > 1:
            name = toks[-1].text
            toks = toks[:-1]
    return CParam(_canon_c_type(toks, array=array), name)


def _parse_function(unit: list[Token], body: list[Token],
                    defined: bool) -> CFunc | None:
    paren_i = _first_top_level_paren(unit)
    if paren_i is None or paren_i == 0:
        return None
    name_tok = unit[paren_i - 1]
    if name_tok.kind != "id":
        return None
    close_i = _find_matching(unit, paren_i, "(", ")")
    params = [p for p in (_parse_param(pt)
                          for pt in _split_params(unit[paren_i + 1:close_i]))
              if p is not None]
    ret_toks = unit[:paren_i - 1]
    fn = CFunc(name=name_tok.text, ret=_canon_c_type(ret_toks),
               params=params, line=name_tok.line, defined=defined)
    fn.body = body  # type: ignore[attr-defined]
    return fn


# --------------------------------------------------------------- the parse


_SUPPRESS_CC_RE = re.compile(
    r"//\s*tpulint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\s]+)"
)
_PRE_START_RE = re.compile(r"//\s*tpulint:\s*pre-start\b")
_GUARDED_BY_RE = re.compile(
    r"//\s*tpulint:\s*guarded-by\(\s*([A-Za-z_]\w*)\s*\)")


class NativeSource:
    """One parsed ``native/*.cc`` (or ``.h``) file."""

    def __init__(self, path: pathlib.Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tokens, self.comments = tokenize(text)
        self.exports: list[CFunc] = []       # extern "C" decls + defs
        self.constants: dict[str, int] = {}  # file-scope constexpr ints
        self.constant_lines: dict[str, int] = {}
        self.abi_version: int | None = None
        self.abi_line: int = 0
        self.string_literals: dict[str, int] = {}  # literal -> first line
        self.classes: list[CClass] = []
        self.free_funcs: list[CMethod] = []
        self.globals: dict[str, CField] = {}
        self.status_codes: list[tuple[str, int]] = []
        self.has_threads = False
        self._line_suppressions: dict[int, set[str]] = {}
        self._file_suppressions: set[str] = set()
        self._pre_start_lines: set[int] = set()
        self._guarded_by_lines: dict[int, tuple[str, ...]] = {}
        self._parse_comments()
        self._parse()

    # -- suppressions / annotations ------------------------------------

    def _parse_comments(self) -> None:
        for line, text in self.comments:
            if _PRE_START_RE.search(text):
                self._pre_start_lines.add(line)
            mutexes = tuple(_GUARDED_BY_RE.findall(text))
            if mutexes:
                self._guarded_by_lines[line] = mutexes
            m = _SUPPRESS_CC_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper()
                     for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self._file_suppressions |= rules
            else:
                # Applies to its own line and the next code line.
                self._line_suppressions.setdefault(line, set()).update(rules)
                self._line_suppressions.setdefault(line + 1,
                                                   set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        rule = rule.upper()
        for pool in (self._file_suppressions,
                     self._line_suppressions.get(line, ())):
            if rule in pool or "ALL" in pool:
                return True
        return False

    def _is_pre_start(self, decl_line: int) -> bool:
        return any(ln in self._pre_start_lines
                   for ln in range(decl_line - 2, decl_line + 1))

    def _guarded_by(self, decl_line: int) -> tuple[str, ...]:
        out: tuple[str, ...] = ()
        for ln in range(decl_line - 2, decl_line + 1):
            out += self._guarded_by_lines.get(ln, ())
        return out

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    # -- main parse ----------------------------------------------------

    def _parse(self) -> None:
        toks = self.tokens
        self.has_threads = any(
            t.kind == "id" and t.text == "thread" for t in toks)
        for lit_tok in toks:
            if lit_tok.kind == "str":
                self.string_literals.setdefault(lit_tok.text, lit_tok.line)
        self._collect_status_codes()
        self._walk_scope(0, len(toks), extern_c=False)
        self._finish_abi()

    def _collect_status_codes(self) -> None:
        toks = self.tokens
        for i, t in enumerate(toks):
            if t.kind != "id" or t.text != "respond_err":
                continue
            if i + 1 >= len(toks) or toks[i + 1].text != "(":
                continue
            close = _find_matching(toks, i + 1, "(", ")")
            for j in range(i + 2, close):
                if toks[j].kind == "str":
                    self.status_codes.append((toks[j].text, toks[j].line))
                    break

    def _walk_scope(self, start: int, end: int, extern_c: bool) -> None:
        """Walk namespace-level statements in ``tokens[start:end]``."""
        toks = self.tokens
        i = start
        unit_start = start
        while i < end:
            t = toks[i]
            if t.kind == "punct" and t.text == ";":
                self._handle_statement(toks[unit_start:i], extern_c)
                i += 1
                unit_start = i
                continue
            if t.kind == "id" and t.text == "extern" and i + 1 < end \
                    and toks[i + 1].kind == "str" and toks[i + 1].text == "C":
                if i + 2 < end and toks[i + 2].text == "{":
                    close = _find_matching(toks, i + 2, "{", "}")
                    self._walk_scope(i + 3, close, extern_c=True)
                    i = close + 1
                else:
                    # Single `extern "C" <decl-or-def>`: let the scope
                    # walker continue, but mark from here.
                    j = i + 2
                    stmt_end, body = self._statement_span(j, end)
                    self._handle_unit(toks[j:stmt_end], body, extern_c=True)
                    i = stmt_end if body is None else stmt_end
                    i += 1 if body is None else 0
                unit_start = i
                continue
            if t.kind == "id" and t.text == "namespace":
                # namespace [name] { ... }
                j = i + 1
                while j < end and toks[j].kind == "id":
                    j += 1
                if j < end and toks[j].text == "{":
                    close = _find_matching(toks, j, "{", "}")
                    self._walk_scope(j + 1, close, extern_c=extern_c)
                    i = close + 1
                    unit_start = i
                    continue
                i += 1
                continue
            if t.kind == "id" and t.text in ("class", "struct") \
                    and i + 1 < end and toks[i + 1].kind == "id":
                # Peek: type definition (body) or a declaration/return
                # type (no body before ; or ().
                j = i + 2
                while j < end and toks[j].kind == "punct" \
                        and toks[j].text in (":", ","):
                    # base clause
                    while j < end and toks[j].text != "{":
                        j += 1
                    break
                while j < end and toks[j].kind == "id":
                    j += 1
                if j < end and toks[j].text == "{":
                    close = _find_matching(toks, j, "{", "}")
                    self._parse_class(toks[i + 1].text, toks[i].line,
                                      j + 1, close)
                    # Skip `};` — possible trailing declarator names are
                    # out of scope for this pass.
                    i = close + 1
                    while i < end and toks[i].text != ";":
                        i += 1
                    i += 1
                    unit_start = i
                    continue
            if t.kind == "punct" and t.text == "{":
                # A function definition body (the unit so far is its
                # declarator) or a brace initializer.
                unit = toks[unit_start:i]
                paren_i = _first_top_level_paren(unit)
                close = _find_matching(toks, i, "{", "}")
                if paren_i is not None and paren_i > 0:
                    self._handle_unit(unit, toks[i + 1:close], extern_c)
                    i = close + 1
                    unit_start = i
                    continue
                # Brace initializer inside a declaration: keep scanning
                # the same unit past the balanced braces.
                i = close + 1
                continue
            i += 1
        if unit_start < end:
            self._handle_statement(toks[unit_start:end], extern_c)

    def _statement_span(self, start: int,
                        end: int) -> tuple[int, list[Token] | None]:
        """From ``start``, find either the terminating ``;`` (returns
        ``(index_of_semicolon, None)``) or a function body (returns
        ``(index_after_close_brace, body_tokens)``)."""
        toks = self.tokens
        i = start
        while i < end:
            t = toks[i]
            if t.kind == "punct" and t.text == ";":
                return i, None
            if t.kind == "punct" and t.text == "{":
                unit = toks[start:i]
                if _first_top_level_paren(unit) is not None:
                    close = _find_matching(toks, i, "{", "}")
                    return close + 1, toks[i + 1:close]
                close = _find_matching(toks, i, "{", "}")
                i = close + 1
                continue
            i += 1
        return end, None

    def _handle_statement(self, unit: list[Token], extern_c: bool) -> None:
        self._handle_unit(unit, None, extern_c)

    def _handle_unit(self, unit: list[Token], body: list[Token] | None,
                     extern_c: bool) -> None:
        if not unit:
            return
        lead = unit[0]
        if lead.kind == "id" and lead.text == "constexpr":
            self._parse_constexpr(unit)
            return
        if lead.kind == "id" and lead.text in _DECL_SKIP_LEADERS:
            # `static`, `using`, control keywords... — but a `static`
            # function definition still matters for the blocking-call
            # closure.
            if body is not None and lead.text in ("static", "inline"):
                fn = _parse_function(unit[1:], body, defined=True)
                if fn is not None:
                    self.free_funcs.append(
                        CMethod(fn.name, fn.line, body))
            return
        paren_i = _first_top_level_paren(unit)
        if paren_i is not None and paren_i > 0:
            fn = _parse_function(unit, body or [], defined=body is not None)
            if fn is None:
                return
            if extern_c:
                fn.rel = self.rel
                self.exports.append(fn)
            if body is not None:
                self.free_funcs.append(CMethod(fn.name, fn.line, body))
            return
        if body is not None:
            return
        # Plain namespace-scope declaration: candidate globals.
        type_words = {t.text for t in unit if t.kind == "id"}
        is_const = "const" in type_words or "constexpr" in type_words
        sync = any(w in type_words for w in _SYNC_TYPE_WORDS)
        for name, line, _arr in _decl_names(unit):
            self.globals[name] = CField(
                name=name, line=line, sync=sync, const=is_const,
                type_text=" ".join(t.text for t in unit[:3]))

    def _parse_constexpr(self, unit: list[Token]) -> None:
        # constexpr TYPE NAME = EXPR
        eq = next((i for i, t in enumerate(unit)
                   if t.kind == "punct" and t.text == "="), None)
        if eq is None or eq == 0:
            return
        name_tok = unit[eq - 1]
        if name_tok.kind != "id":
            return
        val = _ExprEval(unit[eq + 1:], self.constants).eval()
        if val is not None:
            self.constants[name_tok.text] = val
            self.constant_lines[name_tok.text] = name_tok.line

    def _parse_class(self, name: str, line: int, start: int,
                     end: int) -> None:
        toks = self.tokens
        cls = CClass(name=name, line=line)
        i = start
        unit_start = start
        while i < end:
            t = toks[i]
            if t.kind == "punct" and t.text == ";":
                self._class_field_unit(cls, toks[unit_start:i])
                i += 1
                unit_start = i
                continue
            if t.kind == "id" and t.text in ("public", "private",
                                             "protected") \
                    and i + 1 < end and toks[i + 1].text == ":":
                i += 2
                unit_start = i
                continue
            if t.kind == "id" and t.text in ("class", "struct", "enum") \
                    and unit_start == i:
                # Nested type: skip its body entirely.
                j = i
                while j < end and toks[j].text != "{" \
                        and toks[j].text != ";":
                    j += 1
                if j < end and toks[j].text == "{":
                    j = _find_matching(toks, j, "{", "}")
                    while j < end and toks[j].text != ";":
                        j += 1
                i = j + 1
                unit_start = i
                continue
            if t.kind == "punct" and t.text == "{":
                unit = toks[unit_start:i]
                paren_i = _first_top_level_paren(unit)
                close = _find_matching(toks, i, "{", "}")
                if paren_i is not None and paren_i > 0:
                    m_name_tok = unit[paren_i - 1]
                    is_dtor = paren_i >= 2 and \
                        unit[paren_i - 2].kind == "punct" and \
                        unit[paren_i - 2].text == "~"
                    method = CMethod(
                        name=("~" if is_dtor else "") + m_name_tok.text,
                        line=unit[0].line,
                        body=toks[i + 1:close],
                        is_ctor=m_name_tok.text == name and not is_dtor,
                        is_dtor=is_dtor,
                        pre_start=self._is_pre_start(unit[0].line),
                        guarded_by=self._guarded_by(unit[0].line),
                    )
                    cls.methods.append(method)
                    i = close + 1
                    unit_start = i
                    continue
                i = close + 1
                continue
            i += 1
        self.classes.append(cls)

    def _class_field_unit(self, cls: CClass, unit: list[Token]) -> None:
        if not unit:
            return
        lead = unit[0]
        if lead.kind == "id" and lead.text in _DECL_SKIP_LEADERS:
            return
        if _first_top_level_paren(unit) is not None:
            return  # method declaration without body
        type_words = {t.text for t in unit if t.kind == "id"}
        is_const = lead.kind == "id" and lead.text == "const"
        sync = any(w in type_words for w in _SYNC_TYPE_WORDS)
        for name, line, _arr in _decl_names(unit):
            cls.fields[name] = CField(
                name=name, line=line, sync=sync, const=is_const,
                type_text=" ".join(t.text for t in unit[:4]))

    def _finish_abi(self) -> None:
        for fn in self.exports:
            if fn.name != "tpudfs_dataplane_abi" or not fn.defined:
                continue
            body = getattr(fn, "body", [])
            for i, t in enumerate(body):
                if t.kind == "id" and t.text == "return" \
                        and i + 1 < len(body) and body[i + 1].kind == "num":
                    v = _parse_c_int(body[i + 1].text)
                    if v is not None:
                        self.abi_version = v
                        self.abi_line = t.line
                    break


# -------------------------------------------------------- lock-region pass


_LOCK_TYPES = ("lock_guard", "unique_lock", "scoped_lock", "shared_lock")


@dataclass
class _HeldLock:
    var: str
    mutex: str
    depth: int
    active: bool = True


def iter_with_locks(body: list[Token], base: tuple[str, ...] = ()):
    """Yield ``(index, token, held)`` for each token of a method body,
    where ``held`` is the tuple of mutex names lexically locked at that
    point (``lock_guard``/``unique_lock`` declarations, honoring
    ``.unlock()``/``.lock()`` toggles and scope ends). ``base`` seeds
    the held set for the whole body — the caller-holds-the-lock contract
    a ``// tpulint: guarded-by(mu_)`` annotation asserts."""
    depth = 0
    locks: list[_HeldLock] = []
    n = len(body)
    i = 0
    while i < n:
        t = body[i]
        if t.kind == "punct":
            if t.text == "{":
                depth += 1
            elif t.text == "}":
                locks = [lk for lk in locks if lk.depth < depth]
                depth -= 1
        if t.kind == "id" and t.text in _LOCK_TYPES and i + 1 < n \
                and body[i + 1].kind == "punct" and body[i + 1].text == "<":
            close_a = _find_matching(body, i + 1, "<", ">")
            j = close_a + 1
            if j < n and body[j].kind == "id" and j + 1 < n \
                    and body[j + 1].text == "(":
                var = body[j].text
                k = j + 2
                while k < n and body[k].kind == "punct" \
                        and body[k].text in ("&", "*"):
                    k += 1
                if k < n and body[k].kind == "id":
                    locks.append(_HeldLock(var=var, mutex=body[k].text,
                                           depth=depth))
                # The declaration tokens themselves are not "under" the
                # new lock for access purposes; skip past the ctor args.
                close_p = _find_matching(body, j + 1, "(", ")")
                for idx in range(i, close_p + 1):
                    yield idx, body[idx], base + tuple(
                        lk.mutex for lk in locks[:-1] if lk.active)
                i = close_p + 1
                continue
        if t.kind == "id" and i + 2 < n and body[i + 1].kind == "punct" \
                and body[i + 1].text == "." and body[i + 2].kind == "id" \
                and body[i + 2].text in ("lock", "unlock"):
            for lk in reversed(locks):
                if lk.var == t.text:
                    lk.active = body[i + 2].text == "lock"
                    break
        yield i, t, base + tuple(lk.mutex for lk in locks if lk.active)
        i += 1


# ------------------------------------------------------------ file loading


def iter_native_files(root: pathlib.Path) -> list[pathlib.Path]:
    base = root / NATIVE_DIR_NAME
    if not base.is_dir():
        return []
    return sorted(p for p in base.iterdir()
                  if p.is_file() and p.suffix in _NATIVE_SUFFIXES)


def has_native_sources(root: pathlib.Path) -> bool:
    return bool(iter_native_files(root))


def parse_native(path: pathlib.Path,
                 root: pathlib.Path) -> NativeSource | None:
    try:
        text = path.read_text(encoding="utf-8", errors="replace")
    except OSError:
        return None
    rel = path.resolve().relative_to(root.resolve()).as_posix()
    return NativeSource(path, rel, text)


_SOURCE_CACHE: dict[tuple[str, float, int], NativeSource] = {}


def load_native_sources(root: pathlib.Path) -> list[NativeSource]:
    """Parsed native sources under ``root/native``, memoized on
    ``(path, mtime, size)`` so the four TPL04x rules share one parse."""
    out: list[NativeSource] = []
    for path in iter_native_files(root):
        try:
            st = path.stat()
        except OSError:
            continue
        key = (str(path.resolve()), st.st_mtime, st.st_size)
        src = _SOURCE_CACHE.get(key)
        if src is None:
            src = parse_native(path, root)
            if src is None:
                continue
            if len(_SOURCE_CACHE) > 64:  # bound: fixture churn in tests
                _SOURCE_CACHE.clear()
            _SOURCE_CACHE[key] = src
        out.append(src)
    return out


def project_root(project) -> pathlib.Path | None:
    """Repo root for a :class:`~tpudfs.analysis.callgraph.Project`: the
    explicit ``root`` the driver attached, else derived from any
    module's ``path``/``rel_path`` pair."""
    root = getattr(project, "root", None)
    if root is not None:
        return pathlib.Path(root)
    for mod in project.modules.values():
        rel = pathlib.PurePosixPath(mod.rel_path)
        p = mod.path.resolve()
        if len(p.parts) > len(rel.parts):
            return pathlib.Path(*p.parts[:len(p.parts) - len(rel.parts)])
    return None


# ----------------------------------------------- Python-side declarations


@dataclass
class PyDecl:
    name: str
    argtypes: list[str] | None = None
    argtypes_line: int = 0
    restype: str | None = None  # canonical; "void" for None
    restype_line: int = 0


@dataclass
class PyCtypesDecls:
    decls: dict[str, PyDecl] = field(default_factory=dict)
    abi_checks: list[tuple[int, int]] = field(default_factory=list)
    # (expected_version, line)


_CTYPES_CANON = {
    "c_char_p": "cstr",
    "c_wchar_p": "other:wchar",
    "c_void_p": "anyptr",
    "c_bool": "bool",
    "c_uint8": "u8", "c_ubyte": "u8",
    "c_int8": "i8", "c_byte": "i8",
    "c_uint16": "u16", "c_ushort": "u16",
    "c_int16": "i16", "c_short": "i16",
    "c_uint32": "u32", "c_uint": "u32",
    "c_int32": "i32", "c_int": "i32",
    "c_uint64": "u64", "c_ulonglong": "u64", "c_size_t": "u64",
    "c_ulong": "u64",
    "c_int64": "i64", "c_longlong": "i64", "c_ssize_t": "i64",
    "c_long": "i64",
    "c_float": "f32", "c_double": "f64",
}


def _ctypes_name(node: ast.AST) -> str | None:
    """``ctypes.c_uint32`` / bare ``c_uint32`` -> the attribute name."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _canon_ctypes_node(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and node.value is None:
        return "void"
    if isinstance(node, ast.Call):
        fn = _ctypes_name(node.func)
        if fn == "POINTER" and node.args:
            inner = _ctypes_name(node.args[0])
            if inner == "c_char_p":
                return "cstr2"
            return "ptr2"
        return None
    name = _ctypes_name(node)
    if name is None:
        return None
    return _CTYPES_CANON.get(name)


def _lib_symbol_attr(node: ast.AST) -> tuple[str, str] | None:
    """``lib.tpudfs_x.argtypes`` -> ("tpudfs_x", "argtypes")."""
    if not isinstance(node, ast.Attribute):
        return None
    if node.attr not in ("argtypes", "restype"):
        return None
    base = node.value
    if not isinstance(base, ast.Attribute):
        return None
    if not isinstance(base.value, ast.Name) or base.value.id != "lib":
        return None
    return base.attr, node.attr


def parse_ctypes_decls(tree: ast.AST) -> PyCtypesDecls:
    """Every ``lib.NAME.restype``/``.argtypes`` assignment plus the ABI
    version guard (``lib.tpudfs_dataplane_abi() != N``) in native.py."""
    out = PyCtypesDecls()

    def decl(name: str) -> PyDecl:
        return out.decls.setdefault(name, PyDecl(name=name))

    # Source order matters: `lib.a.argtypes = list(lib.b.argtypes)` must
    # see b's declaration first, and ast.walk is breadth-first.
    nodes = sorted(
        (n for n in ast.walk(tree) if isinstance(n, (ast.Assign,
                                                     ast.Compare))),
        key=lambda n: (n.lineno, n.col_offset))
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            sym = _lib_symbol_attr(node.targets[0])
            if sym is None:
                continue
            name, attr = sym
            d = decl(name)
            if attr == "restype":
                d.restype = _canon_ctypes_node(node.value) or "other:?"
                d.restype_line = node.lineno
                continue
            d.argtypes_line = node.lineno
            val = node.value
            if isinstance(val, ast.Call) and \
                    isinstance(val.func, ast.Name) and \
                    val.func.id == "list" and len(val.args) == 1:
                alias = _lib_symbol_attr(val.args[0])
                if alias is not None and alias[1] == "argtypes":
                    src = out.decls.get(alias[0])
                    d.argtypes = list(src.argtypes) \
                        if src is not None and src.argtypes is not None \
                        else None
                    continue
            if isinstance(val, (ast.List, ast.Tuple)):
                d.argtypes = [_canon_ctypes_node(e) or "other:?"
                              for e in val.elts]
            continue
        if not isinstance(node, ast.Compare):
            continue
        if len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.NotEq, ast.Eq)):
            left, right = node.left, node.comparators[0]
            call = left if isinstance(left, ast.Call) else \
                right if isinstance(right, ast.Call) else None
            const = right if isinstance(right, ast.Constant) else \
                left if isinstance(left, ast.Constant) else None
            if call is None or const is None:
                continue
            if not isinstance(const.value, int):
                continue
            target = call.func
            if isinstance(target, ast.Attribute) \
                    and target.attr == "tpudfs_dataplane_abi":
                out.abi_checks.append((const.value, node.lineno))
    return out


def py_int_constants(tree: ast.AST) -> dict[str, tuple[int, int]]:
    """Module-level integer constants ``{name: (value, line)}``, with
    simple arithmetic (``1 << 30``, ``2 * FRAME_SIZE``) folded against
    earlier constants in the same module."""
    env: dict[str, tuple[int, int]] = {}

    def ev(node: ast.AST) -> int | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.Name):
            hit = env.get(node.id)
            return hit[0] if hit else None
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = ev(node.operand)
            return -v if v is not None else None
        if isinstance(node, ast.BinOp):
            a, b = ev(node.left), ev(node.right)
            if a is None or b is None:
                return None
            op = node.op
            if isinstance(op, ast.LShift):
                return a << b
            if isinstance(op, ast.RShift):
                return a >> b
            if isinstance(op, ast.Mult):
                return a * b
            if isinstance(op, ast.Add):
                return a + b
            if isinstance(op, ast.Sub):
                return a - b
            if isinstance(op, ast.FloorDiv) and b:
                return a // b
            if isinstance(op, ast.BitOr):
                return a | b
            if isinstance(op, ast.BitAnd):
                return a & b
            if isinstance(op, ast.BitXor):
                return a ^ b
        return None

    body = getattr(tree, "body", [])
    for stmt in body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            v = ev(stmt.value)
            if v is not None:
                env[stmt.targets[0].id] = (v, stmt.lineno)
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            v = ev(stmt.value)
            if v is not None:
                env[stmt.target.id] = (v, stmt.lineno)
    return env


def py_string_literals(tree: ast.AST) -> dict[str, int]:
    """``{literal: first line}`` excluding module/class/function
    docstrings."""
    doc_nodes: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                doc_nodes.add(id(body[0].value))
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and id(node) not in doc_nodes:
            line = getattr(node, "lineno", 0)
            if node.value not in out or line < out[node.value]:
                out[node.value] = line
    return out
