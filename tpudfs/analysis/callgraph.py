"""Project-wide symbol table and call graph for tpulint's interprocedural
rules (TPL010-TPL014).

Per-function AST rules (TPL001-TPL007) see one module at a time; the bugs
that dominate distributed-systems incident reports cross those boundaries: a
``time.sleep`` three calls deep under an async handler, a lock-order
inversion split across ``raft/node.py`` and ``common/rpc.py``, a client stub
calling an RPC method the server never registered. This module gives rules a
whole-program view:

- :class:`Project` parses every module once (reusing :class:`ModuleInfo`)
  and builds a symbol table of classes, methods, module functions and nested
  functions, keyed by dotted qualified name.
- Self-type inference: ``self.attr`` receivers resolve through attribute
  types inferred from ``self.attr = Ctor(...)`` assignments,
  ``self.attr: Ctor`` / class-body annotations, and ``self.attr = param``
  where the parameter is annotated (``def __init__(self, store:
  BlockStore)``), so ``self.store.read()`` edges into ``BlockStore.read``.
  Receiver chains resolve to arbitrary depth
  (``self.cs.store.stats`` walks two attribute hops before the method).
- Call edges carry a ``kind``: ``"call"`` (same execution context),
  ``"thread"`` (``asyncio.to_thread`` / ``loop.run_in_executor`` /
  ``threading.Thread(target=...)`` — a worker thread, NOT the event loop)
  and ``"task"`` (``asyncio.create_task``/``ensure_future`` — a new
  coroutine on the loop). Reachability analyses propagate along ``"call"``
  edges only; blocking work behind a ``"thread"`` edge is exactly the fix
  the blocking rules recommend.

Resolution is deliberately conservative: an edge exists only when the callee
resolves to a function in the project. Dynamic dispatch, higher-order calls
and external libraries produce no edge — interprocedural rules therefore err
toward silence, never toward false positives.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from tpudfs.analysis.linter import ModuleInfo, dotted_name

__all__ = [
    "CallEdge",
    "ClassInfo",
    "FunctionInfo",
    "Project",
    "module_qualname",
]

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: asyncio bridges whose first argument runs on a worker thread.
_THREAD_BRIDGES = {"asyncio.to_thread"}
#: ``loop.run_in_executor(executor, fn, ...)`` — fn runs off-loop.
_EXECUTOR_ATTRS = {"run_in_executor"}
#: spawn points whose coroutine argument becomes a new loop task.
_TASK_SPAWNS = {"create_task", "ensure_future"}


def module_qualname(rel_path: str) -> str:
    """``tpudfs/client/client.py`` -> ``tpudfs.client.client``;
    ``tpudfs/raft/__init__.py`` -> ``tpudfs.raft``."""
    parts = rel_path.split("/")
    parts[-1] = parts[-1].removesuffix(".py")
    if parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


@dataclass
class FunctionInfo:
    """One function or method definition anywhere in the project."""

    qualname: str  # "tpudfs.client.client.Client._read_ec_block"
    module: ModuleInfo
    node: ast.FunctionDef | ast.AsyncFunctionDef
    cls: "ClassInfo | None" = None
    #: outgoing edges, populated by Project._build_edges
    calls: list["CallEdge"] = field(default_factory=list)

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)

    @property
    def name(self) -> str:
        return self.node.name

    def short(self) -> str:
        """Human name for findings: drop the package prefix."""
        return self.qualname.rsplit(".", 2)[-2] + "." + self.name \
            if self.cls else self.name

    def __hash__(self) -> int:
        return id(self.node)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, FunctionInfo) and other.node is self.node


@dataclass
class ClassInfo:
    qualname: str  # "tpudfs.chunkserver.blockstore.BlockStore"
    module: ModuleInfo
    node: ast.ClassDef
    #: base-class dotted names as written (resolved lazily via imports)
    bases: list[str] = field(default_factory=list)
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    #: ``self.attr`` -> class qualname, inferred from constructor calls and
    #: annotations inside this class's body
    attr_types: dict[str, str] = field(default_factory=dict)


@dataclass
class CallEdge:
    caller: FunctionInfo
    callee: FunctionInfo
    site: ast.AST  # the Call node at the caller
    kind: str  # "call" | "thread" | "task"


class Project:
    """Symbol table + call graph over a set of parsed modules."""

    def __init__(self, modules: dict[str, ModuleInfo]):
        #: rel_path -> ModuleInfo
        self.modules = modules
        #: dotted module name -> ModuleInfo
        self.by_modname: dict[str, ModuleInfo] = {}
        #: fully qualified name -> info
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        #: ast function node -> FunctionInfo (edge attribution)
        self._func_by_node: dict[ast.AST, FunctionInfo] = {}
        #: per module: local name -> imported dotted target
        self._imports: dict[str, dict[str, str]] = {}
        #: per module: module-level constant name -> string value
        self._str_consts: dict[str, dict[str, str]] = {}
        #: per module: module-level function name -> FunctionInfo
        self._mod_funcs: dict[str, dict[str, FunctionInfo]] = {}
        #: per function node: directly nested function name -> FunctionInfo
        self._nested: dict[ast.AST, dict[str, FunctionInfo]] = {}

        for mod in modules.values():
            self._index_module(mod)
        for mod in modules.values():
            self._infer_attr_types(mod)
        for mod in modules.values():
            self._build_edges(mod)

    # ------------------------------------------------------------- indexing

    def _index_module(self, mod: ModuleInfo) -> None:
        modname = module_qualname(mod.rel_path)
        self.by_modname[modname] = mod
        self._imports[modname] = imports = {}
        self._str_consts[modname] = consts = {}
        self._mod_funcs[modname] = mod_funcs = {}

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else alias.name.split(".")[0]
                    if alias.asname is None:
                        # `import a.b.c` binds `a`, but dotted uses of the
                        # full path must also resolve.
                        imports.setdefault(alias.name, alias.name)
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(modname, node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}" if base else alias.name
            elif isinstance(node, ast.Assign) and mod.parent(node) is mod.tree:
                if isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            consts[t.id] = node.value.value

        # Classes, methods, functions (including nested).
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                scope = mod.qualname(node)
                qual = f"{modname}.{scope}"
                info = ClassInfo(
                    qualname=qual, module=mod, node=node,
                    bases=[n for n in map(dotted_name, node.bases) if n],
                )
                self.classes[qual] = info
            elif isinstance(node, _FUNC_NODES):
                scope = mod.qualname(node)
                qual = f"{modname}.{scope}"
                finfo = FunctionInfo(qualname=qual, module=mod, node=node)
                self.functions[qual] = finfo
                self._func_by_node[node] = finfo
                parent = mod.parent(node)
                if isinstance(parent, ast.ClassDef):
                    cls_qual = f"{modname}.{mod.qualname(parent)}"
                    cls = self.classes.get(cls_qual)
                    if cls is not None:
                        finfo.cls = cls
                        cls.methods[node.name] = finfo
                elif parent is mod.tree:
                    self._mod_funcs[modname][node.name] = finfo
                elif isinstance(parent, _FUNC_NODES):
                    self._nested.setdefault(parent, {})[node.name] = finfo

    @staticmethod
    def _import_base(modname: str, node: ast.ImportFrom) -> str | None:
        if node.level == 0:
            return node.module or ""
        # Relative import: strip `level` trailing components of the package.
        parts = modname.split(".")
        if node.level > len(parts):
            return None
        base_parts = parts[: len(parts) - node.level]
        if node.module:
            base_parts.append(node.module)
        return ".".join(base_parts)

    def _infer_attr_types(self, mod: ModuleInfo) -> None:
        modname = module_qualname(mod.rel_path)
        for cls in self.classes.values():
            if cls.module is not mod:
                continue
            for node in ast.walk(cls.node):
                target = value = anno = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value, anno = node.target, node.value, \
                        node.annotation
                else:
                    continue
                name = dotted_name(target) if target is not None else None
                if not name or not name.startswith("self.") \
                        or name.count(".") != 1:
                    # class-body annotation `attr: Foo` (dataclass style)
                    if isinstance(target, ast.Name) \
                            and mod.parent(node) is cls.node and anno:
                        name = f"self.{target.id}"
                    else:
                        continue
                attr = name.split(".", 1)[1]
                resolved = None
                if isinstance(value, ast.Call):
                    resolved = self._resolve_class(modname, dotted_name(value.func))
                if resolved is None and anno is not None:
                    anno_name = dotted_name(anno)
                    if anno_name is None and isinstance(anno, ast.Constant) \
                            and isinstance(anno.value, str):
                        anno_name = anno.value.strip("'\" ").split("|")[0].strip()
                    resolved = self._resolve_class(modname, anno_name)
                if resolved is None and isinstance(value, ast.Name):
                    resolved = self._param_class(mod, modname, node, value.id)
                if resolved is not None:
                    cls.attr_types.setdefault(attr, resolved.qualname)

    def _param_class(self, mod: ModuleInfo, modname: str, node: ast.AST,
                     var: str) -> ClassInfo | None:
        """Type of ``var`` when it is an annotated parameter of the method
        enclosing ``node`` — the ``self.store = store`` injection idiom."""
        fn = mod.enclosing_function(node)
        if fn is None:
            return None
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.arg != var or a.annotation is None:
                continue
            anno_name = dotted_name(a.annotation)
            if anno_name is None and isinstance(a.annotation, ast.Constant) \
                    and isinstance(a.annotation.value, str):
                anno_name = a.annotation.value.strip("'\" ") \
                    .split("|")[0].strip()
            return self._resolve_class(modname, anno_name)
        return None

    # ----------------------------------------------------------- resolution

    def _resolve_class(self, modname: str, name: str | None) -> ClassInfo | None:
        if not name:
            return None
        qual = self._qualify(modname, name)
        return self.classes.get(qual) if qual else None

    def _qualify(self, modname: str, name: str) -> str | None:
        """Fully qualify a dotted name as written in ``modname``."""
        head, _, rest = name.partition(".")
        imports = self._imports.get(modname, {})
        if name in self.classes or name in self.functions:
            return name
        if head in imports:
            target = imports[head]
            return f"{target}.{rest}" if rest else target
        local = f"{modname}.{name}"
        if local in self.classes or local in self.functions:
            return local
        return None

    def resolve_str_const(self, mod: ModuleInfo, node: ast.AST) -> str | None:
        """String value of ``node``: a literal, a module-level constant, or
        an imported module-level constant."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        name = dotted_name(node)
        if not name:
            return None
        modname = module_qualname(mod.rel_path)
        if name in self._str_consts.get(modname, {}):
            return self._str_consts[modname][name]
        qual = self._qualify(modname, name)
        if qual and "." in qual:
            owner, const = qual.rsplit(".", 1)
            return self._str_consts.get(owner, {}).get(const)
        return None

    def class_of(self, fn: FunctionInfo) -> ClassInfo | None:
        return fn.cls

    def method_on(self, cls: ClassInfo, name: str,
                  _depth: int = 0) -> FunctionInfo | None:
        """Method lookup through the (project-resolvable) MRO."""
        if name in cls.methods:
            return cls.methods[name]
        if _depth > 6:
            return None
        modname = module_qualname(cls.module.rel_path)
        for base in cls.bases:
            base_cls = self._resolve_class(modname, base)
            if base_cls is not None:
                hit = self.method_on(base_cls, name, _depth + 1)
                if hit is not None:
                    return hit
        return None

    def attr_class(self, cls: ClassInfo, attr: str) -> ClassInfo | None:
        qual = cls.attr_types.get(attr)
        if qual is not None:
            return self.classes.get(qual)
        modname = module_qualname(cls.module.rel_path)
        for base in cls.bases:
            base_cls = self._resolve_class(modname, base)
            if base_cls is not None:
                hit = self.attr_class(base_cls, attr)
                if hit is not None:
                    return hit
        return None

    def function_at(self, node: ast.AST) -> FunctionInfo | None:
        return self._func_by_node.get(node)

    def enclosing_function_info(self, mod: ModuleInfo,
                                node: ast.AST) -> FunctionInfo | None:
        """FunctionInfo of the innermost enclosing def/async def (lambdas
        are transparent: a call inside a lambda is attributed to the lambda's
        enclosing function)."""
        cur = mod.parent(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                return self._func_by_node.get(cur)
            cur = mod.parent(cur)
        return None

    def resolve_call(self, caller: FunctionInfo,
                     func: ast.AST) -> FunctionInfo | None:
        """Resolve the callee of ``func`` (a Call's .func, or a callable
        reference passed to to_thread/run_in_executor) to a FunctionInfo."""
        mod = caller.module
        modname = module_qualname(mod.rel_path)
        name = dotted_name(func)
        if name is None:
            return None
        parts = name.split(".")

        # self.m(...) / cls.m(...) / self.attr[...attr].m(...) — the
        # receiver chain walks inferred attribute types to any depth.
        if parts[0] in ("self", "cls") and caller.cls is not None:
            return self._walk_chain(caller.cls, parts[1:])

        # Bare name: nested defs (walking out), then module functions,
        # then imports.
        if len(parts) == 1:
            cur: ast.AST | None = caller.node
            while cur is not None:
                hit = self._nested.get(cur, {}).get(name)
                if hit is not None:
                    return hit
                cur = mod.parent(cur)
                if not isinstance(cur, _FUNC_NODES):
                    break
            hit = self._mod_funcs.get(modname, {}).get(name)
            if hit is not None:
                return hit
            qual = self._imports.get(modname, {}).get(name)
            return self.functions.get(qual) if qual else None

        # Dotted: local-variable constructor types, imported modules/classes.
        local_cls = self._local_var_class(caller, parts[0])
        if local_cls is not None:
            hit = self._walk_chain(local_cls, parts[1:])
            if hit is not None:
                return hit
        qual = self._qualify(modname, name)
        if qual is None:
            return None
        if qual in self.functions:
            return self.functions[qual]
        # Imported-class method reference: `BlockStore.read`.
        owner, _, meth = qual.rpartition(".")
        cls = self.classes.get(owner)
        if cls is not None:
            return self.method_on(cls, meth)
        return None

    def _walk_chain(self, cls: ClassInfo,
                    parts: list[str]) -> FunctionInfo | None:
        """Resolve ``attr.attr...method`` against ``cls`` through inferred
        attribute types; the last part is the method."""
        if not parts:
            return None
        cur: ClassInfo | None = cls
        for attr in parts[:-1]:
            cur = self.attr_class(cur, attr)
            if cur is None:
                return None
        return self.method_on(cur, parts[-1])

    def attr_chain_class(self, cls: ClassInfo,
                         parts: list[str]) -> ClassInfo | None:
        """Class reached by following every attribute in ``parts`` from
        ``cls`` (for attribute *access* resolution, not calls)."""
        cur: ClassInfo | None = cls
        for attr in parts:
            cur = self.attr_class(cur, attr)
            if cur is None:
                return None
        return cur

    def _local_var_class(self, caller: FunctionInfo,
                         var: str) -> ClassInfo | None:
        """Type of a local assigned from a constructor inside ``caller``
        (``store = BlockStore(...)``)."""
        modname = module_qualname(caller.module.rel_path)
        for node in ast.walk(caller.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == var \
                    and isinstance(node.value, ast.Call):
                return self._resolve_class(modname, dotted_name(node.value.func))
        return None

    # ---------------------------------------------------------- call edges

    def _build_edges(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            caller = self.enclosing_function_info(mod, node)
            if caller is None:
                continue
            name = dotted_name(node.func) or ""
            kind = "call"
            target: ast.AST | None = node.func

            if name in _THREAD_BRIDGES and node.args:
                kind, target = "thread", node.args[0]
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _EXECUTOR_ATTRS \
                    and len(node.args) >= 2:
                kind, target = "thread", node.args[1]
            elif name == "threading.Thread" or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Thread"):
                kw = next((k.value for k in node.keywords
                           if k.arg == "target"), None)
                if kw is None:
                    continue
                kind, target = "thread", kw
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _TASK_SPAWNS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    kind, target = "task", arg.func
                else:
                    continue

            if target is None:
                continue
            callee = self.resolve_call(caller, target)
            if callee is None:
                continue
            caller.calls.append(
                CallEdge(caller=caller, callee=callee, site=node, kind=kind)
            )

    # ---------------------------------------------------- execution context

    def execution_contexts(self) -> dict[FunctionInfo, frozenset[str]]:
        """Classify where each function's body runs, from call-graph roots:

        - ``"loop"`` — on the event loop: every coroutine, plus sync
          functions (transitively) called from one;
        - ``"worker"`` — on an executor thread: targets of ``to_thread`` /
          ``run_in_executor`` / ``threading.Thread``, plus sync functions
          they call;
        - ``"task"`` — additionally entered via ``create_task`` /
          ``ensure_future``: still the loop thread, but running concurrently
          with its spawner at every await.

        A function reachable several ways carries several labels; one with
        no label is never called from analyzed code (tests, dead code) and
        contributes nothing to cross-context reasoning. The thread
        dimension is what races care about: ``"task"`` and ``"loop"`` share
        one OS thread, ``"worker"`` does not.
        """
        cached = getattr(self, "_contexts", None)
        if cached is not None:
            return cached

        ctx: dict[FunctionInfo, set[str]] = {}

        def add(fn: FunctionInfo, labels: set[str]) -> bool:
            have = ctx.setdefault(fn, set())
            new = labels - have
            if new:
                have |= new
                return True
            return False

        pending: list[FunctionInfo] = []
        for fn in self.functions.values():
            labels = set()
            if fn.is_async:
                labels.add("loop")
            for edge in fn.calls:
                if edge.kind == "thread":
                    if add(edge.callee, {"worker"}):
                        pending.append(edge.callee)
                elif edge.kind == "task":
                    if add(edge.callee, {"task", "loop"}):
                        pending.append(edge.callee)
            if labels and add(fn, labels):
                pending.append(fn)

        # Propagate along plain call edges: a sync callee runs wherever its
        # caller runs; an async callee only ever runs on the loop (a worker
        # cannot await), so it gains nothing from its callers.
        while pending:
            fn = pending.pop()
            labels = ctx.get(fn, set())
            if not labels:
                continue
            for edge in fn.calls:
                if edge.kind != "call" or edge.callee.is_async:
                    continue
                if add(edge.callee, set(labels)):
                    pending.append(edge.callee)

        result = {fn: frozenset(labels)
                  for fn, labels in ctx.items() if labels}
        self._contexts = result
        return result

    @staticmethod
    def thread_dim(labels: frozenset[str]) -> frozenset[str]:
        """Collapse context labels to OS-thread identity: ``task`` runs on
        the loop thread."""
        dims = set()
        if "worker" in labels:
            dims.add("worker")
        if "loop" in labels or "task" in labels:
            dims.add("loop")
        return frozenset(dims)

    # -------------------------------------------------------- reachability

    def sync_call_edges(self, fn: FunctionInfo) -> Iterator[CallEdge]:
        """Edges that stay in the caller's execution context (kind "call")
        and land on a SYNC function — the propagation edges for
        blocking/lock reachability from async code. Calling an async
        function without awaiting creates a coroutine, it runs nothing;
        awaited async callees are analyzed in their own right."""
        for edge in fn.calls:
            if edge.kind == "call" and not edge.callee.is_async:
                yield edge
