"""tpulint CLI.

    python -m tpudfs.analysis                 # lint tpudfs/ against baseline
    python -m tpudfs.analysis path/to/file.py # lint specific paths
    python -m tpudfs.analysis --format sarif  # SARIF 2.1.0 to stdout
    python -m tpudfs.analysis --changed       # only files differing from
                                              # `git merge-base HEAD main`
    python -m tpudfs.analysis --write-baseline
    python -m tpudfs.analysis --list-rules
    python -m tpudfs.analysis --explain TPL020  # why + example + fix
    python -m tpudfs.analysis --stats         # per-rule wall-time report
    python -m tpudfs.analysis --profile TPL030  # one rule, per-unit timing
    python -m tpudfs.analysis --no-baseline   # show grandfathered too
    python -m tpudfs.analysis --write-rule-table  # sync docs table
    python -m tpudfs.analysis --write-ledger  # regenerate copy_ledger.json
    python -m tpudfs.analysis --check-ledger  # byte-cost budget gate

Full-tree runs reuse a content-hash cache (``.tpulint_cache.json`` at the
repo root, git-ignored) so the common nothing-changed case costs file
hashing only; ``--no-cache`` forces a cold analysis. ``--changed`` is the
fast pre-commit mode — the interprocedural rules (TPL010-TPL014) then see
only the changed files' call graph, so most cross-file findings involving
unchanged files surface in the next full run, not here. The exception is
the hot data plane: ``--changed`` also pulls in unchanged files whose
*hot-path* functions call into the changed files, so the performance
rules (TPL030-TPL034) re-judge callers whose effective loop depth or
buffer provenance a changed callee may have shifted.

Exit codes: 0 clean (or fully baselined), 1 non-baselined findings,
2 bad invocation.
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys

from tpudfs.analysis import linter

#: Repo root = parent of the ``tpudfs`` package directory.
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_TARGET = REPO_ROOT / "tpudfs"
BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "baseline.json"


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpulint",
        description="distributed-systems-aware static analysis for tpudfs",
    )
    p.add_argument("paths", nargs="*", type=pathlib.Path,
                   help="files/dirs to lint (default: the tpudfs package)")
    p.add_argument("--root", type=pathlib.Path, default=REPO_ROOT,
                   help="repo root used for relative paths and baselines")
    p.add_argument("--baseline", type=pathlib.Path, default=BASELINE_PATH,
                   help="baseline file (default: tpudfs/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline; report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from current findings")
    p.add_argument("--list-rules", action="store_true",
                   help="print every registered rule and exit")
    p.add_argument("--explain", metavar="TPLxxx",
                   help="print a rule's full documentation (what it "
                        "catches, a flagged example, how to fix) and exit")
    p.add_argument("--stats", action="store_true",
                   help="after linting, print wall time spent per rule")
    p.add_argument("--profile", metavar="TPLxxx",
                   help="run only this rule with per-unit timing and "
                        "print its top-10 most expensive analysis units "
                        "(functions for the hot-path rules, files for "
                        "per-module rules)")
    p.add_argument("--write-rule-table", action="store_true",
                   help="regenerate the rule table in "
                        "docs/static-analysis.md from rule metadata")
    p.add_argument("--write-native-abi", action="store_true",
                   help="regenerate the native ABI manifest "
                        "(tpudfs/analysis/native_abi.json) from the "
                        "current extern \"C\" dataplane exports; refuses "
                        "if signatures changed without an ABI version "
                        "bump")
    p.add_argument("--write-ledger", action="store_true",
                   help="regenerate the byte-cost ledger "
                        "(tpudfs/analysis/copy_ledger.json) from the "
                        "current tree; refuses if any route's copy count "
                        "grew over the committed budget")
    p.add_argument("--ledger-allow-growth", action="store_true",
                   help="with --write-ledger: accept a route's copy "
                        "count growing over the committed budget (use "
                        "when a copy is added deliberately and reviewed)")
    p.add_argument("--check-ledger", action="store_true",
                   help="verify the committed byte-cost ledger: exit 1 "
                        "when any route's copies exceed its budget or "
                        "the file is stale vs the tree")
    p.add_argument("--rule", action="append", dest="rules", metavar="TPLxxx",
                   help="run only these rule ids (repeatable)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text",
                   help="output format (default: human-readable text)")
    p.add_argument("--output", type=pathlib.Path, metavar="FILE",
                   help="write the report to FILE instead of stdout")
    p.add_argument("--changed", action="store_true",
                   help="lint files differing from `git merge-base HEAD "
                        "main`, widened with unchanged hot-path callers "
                        "of the changed functions (fast pre-commit mode)")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the content-hash analysis cache")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def _git_lines(root: pathlib.Path, *args: str) -> list[str]:
    out = subprocess.run(
        ["git", *args], cwd=root, capture_output=True, text=True, check=True,
    ).stdout
    return [line for line in out.splitlines() if line.strip()]


#: Python modules a changed native/*.cc or *.h file maps to. The TPL04x
#: rules are project rules that read native sources straight from the
#: repo root, so a native edit only needs SOME analyzed module for the
#: project pass to run — but it needs the RIGHT ones for the diff to be
#: meaningful: the ctypes bindings (TPL040) and every wire module whose
#: constants/literals TPL041 pairs against the C++.
NATIVE_COUNTERPART_MODULES: tuple[str, ...] = (
    "tpudfs/common/native.py",
    "tpudfs/common/writestream.py",
    "tpudfs/common/blocknet.py",
    "tpudfs/common/checksum.py",
    "tpudfs/common/resilience.py",
    "tpudfs/chunkserver/service.py",
)


def _ledger_file_changed(root: pathlib.Path) -> bool:
    """Did the committed copy_ledger.json itself change vs merge-base?
    A budget edit affects every route, so --changed must re-gate them
    all even though no Python file moved."""
    from tpudfs.analysis.byteflow import LEDGER_REL_PATH

    try:
        base = _git_lines(root, "merge-base", "HEAD", "main")[0]
        names = _git_lines(root, "diff", "--name-only", base)
        names += _git_lines(root, "ls-files", "--others",
                            "--exclude-standard")
    except (subprocess.CalledProcessError, OSError, IndexError):
        return False
    return LEDGER_REL_PATH in names


def changed_paths(root: pathlib.Path) -> list[pathlib.Path] | None:
    """Python files differing from ``git merge-base HEAD main``, plus
    untracked ones. None when git/merge-base is unavailable (detached
    checkouts, exported trees) — the caller falls back to a full lint.

    A changed ``.cc``/``.h`` under ``native/`` does not enter the path
    list itself (the tree walker lints Python sources); instead it pulls
    in :data:`NATIVE_COUNTERPART_MODULES`, which makes the TPL04x
    cross-language rules re-check the native tree against its Python
    counterparts — previously a dataplane.cc edit ran zero rules. The
    same widening applies when any ONE counterpart module changes:
    TPL041 pairs native wire constants against the whole counterpart
    set, so a subset holding service.py without blocknet.py would
    "miss" every header key blocknet defines and report phantom
    drift."""
    try:
        base = _git_lines(root, "merge-base", "HEAD", "main")[0]
        names = _git_lines(root, "diff", "--name-only", base)
        names += _git_lines(root, "ls-files", "--others",
                            "--exclude-standard")
    except (subprocess.CalledProcessError, OSError, IndexError):
        return None
    out = []
    widen_native = False
    for name in sorted(set(names)):
        p = root / name
        if name.endswith(".py") and p.exists():
            out.append(p)
            if name in NATIVE_COUNTERPART_MODULES:
                widen_native = True
        elif name.endswith((".cc", ".h")) and name.startswith("native/") \
                and p.exists():
            widen_native = True
    if widen_native:
        for rel in NATIVE_COUNTERPART_MODULES:
            p = root / rel
            if p.exists():
                out.append(p)
    return sorted(set(out))


def hot_caller_files(
    root: pathlib.Path, changed: list[pathlib.Path], project=None
) -> list[pathlib.Path]:
    """Unchanged files that contain *hot-path* callers of functions
    defined in ``changed``.

    The TPL03x performance rules judge a statement by its effective loop
    depth and buffer provenance, both of which flow through call edges: a
    changed callee can move an unchanged caller's finding set without the
    caller's text changing (e.g. a callee that starts returning a list of
    buffers, or a root whose loop now encloses the call site). A plain
    ``--changed`` subset would miss those, so the CLI widens the subset
    with the files this returns. Cold callers are deliberately excluded —
    off the data plane the TPL03x rules never fire, and widening to every
    caller would turn most edits into full-tree lints.
    """
    from tpudfs.analysis import byteflow
    from tpudfs.analysis.hotpath import hot_paths

    if project is None:
        project = byteflow.load_project(root)
    if not project.modules:
        return []
    hp = hot_paths(project)
    changed_set = {p.resolve() for p in changed}
    extra: set[pathlib.Path] = set()
    for caller in project.functions.values():
        cpath = caller.module.path.resolve()
        if cpath in changed_set or not hp.is_hot(caller):
            continue
        if any(edge.callee.module.path.resolve() in changed_set
               for edge in caller.calls):
            extra.add(cpath)
    return sorted(extra)


def write_native_abi(root: pathlib.Path) -> int:
    """Regenerate ``tpudfs/analysis/native_abi.json`` from the current
    ``extern "C"`` dataplane exports. Refuses (exit 2) when a pinned
    signature changed while ``tpudfs_dataplane_abi()`` still returns the
    manifest's version — the whole point of the manifest is that such an
    edit must bump the version, not rewrite history."""
    import json

    from tpudfs.analysis.nativesrc import load_native_sources
    from tpudfs.analysis.rules.native_abi import (
        ABI_MANIFEST_REL,
        current_abi_surface,
        load_abi_manifest,
    )

    sources = load_native_sources(root)
    version, sigs = current_abi_surface(sources)
    if version is None or not sigs:
        print("tpulint: --write-native-abi: no tpudfs_dataplane_* "
              f"exports (or no ABI version) found under {root / 'native'}",
              file=sys.stderr)
        return 2
    old = load_abi_manifest(root)
    if old is not None and old.get("abi_version") == version \
            and old.get("exports") != sigs:
        drifted = sorted(
            name for name in set(old["exports"]) | set(sigs)
            if old["exports"].get(name) != sigs.get(name))
        print("tpulint: --write-native-abi: refusing to regenerate — "
              f"dataplane export(s) changed ({', '.join(drifted)}) but "
              f"tpudfs_dataplane_abi() still returns {version}. Bump the "
              "ABI version in native/dataplane.cc and the guard in "
              "tpudfs/common/native.py first, then regenerate.",
              file=sys.stderr)
        return 2
    path = root / ABI_MANIFEST_REL
    data = {
        "version": 1,
        "comment": (
            "Pinned signatures of the tpudfs_dataplane_* C ABI at the "
            "current TPUDFS_DATAPLANE_ABI version. TPL040 fails lint "
            "when a signature drifts from this file without a version "
            "bump. Regenerate with `python -m tpudfs.analysis "
            "--write-native-abi` — never edit by hand."
        ),
        "abi_version": version,
        "exports": dict(sorted(sigs.items())),
    }
    path.write_text(json.dumps(data, indent=2) + "\n")
    print(f"wrote ABI manifest: {len(sigs)} dataplane export(s) at "
          f"version {version} -> {path}")
    return 0


def write_ledger(root: pathlib.Path, allow_growth: bool = False) -> int:
    """Regenerate ``tpudfs/analysis/copy_ledger.json``. Refuses (exit 2)
    when a route's copy count grew over the committed budget — silent
    regeneration would turn the ratchet into a rubber stamp; growth must
    be explicit (``--ledger-allow-growth``) and reviewed."""
    from tpudfs.analysis import byteflow

    ledger = byteflow.ledger_for_project(root)
    committed = byteflow.load_committed_ledger(root)
    if committed is not None and not allow_growth:
        breaches = byteflow.check_ledger(ledger, committed)
        if breaches:
            print("tpulint: --write-ledger: refusing to regenerate — "
                  "the new ledger GROWS a route's copy budget:",
                  file=sys.stderr)
            for msg in breaches:
                print(f"  {msg}", file=sys.stderr)
            print("Remove the copy (preferred), or rerun with "
                  "--ledger-allow-growth if the new copy is deliberate.",
                  file=sys.stderr)
            return 2
    byteflow.write_ledger_file(root, ledger)
    routes = ledger["routes"]
    total = sum(r["copies"] for r in routes.values())
    print(f"wrote byte-cost ledger: {len(routes)} route(s), "
          f"{total} copy hop(s) -> {root / byteflow.LEDGER_REL_PATH}")
    return 0


def check_ledger_gate(root: pathlib.Path, project=None,
                      routes: list[str] | None = None,
                      quiet: bool = False) -> int:
    """CI gate for the committed byte-cost ledger. Full mode (``routes``
    None): any budget breach OR staleness (ledger != tree) fails. Changed
    mode (``routes`` given, from ``--changed``): only budget breaches on
    the affected routes fail — staleness on untouched routes is the full
    gate's job, not the warm pre-commit's."""
    from tpudfs.analysis import byteflow

    committed = byteflow.load_committed_ledger(root)
    if committed is None:
        print(f"tpulint: no committed ledger at "
              f"{root / byteflow.LEDGER_REL_PATH}; run --write-ledger",
              file=sys.stderr)
        return 1
    if project is None:
        project = byteflow.load_project(root)
    computed = byteflow.compute_ledger(project)
    breaches = byteflow.check_ledger(computed, committed)
    if routes is not None:
        affected = set(routes)
        breaches = [m for m in breaches
                    if m.split(":", 1)[0].removeprefix("route ").strip()
                    in affected]
    for msg in breaches:
        print(f"tpulint: ledger breach: {msg}", file=sys.stderr)
    if routes is None and not breaches \
            and byteflow.ledger_is_stale(computed, committed):
        print("tpulint: copy_ledger.json is stale (the tree's byte-cost "
              "ledger no longer matches the committed file); run "
              "`python -m tpudfs.analysis --write-ledger`",
              file=sys.stderr)
        return 1
    if breaches:
        return 1
    if not quiet:
        scope = f"{len(routes)} affected route(s)" if routes is not None \
            else f"all {len(committed.get('routes', {}))} route(s)"
        print(f"tpulint: byte-cost ledger holds for {scope}")
    return 0


def main(argv: list[str] | None = None) -> int:
    args = _parser().parse_args(argv)

    rules = linter.all_rules()
    if args.list_rules:
        for rule in rules.values():
            print(f"{rule.id}  {rule.name}")
            print(f"        {rule.summary}")
        return 0

    if args.explain:
        rule = rules.get(args.explain.upper())
        if rule is None:
            print(f"unknown rule id: {args.explain} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
        print(rule.explain(), end="")
        return 0

    if args.write_rule_table:
        from tpudfs.analysis import docgen

        doc = args.root / docgen.DOC_REL_PATH
        changed = docgen.sync_rule_table(doc)
        print(f"{doc}: rule table "
              f"{'updated' if changed else 'already in sync'}")
        return 0

    if args.write_native_abi:
        return write_native_abi(args.root)

    if args.write_ledger:
        return write_ledger(args.root, args.ledger_allow_growth)

    if args.check_ledger:
        return check_ledger_gate(args.root, quiet=args.quiet)

    selected = None
    if args.rules:
        wanted = {r.upper() for r in args.rules}
        unknown = wanted - rules.keys()
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        selected = [rules[r] for r in sorted(wanted)]

    profile_rule = None
    if args.profile:
        if args.rules:
            print("--profile and --rule are mutually exclusive "
                  "(--profile already restricts the run to one rule)",
                  file=sys.stderr)
            return 2
        profile_rule = rules.get(args.profile.upper())
        if profile_rule is None:
            print(f"unknown rule id: {args.profile} (see --list-rules)",
                  file=sys.stderr)
            return 2
        selected = [profile_rule]

    if args.paths:
        paths = args.paths
    elif args.root.resolve() == REPO_ROOT:
        paths = [DEFAULT_TARGET]
    else:
        # Custom --root: lint its tpudfs package (or the whole root) —
        # DEFAULT_TARGET lives under THIS repo and would not be relative
        # to a foreign root, which matters when --changed falls back here.
        custom = args.root / "tpudfs"
        paths = [custom if custom.is_dir() else args.root]
    changed_subset = False
    ledger_rc = 0
    if args.changed:
        if args.paths:
            print("--changed and explicit paths are mutually exclusive",
                  file=sys.stderr)
            return 2
        subset = changed_paths(args.root)
        if subset is None:
            # Detached-HEAD CI, shallow clones, exported trees: there is
            # no merge-base to diff against. Degrade to a full-tree lint
            # (strictly more coverage) instead of crashing or silently
            # linting nothing.
            print("tpulint: --changed: cannot determine a merge-base "
                  "with main (detached HEAD or not a git checkout); "
                  "falling back to a full-tree lint", file=sys.stderr)
        elif not subset:
            if not args.quiet:
                print("tpulint: no lintable files (python or native) "
                      "changed since merge-base with main")
            return 0
        else:
            from tpudfs.analysis import byteflow

            # One project build serves both the hot-path caller widening
            # and the per-route ledger drift check — the 2s warm-lint
            # budget cannot afford two full parses.
            project = byteflow.load_project(args.root)
            extra = hot_caller_files(args.root, subset, project=project)
            if extra and not args.quiet:
                print(f"tpulint: --changed: widening to {len(extra)} "
                      "unchanged file(s) whose hot-path functions call "
                      "into the changed set", file=sys.stderr)
            root_res = args.root.resolve()
            rel_changed = [
                p.resolve().relative_to(root_res).as_posix()
                for p in subset
            ]
            if _ledger_file_changed(args.root):
                rel_changed.append(byteflow.LEDGER_REL_PATH)
            affected = byteflow.routes_for_files(rel_changed)
            if affected:
                if not args.quiet:
                    print("tpulint: --changed: checking ledger budget "
                          f"for route(s): {', '.join(affected)}",
                          file=sys.stderr)
                ledger_rc = check_ledger_gate(
                    args.root, project=project, routes=affected,
                    quiet=args.quiet)
            paths = sorted({*subset, *extra})
            changed_subset = True
    for p in paths:
        if not p.exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    if args.write_baseline:
        findings = linter.analyze_tree(paths, args.root, selected)
        linter.write_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    # The cache is only sound for the full default rule set (a --rule
    # subset would poison stored findings), so selection disables it.
    cache_path = None
    if not args.no_cache and selected is None:
        cache_path = args.root / ".tpulint_cache.json"

    baseline = None if args.no_baseline else args.baseline
    linter.reset_rule_timings()
    import time as _time
    t0 = _time.perf_counter()
    if profile_rule is not None:
        linter.PROFILE_UNITS = True
    try:
        result = linter.run(paths, args.root, baseline, selected,
                            cache_path=cache_path)
    finally:
        linter.PROFILE_UNITS = False
    wall = _time.perf_counter() - t0

    if profile_rule is not None:
        per = linter.UNIT_TIMINGS.get(profile_rule.id, {})
        top = sorted(per.items(), key=lambda kv: kv[1], reverse=True)[:10]
        total = sum(per.values())
        print(f"tpulint --profile {profile_rule.id}: {total * 1000:.1f} ms "
              f"attributed across {len(per)} unit(s); top {len(top)}:",
              file=sys.stderr)
        for unit, secs in top:
            print(f"  {secs * 1000:8.2f} ms  {unit}", file=sys.stderr)

    if args.stats:
        # Stderr: --format sarif/json write a document to stdout.
        timings = sorted(linter.RULE_TIMINGS.items(),
                         key=lambda kv: kv[1], reverse=True)
        ruled = sum(t for _, t in timings)
        print(f"tpulint --stats: {wall:.3f}s wall, {ruled:.3f}s in rules "
              f"({len(timings)} rule(s) executed; cached files run no "
              "rules)", file=sys.stderr)
        for rule_id, secs in timings:
            rule = rules.get(rule_id)
            name = rule.name if rule is not None else ""
            print(f"  {rule_id}  {secs * 1000:8.1f} ms  {name}",
                  file=sys.stderr)

    if args.format != "text":
        from tpudfs.analysis import output as output_mod

        if args.format == "json":
            doc = output_mod.render_json(result)
        else:
            doc = output_mod.render_sarif(result)
        if args.output is not None:
            args.output.write_text(doc)
            if not args.quiet:
                print(f"tpulint: wrote {args.format} report "
                      f"({len(result.new)} new, {len(result.baselined)} "
                      f"baselined) to {args.output}")
        else:
            print(doc, end="")
        return 1 if result.new or ledger_rc else 0

    report = result.findings if args.no_baseline else result.new
    lines = [f.render() for f in report]
    if args.output is not None:
        args.output.write_text("\n".join(lines) + ("\n" if lines else ""))
    else:
        for line in lines:
            print(line)
    if not args.quiet:
        n_files = "" if args.paths else \
            (" (changed files only)" if changed_subset else " across tpudfs/")
        print(
            f"tpulint: {len(result.new)} new finding(s), "
            f"{len(result.baselined)} baselined{n_files}"
        )
        if result.stale_baseline:
            print(
                f"tpulint: {len(result.stale_baseline)} stale baseline "
                "entr(ies) — findings fixed but still grandfathered; run "
                "--write-baseline to shrink the baseline"
            )
    return 1 if result.new or ledger_rc else 0
