"""tpulint CLI.

    python -m tpudfs.analysis                 # lint tpudfs/ against baseline
    python -m tpudfs.analysis path/to/file.py # lint specific paths
    python -m tpudfs.analysis --write-baseline
    python -m tpudfs.analysis --list-rules
    python -m tpudfs.analysis --no-baseline   # show grandfathered too

Exit codes: 0 clean (or fully baselined), 1 non-baselined findings,
2 bad invocation.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from tpudfs.analysis import linter

#: Repo root = parent of the ``tpudfs`` package directory.
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_TARGET = REPO_ROOT / "tpudfs"
BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "baseline.json"


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpulint",
        description="distributed-systems-aware static analysis for tpudfs",
    )
    p.add_argument("paths", nargs="*", type=pathlib.Path,
                   help="files/dirs to lint (default: the tpudfs package)")
    p.add_argument("--root", type=pathlib.Path, default=REPO_ROOT,
                   help="repo root used for relative paths and baselines")
    p.add_argument("--baseline", type=pathlib.Path, default=BASELINE_PATH,
                   help="baseline file (default: tpudfs/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline; report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from current findings")
    p.add_argument("--list-rules", action="store_true",
                   help="print every registered rule and exit")
    p.add_argument("--rule", action="append", dest="rules", metavar="TPLxxx",
                   help="run only these rule ids (repeatable)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def main(argv: list[str] | None = None) -> int:
    args = _parser().parse_args(argv)

    rules = linter.all_rules()
    if args.list_rules:
        for rule in rules.values():
            print(f"{rule.id}  {rule.name}")
            print(f"        {rule.summary}")
        return 0

    selected = None
    if args.rules:
        wanted = {r.upper() for r in args.rules}
        unknown = wanted - rules.keys()
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        selected = [rules[r] for r in sorted(wanted)]

    paths = args.paths or [DEFAULT_TARGET]
    for p in paths:
        if not p.exists():
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    if args.write_baseline:
        findings = linter.analyze_tree(paths, args.root, selected)
        linter.write_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = None if args.no_baseline else args.baseline
    result = linter.run(paths, args.root, baseline, selected)

    report = result.findings if args.no_baseline else result.new
    for f in report:
        print(f.render())
    if not args.quiet:
        n_files = "" if args.paths else " across tpudfs/"
        print(
            f"tpulint: {len(result.new)} new finding(s), "
            f"{len(result.baselined)} baselined{n_files}"
        )
        if result.stale_baseline:
            print(
                f"tpulint: {len(result.stale_baseline)} stale baseline "
                "entr(ies) — findings fixed but still grandfathered; run "
                "--write-baseline to shrink the baseline"
            )
    return 1 if result.new else 0
